"""Process-backed replica fleet: N OS processes past `_BACKEND_LOCK`.

In-process replicas (serve/replica.py) share one JAX backend, so every
device execution serializes on `service._BACKEND_LOCK` — N replicas buy
fault isolation but zero throughput.  This module gives the router the
SAME duck-typed replica surface (submit/poll/peek/health/drain/
warm_from/shutdown + slot/incarnation/name/condemned/assigned/failed)
backed by a `serve/procworker.py` child process per slot:

  * each worker owns its own JAX runtime — solves on different slots
    genuinely run in parallel on a multi-core host;
  * the parent talks to each worker over the serve/net wire protocol
    on a loopback socket through a pooled, pipelined `PooledClient`
    (persistent sockets, multiple in-flight frames);
  * workers boot warm: they `prewarm()` the shared
    `MPISPPY_TPU_COMPILE_CACHE_DIR/aot` artifact set, so a rolled or
    replaced incarnation serves its first request without re-tracing;
  * process DEATH (kill -9, OOM, a segfaulting native op) is a
    first-class health signal: `health()` checks the child's exit
    status before anything else, and a dead worker reports
    `failed="worker process died ..."` — which flows into the router's
    existing breaker → replace-and-replay path unchanged.  Escalation
    on shutdown mirrors the SpokeSupervisor poll/escalate discipline:
    cooperative verb → SIGTERM → SIGKILL.

Layering: jax-free at module level (AST + fresh-interpreter guarded in
tests/test_procserve.py) — the parent NEVER needs jax to run a process
fleet; only `P.encode_batch` touches numpy.
"""

from __future__ import annotations

import itertools
import json
import os
import subprocess
import sys
import tempfile
import threading
import time
import uuid

from .. import global_toc
from .net import protocol as P
from .net.client import ClientError, PooledClient
from .replica import _GLOBAL_CHAOS, _SLOT_CHAOS
from .request import RequestHandle

#: consecutive transport-failed health probes against a LIVE process
#: before the replica is declared unreachable (a wedged-but-breathing
#: worker must not dodge the breaker forever)
_PROBE_FAILURE_LIMIT = 5
#: health snapshots younger than this are served from cache — the
#: router probes on every submit pick, and every wire frame the parent
#: sends mid-solve steals CPU (and worker GIL) from the solve itself;
#: the DEATH check (waitpid) always runs fresh, so kill -9 detection
#: does not wait on this, and submit-burst routing accuracy comes from
#: the parent-side outstanding overlay, not snapshot freshness
_HEALTH_CACHE_S = 0.25

#: at most one bulk `peek_many` poll per ProcReplica per this window —
#: the router's scan peeks EVERY open request every tick, and
#: per-handle wire peeks at that cadence convoy the worker's GIL
#: against its own dispatch thread; one bulk frame per window replaces
#: them, and since that frame carries the done results themselves,
#: keeping the window near the scan tick keeps the completion tail
#: short without adding frames that carry nothing
_PEEK_CACHE_S = 0.02


def _repo_root():
    """The directory that makes `import mpisppy_tpu` work in a child
    spawned from an arbitrary cwd."""
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def _jsonable_options(options):
    """The subset of the options dict a worker can receive: config
    JSON crosses the process boundary, so non-JSON values (injected
    objects, callables) are dropped — loudly, they would silently
    change worker behavior otherwise."""
    out = {}
    for k, v in dict(options or {}).items():
        try:
            json.dumps(v)
        except (TypeError, ValueError):
            global_toc(f"WARNING: procpool dropping non-JSON option "
                       f"{k!r} ({type(v).__name__}) from worker config")
            continue
        out[k] = v
    return out


def _detect_x64():
    """The parent's x64 state, to be reproduced in the worker (None:
    parent never loaded jax and set no env — let the worker default)."""
    jax = sys.modules.get("jax")
    if jax is not None:
        try:
            return bool(jax.config.jax_enable_x64)
        except Exception:              # pragma: no cover - odd builds
            pass
    env = os.environ.get("JAX_ENABLE_X64")
    if env is not None:
        return env.lower() in ("1", "true", "on")
    return None


def _detect_force_cpu():
    """Mirror the parent's backend pinning: a parent already running
    jax on CPU forces the worker onto CPU too (the tests' 8-virtual-
    device topology crosses via the inherited XLA_FLAGS env)."""
    jax = sys.modules.get("jax")
    if jax is None:
        return False
    try:
        return jax.default_backend() == "cpu"
    except Exception:                  # pragma: no cover - not init'd
        return False


class ProcReplica:
    """One process-backed fault domain, duck-typed to replica.Replica.

    `name` is "p<slot>i<incarnation>" — the process fleet's analogue of
    the thread fleet's "r<slot>i<inc>" labels."""

    def __init__(self, slot, incarnation, options, chaos=None,
                 workdir=None, boot_timeout=180.0):
        self.slot = int(slot)
        self.incarnation = int(incarnation)
        self.name = f"p{self.slot}i{self.incarnation}"
        o = dict(options or {})
        o["chaos"] = dict(chaos or {})
        self.options = o
        self.workdir = workdir or tempfile.mkdtemp(
            prefix="mpisppy_procpool_")
        self.boot_timeout = float(boot_timeout)
        self.token = uuid.uuid4().hex
        self.condemned = False
        self.assigned = {}             # inner request id -> router rid
        self.proc = None
        self.pid = None
        self.port = None
        self.client = None
        self.boot_seconds = None       # worker-reported service boot
        self.spawn_seconds = None      # parent-observed spawn -> ready
        self.prewarm_loaded = 0
        self._logfile = None
        self._spawned_at = None
        self._dead_ids = itertools.count(-1, -1)
        self._health_lock = threading.Lock()
        self._last_health = None
        self._last_health_at = 0.0
        self._last_cache = {}
        self._probe_failures = 0
        self._death_reason = None
        self._peek_lock = threading.Lock()
        self._peek_live = set()        # ids whose done-ness we track
        self._fetched = {}             # id -> decoded result, un-peeked
        self._last_statuses_at = 0.0
        self._outstanding = 0          # submitted minus results fetched

    # -- lifecycle --------------------------------------------------------
    def spawn(self):
        """Fork the worker (non-blocking half of start: the set spawns
        every slot first, then waits on all — boots overlap)."""
        cfg = {
            "options": _jsonable_options(self.options),
            "token": self.token,
            "portfile": self._portfile,
            "x64": _detect_x64(),
            "force_cpu": _detect_force_cpu(),
        }
        cfgfile = os.path.join(self.workdir, f"cfg_{self.name}.json")
        with open(cfgfile, "w") as f:
            json.dump(cfg, f)
        try:
            os.remove(self._portfile)
        except OSError:
            pass
        env = dict(os.environ)
        env["PYTHONPATH"] = _repo_root() + os.pathsep \
            + env.get("PYTHONPATH", "")
        self._logfile = os.path.join(self.workdir,
                                     f"worker_{self.name}.log")
        log = open(self._logfile, "ab")
        self._spawned_at = time.monotonic()
        # stdin is the parent-liveness pipe: the worker hard-exits on
        # EOF there, so a crashed router never leaks worker processes
        self.proc = subprocess.Popen(
            [sys.executable, "-m", "mpisppy_tpu.serve.procworker",
             cfgfile],
            stdin=subprocess.PIPE, stdout=log, stderr=log, env=env)
        log.close()
        self.pid = self.proc.pid
        return self

    @property
    def _portfile(self):
        return os.path.join(self.workdir, f"port_{self.name}.json")

    def _log_tail(self, n=2000):
        try:
            with open(self._logfile, "rb") as f:
                return f.read()[-n:].decode("utf-8", "replace")
        except OSError:
            return "<no worker log>"

    def wait_ready(self):
        """Block until the worker's portfile lands (atomic write: a
        visible file is a complete file), then connect.  A child that
        exits first raises with its log tail."""
        deadline = time.monotonic() + self.boot_timeout
        while True:
            if os.path.exists(self._portfile):
                break
            rc = self.proc.poll()
            if rc is not None:
                raise RuntimeError(
                    f"worker {self.name} exited rc={rc} before "
                    f"serving; log tail:\n{self._log_tail()}")
            if time.monotonic() > deadline:
                self.proc.kill()
                raise RuntimeError(
                    f"worker {self.name} failed to boot within "
                    f"{self.boot_timeout}s; log tail:\n"
                    f"{self._log_tail()}")
            time.sleep(0.02)
        with open(self._portfile) as f:
            info = json.load(f)
        self.port = int(info["port"])
        self.boot_seconds = info.get("boot_seconds")
        self.prewarm_loaded = int(info.get("prewarm_loaded", 0))
        self.spawn_seconds = time.monotonic() - self._spawned_at
        self.client = PooledClient(
            "127.0.0.1", self.port, token=self.token, pool_size=2,
            request_timeout=float(
                self.options.get("serve_result_timeout", 600.0)) + 30.0)
        return self

    def start(self):
        if self.proc is None:
            self.spawn()
        if self.client is None:
            self.wait_ready()
        return self

    # -- the router-facing replica surface --------------------------------
    def _dead_handle(self):
        """Submit against a dead worker must still return a handle (the
        router records it, then replace-and-replay picks the request
        up); negative ids poll "unknown" and peek None forever."""
        return RequestHandle(next(self._dead_ids))

    def submit(self, batch, options=None, scenario_names=None,
               deadline=None, model=None):
        try:
            resp, _ = self.client.call(
                "submit", P.encode_batch(batch), options=options,
                scenario_names=scenario_names, deadline=deadline,
                model=model)
        except (ConnectionError, ClientError, OSError):
            return self._dead_handle()
        hid = int(resp["result"]["handle"])
        with self._peek_lock:
            self._peek_live.add(hid)
            self._outstanding += 1
        return RequestHandle(hid)

    def poll(self, handle):
        if handle.id < 0:
            return "unknown"
        try:
            resp, _ = self.client.call("poll", handle=handle.id,
                                       timeout=10.0)
        except (ConnectionError, ClientError, OSError):
            return "unknown"
        return resp["result"]["state"]

    def _refresh_fetched(self, rid):
        """Pull every done result for this worker in ONE `peek_many`
        frame into `_fetched`, at most once per `_PEEK_CACHE_S`
        window.  One frame serves the router's whole scan tick —
        discovery and payload fetch combined — so a 16-request tick
        costs one round trip, not 16, and a completed group's tail is
        one frame, not one per request.  Returns True when `rid` is
        fetched."""
        now = time.monotonic()
        with self._peek_lock:
            if rid in self._fetched:
                return True
            self._peek_live.add(rid)
            if now - self._last_statuses_at < _PEEK_CACHE_S:
                return False
            self._last_statuses_at = now
            live = sorted(self._peek_live)
        try:
            resp, payload = self.client.call("peek_many",
                                             handles=live,
                                             timeout=30.0)
        except (ConnectionError, ClientError, OSError):
            return False
        r = resp["result"]
        off, fetched = 0, {}
        for hid, n in r["sizes"]:
            hid, n = int(hid), int(n)
            fetched[hid] = P.decode_result(r["results"][str(hid)],
                                           payload[off:off + n])
            off += n
        unknown = {int(u) for u in r.get("unknown") or ()}
        with self._peek_lock:
            for hid in list(fetched) + sorted(unknown):
                if hid in self._peek_live:
                    self._outstanding = max(0, self._outstanding - 1)
                self._peek_live.discard(hid)
            self._fetched.update(fetched)
            return rid in self._fetched

    def peek(self, handle):
        """Non-blocking terminal-result fetch, served from the bulk
        `_refresh_fetched` cache (see above)."""
        if handle.id < 0:
            return None
        with self._peek_lock:
            res = self._fetched.pop(handle.id, None)
        if res is not None:
            return res
        if not self._refresh_fetched(handle.id):
            return None
        with self._peek_lock:
            return self._fetched.pop(handle.id, None)

    def _dead_health(self, reason):
        return {
            "failed": reason, "draining": False, "stopped": True,
            "queue_depth": 0, "inflight": 0, "last_dispatch_age": 0.0,
            "restarts": 0, "crash_suspects": set(),
            "bucket_starvation": 0, "replica_mode": "process",
            "pid": self.pid, "cache": dict(self._last_cache),
        }

    def _with_outstanding(self, h, fresh):
        """Overlay the parent-side outstanding count (submits minus
        results fetched) onto a health snapshot so the router's load
        metric (`queue_depth + inflight`) tracks reality during a
        submit burst, when the wire snapshot is up to
        `_HEALTH_CACHE_S` stale.  Outstanding is an upper bound on the
        worker's true load, and a FRESH wire reading is a lower
        bound, so their max is safe; a STALE reading is neither — it
        can still show the previous burst's load and mis-route the
        whole next burst onto one worker (uneven splits dispatch
        odd-width groups downstream, and each width is its own
        trace) — so on the cached path outstanding replaces it."""
        with self._peek_lock:
            outstanding = self._outstanding
        if fresh:
            qd = int(h.get("queue_depth", 0) or 0)
            h["inflight"] = max(int(h.get("inflight", 0) or 0),
                                outstanding - qd)
        else:
            h["queue_depth"] = 0
            h["inflight"] = outstanding
        return h

    def health(self):
        """One probe, three layers: (1) the waitpid death check ALWAYS
        runs — kill -9 is detected on the next probe, not after a
        socket timeout; (2) fresh-enough snapshots are served from a
        tiny cache so per-submit picks don't convoy on a busy worker's
        wire RTT; (3) repeated transport failures against a LIVE
        process synthesize failure — wedged != healthy."""
        rc = self.proc.poll() if self.proc is not None else None
        if rc is not None:
            if self._death_reason is None:
                self._death_reason = (
                    f"worker process died (pid {self.pid}, rc={rc})")
            return self._dead_health(self._death_reason)
        now = time.monotonic()
        with self._health_lock:
            if self._last_health is not None \
                    and now - self._last_health_at < _HEALTH_CACHE_S:
                return self._with_outstanding(
                    dict(self._last_health,
                         crash_suspects=set(
                             self._last_health["crash_suspects"])),
                    fresh=False)
        try:
            resp, _ = self.client.call("health", timeout=10.0)
        except (ConnectionError, ClientError, OSError) as exc:
            with self._health_lock:
                self._probe_failures += 1
                n = self._probe_failures
            if n >= _PROBE_FAILURE_LIMIT:
                return self._dead_health(
                    f"worker unreachable ({n} consecutive probe "
                    f"failures: {exc})")
            if self._last_health is not None:
                return self._with_outstanding(
                    dict(self._last_health,
                         crash_suspects=set(
                             self._last_health["crash_suspects"])),
                    fresh=False)
            return self._dead_health(f"worker not answering: {exc}")
        h = dict(resp["result"])
        h["crash_suspects"] = set(h.get("crash_suspects") or ())
        with self._health_lock:
            self._probe_failures = 0
            self._last_health = h
            self._last_health_at = time.monotonic()
            self._last_cache = dict(h.get("cache") or {})
        return self._with_outstanding(
            dict(h, crash_suspects=set(h["crash_suspects"])),
            fresh=True)

    def cache_stats(self):
        """The worker's CompileCache.stats() as last reported over the
        health wire (the cache object never leaves the worker)."""
        with self._health_lock:
            return dict(self._last_cache)

    @property
    def failed(self):
        return self.health()["failed"] is not None

    def drain(self, deadline=1.0, checkpoint_path=None):
        if self.proc is not None and self.proc.poll() is not None:
            # a corpse has nothing to flush and nothing to checkpoint;
            # the router replays its requests from its own table
            return {"drained": 0, "checkpoint": None}
        try:
            resp, _ = self.client.call(
                "drain", deadline=deadline,
                checkpoint_path=checkpoint_path,
                timeout=float(deadline) + 30.0)
        except (ConnectionError, ClientError, OSError):
            return {"drained": 0, "checkpoint": None}
        return dict(resp["result"])

    def warm_from(self, path):
        try:
            resp, _ = self.client.call("warm_from", path=str(path),
                                       timeout=60.0)
        except (ConnectionError, ClientError, OSError) as exc:
            return {"status": "failed",
                    "reason": "worker_unreachable", "error": repr(exc)}
        r = resp["result"]
        if "adopted" in r:
            adopted = [(sid, RequestHandle(int(hid)))
                       for sid, hid in r["adopted"]]
            # adopted requests are load this parent now owns: count
            # them like submits so routing sees them and the peek
            # fetch decrements them symmetrically
            with self._peek_lock:
                for _sid, h in adopted:
                    if h.id not in self._peek_live:
                        self._peek_live.add(h.id)
                        self._outstanding += 1
            return adopted
        return r.get("error")

    def shutdown(self, timeout=5.0):
        """Cooperative verb → SIGTERM → SIGKILL, the SpokeSupervisor
        escalation ladder, each rung bounded by a slice of `timeout`."""
        proc = self.proc
        if proc is None:
            return
        slice_s = max(0.2, float(timeout) / 3.0)
        if proc.poll() is None:
            try:
                self.client.call("shutdown", timeout=slice_s)
            except (ConnectionError, ClientError, OSError):
                pass
            try:
                proc.wait(timeout=slice_s)
            except subprocess.TimeoutExpired:
                pass
        if proc.poll() is None:
            proc.terminate()
            try:
                proc.wait(timeout=slice_s)
            except subprocess.TimeoutExpired:
                pass
        if proc.poll() is None:
            proc.kill()
            try:
                proc.wait(timeout=slice_s)
            except subprocess.TimeoutExpired:  # pragma: no cover
                pass
        if self.client is not None:
            self.client.close()
        if proc.stdin is not None:
            try:
                proc.stdin.close()
            except OSError:
                pass


class ProcReplicaSet:
    """The process fleet behind the router — replica.ReplicaSet's
    surface (slots, incarnations, chaos targeting, replace) over
    ProcReplica workers.

    Chaos targeting reuses the thread fleet's rules verbatim
    (replica._SLOT_CHAOS / _GLOBAL_CHAOS): slot-targeted keys reach
    only the chaos slot's FIRST incarnation, `poison_request` arms
    every worker.  The chaos config rides the worker's options JSON —
    the injector fires inside the child, so `replica_crash` there is a
    real process exit."""

    def __init__(self, options=None, n_replicas=None):
        o = dict(options or {})
        self.options = o
        self.n = int(n_replicas if n_replicas is not None
                     else o.get("serve_replicas", 2))
        if self.n < 1:
            raise ValueError(f"serve_replicas must be >= 1, got {self.n}")
        chaos = dict(o.get("chaos") or {})
        self.chaos_slot = int(chaos.pop("chaos_replica", 0))
        self.chaos = chaos
        self.boot_timeout = float(o.get("serve_proc_boot_timeout", 180.0))
        self.workdir = o.get("serve_proc_workdir") or tempfile.mkdtemp(
            prefix="mpisppy_procpool_")
        self.incarnations = [0] * self.n
        self.replacements = 0
        self.replicas = [self._build(slot) for slot in range(self.n)]
        self._started = False

    def _chaos_for(self, slot, incarnation):
        cfg = {k: self.chaos[k] for k in _GLOBAL_CHAOS if k in self.chaos}
        if slot == self.chaos_slot and incarnation == 0:
            cfg.update({k: self.chaos[k] for k in _SLOT_CHAOS
                        if k in self.chaos})
        return cfg

    def _build(self, slot):
        inc = self.incarnations[slot]
        return ProcReplica(slot, inc, self.options,
                           chaos=self._chaos_for(slot, inc),
                           workdir=self.workdir,
                           boot_timeout=self.boot_timeout)

    def start(self):
        """Spawn EVERY worker first, then wait on all — N boots cost
        max(boot), not sum(boot)."""
        if self._started:
            return self
        for r in self.replicas:
            if r.proc is None:
                r.spawn()
        for r in self.replicas:
            if r.client is None:
                r.wait_ready()
        self._started = True
        return self

    def __iter__(self):
        return iter(self.replicas)

    def __len__(self):
        return self.n

    def __getitem__(self, slot):
        return self.replicas[slot]

    def replace(self, slot, drain_deadline=1.0, checkpoint_path=None):
        """Mirror ReplicaSet.replace over processes: drain the corpse
        (a DEAD process drains to nothing — the router replays from its
        own table), kill it, boot a fresh incarnation (prewarmed from
        the shared AOT dir), warm it from the drain checkpoint when one
        was written."""
        corpse = self.replicas[slot]
        corpse.condemned = True
        drain_info = corpse.drain(deadline=drain_deadline,
                                  checkpoint_path=checkpoint_path)
        corpse.shutdown(timeout=max(1.0, drain_deadline))
        self.incarnations[slot] += 1
        self.replacements += 1
        fresh = self._build(slot).start()
        self.replicas[slot] = fresh
        adopted = []
        saved = drain_info.get("checkpoint")
        if saved:
            out = fresh.warm_from(saved)
            if isinstance(out, list):
                adopted = out
        return fresh, drain_info, adopted

    def boot_stats(self):
        """Fleet boot economics for the bench JSON: parent-observed
        spawn-to-ready seconds per live replica, and the total AOT
        artifacts the workers prewarmed."""
        spawns = [r.spawn_seconds for r in self.replicas
                  if r.spawn_seconds is not None]
        return {"proc_boot_seconds": spawns,
                "prewarm_loaded": sum(r.prewarm_loaded
                                      for r in self.replicas)}

    def shutdown(self, timeout=5.0):
        deadline = time.monotonic() + float(timeout)
        for r in self.replicas:
            r.shutdown(timeout=max(0.5, deadline - time.monotonic()))

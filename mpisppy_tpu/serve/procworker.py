"""Process-replica worker entrypoint: one SolverService per OS process.

`python -m mpisppy_tpu.serve.procworker <cfg.json>` boots a
`SolverService` in THIS process and serves it over the serve/net wire
protocol on a loopback socket — the out-of-process half of
`serve_replica_mode="process"`.  The parent (serve/procpool.py) never
shares a JAX runtime with the worker, which is the whole point: each
worker owns its own backend, so N workers execute N solves truly in
parallel instead of convoying on the in-process `_BACKEND_LOCK`.

Boot sequence (the order matters):

  1. read the config JSON (options, token, portfile path, x64 flag);
  2. export `JAX_ENABLE_X64` BEFORE anything imports jax — the parent's
     x64 state must be reproduced or batch=1 results stop being
     bitwise-comparable across the process boundary;
  3. start the parent watchdog: the parent holds our stdin open, so
     EOF there means the parent is gone and we hard-exit — no orphan
     workers accumulating after a crashed router;
  4. `ensure_cpu_backend(force=cfg["force_cpu"])` — mirror the parent's
     backend choice;
  5. build + start the service, `prewarm()` the shared AOT artifact
     dir (`MPISPPY_TPU_COMPILE_CACHE_DIR/aot`, inherited env) so the
     first request of every previously-seen shape runs warm;
  6. bind 127.0.0.1:0, then atomically write the portfile — the parent
     polls for it; a complete portfile means "ready to serve".

Wire surface: the replica verbs (`submit/poll/peek/peek_many/statuses/
health/drain/warm_from/shutdown`), one frame in → one frame out per connection in
FIFO order (so the parent's pipelined PooledClient can match responses
without ids; the `seq` header is echoed as a cross-check).  Responses
reuse the gateway's frame shapes.

Layering: module-level imports are stdlib + serve/net/protocol +
serve/request only — jax loads when `main()` configures the service,
never at import time (AST + fresh-interpreter guarded in
tests/test_procserve.py).
"""

from __future__ import annotations

import json
import os
import socket
import sys
import threading
import time

from .net import protocol as P
from .request import RequestHandle

#: the verbs this worker serves (a subset of protocol.VERBS plus the
#: replica-only ones the gateway rejects)
WORKER_VERBS = ("submit", "poll", "peek", "peek_many", "statuses",
                "health", "drain", "warm_from", "shutdown")


class WorkerServer:
    """The in-process half of one process replica: a SolverService
    behind a loopback wire endpoint (see module docstring)."""

    def __init__(self, options=None, token="", host="127.0.0.1",
                 max_payload=P.DEFAULT_MAX_PAYLOAD):
        self.options = dict(options or {})
        self.token = token
        self.host = host
        self.max_payload = int(max_payload)
        self.service = None
        self.port = None
        self.boot_seconds = None
        self.prewarm_loaded = 0
        self._sock = None
        self._stopped = False
        self._done = threading.Event()

    # -- lifecycle --------------------------------------------------------
    def start(self):
        """Build + start the service (heavy: first jax import), prewarm
        the AOT artifact set, then open the loopback endpoint."""
        t0 = time.monotonic()
        from . import compile_cache as _cc
        from .service import SolverService
        self.service = SolverService(self.options).start()
        if self.options.get("serve_prewarm", True):
            self.prewarm_loaded = _cc.prewarm()
        self.boot_seconds = time.monotonic() - t0
        sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        sock.bind((self.host, 0))
        sock.listen(64)
        self._sock = sock
        self.port = sock.getsockname()[1]
        threading.Thread(target=self._accept_main,
                         name="procworker-accept", daemon=True).start()
        return self

    def wait(self):
        """Block until a shutdown verb lands (the worker main loop)."""
        while not self._done.wait(0.5):
            pass

    def stop(self):
        self._stopped = True
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
        self._done.set()

    # -- connection handling ----------------------------------------------
    def _accept_main(self):
        while not self._stopped:
            try:
                conn, _addr = self._sock.accept()
            except OSError:
                return                 # listener closed: shutting down
            threading.Thread(target=self._conn_main, args=(conn,),
                             name="procworker-conn", daemon=True).start()

    def _conn_main(self, conn):
        """One connection's frames, strictly in order — the FIFO
        contract the parent's pipelined client relies on."""
        try:
            while not self._stopped:
                header, payload = P.read_message(
                    conn, max_payload=self.max_payload)
                if header is None:
                    return             # clean EOF
                try:
                    resp, rpayload = self._dispatch(header, payload)
                except P.ProtocolError as exc:
                    resp, rpayload = self._error(
                        P.E_BAD_PAYLOAD, str(exc))
                except Exception as exc:
                    resp, rpayload = self._error(P.E_INTERNAL,
                                                 repr(exc))
                if "seq" in header:
                    resp["seq"] = header["seq"]
                conn.sendall(P.pack_message(resp, rpayload))
        except (P.ProtocolError, ConnectionError, OSError):
            pass                       # torn stream: client reconnects
        finally:
            try:
                conn.close()
            except OSError:
                pass

    # -- frames ------------------------------------------------------------
    def _error(self, code, message):
        return {"kind": "response", "ok": False, "error_code": code,
                "error": str(message)[:2000]}, b""

    def _ok(self, verb, result=None, payload=b""):
        hdr = {"kind": "response", "ok": True, "verb": verb,
               "error_code": None}
        if result is not None:
            hdr["result"] = result
        return hdr, payload

    def _dispatch(self, header, payload):
        verb = header.get("verb")
        if verb not in WORKER_VERBS:
            return self._error(P.E_BAD_VERB, f"unknown verb {verb!r}")
        if header.get("token") != self.token:
            return self._error(P.E_UNAUTHORIZED,
                               "worker token mismatch")
        return getattr(self, f"_verb_{verb}")(header, payload)

    # -- verbs -------------------------------------------------------------
    def _verb_submit(self, header, payload):
        batch = P.decode_batch(payload)
        h = self.service.submit(
            batch, options=header.get("options"),
            scenario_names=header.get("scenario_names"),
            deadline=header.get("deadline"),
            model=header.get("model"))
        return self._ok("submit", {"handle": h.id})

    def _verb_poll(self, header, payload):
        h = RequestHandle(int(header.get("handle", -1)))
        return self._ok("poll", {"state": self.service.poll(h)})

    def _verb_peek(self, header, payload):
        """Non-blocking terminal-result fetch, mirroring
        replica.Replica.peek: {"pending": true} until the inner request
        is done, then the encoded result (npz payload, bit-exact)."""
        rid = int(header.get("handle", -1))
        req = self.service._requests.get(rid)
        if req is None or not req.done.is_set():
            return self._ok("peek", {"pending": True})
        res = self.service._results.get(rid)
        if res is None:                # finished-but-unrecorded race
            return self._ok("peek", {"pending": True})
        scalars, rpayload = P.encode_result(res)
        return self._ok("peek", {"pending": False,
                                 "result": scalars}, rpayload)

    def _verb_peek_many(self, header, payload):
        """Bulk terminal-result fetch: every done handle's result in
        ONE frame.  When a group of 8 completes, per-handle peeks cost
        8 round trips of pure tail latency (the device is idle by
        then); this returns the whole group at once.  Payload is the
        per-result npz blobs concatenated, with `sizes` ([rid, nbytes]
        in payload order) as the slicing map."""
        done, sizes, blobs, unknown = {}, [], [], []
        for rid in header.get("handles") or ():
            rid = int(rid)
            req = self.service._requests.get(rid)
            if req is None:
                unknown.append(rid)    # caller stops tracking it
                continue
            if not req.done.is_set():
                continue
            res = self.service._results.get(rid)
            if res is None:            # finished-but-unrecorded race
                continue
            scalars, rpayload = P.encode_result(res)
            done[str(rid)] = scalars
            sizes.append([rid, len(rpayload)])
            blobs.append(rpayload)
        return self._ok("peek_many", {"results": done, "sizes": sizes,
                                      "unknown": unknown},
                        b"".join(blobs))

    def _verb_statuses(self, header, payload):
        """Bulk done-ness check: ONE frame answers the router's whole
        scan tick.  Per-handle `peek`s at scan cadence would mean
        hundreds of frames per second, each waking a connection thread
        that contends the GIL against the dispatch thread's
        per-iteration host work — the convoy shows up directly as
        solve throughput."""
        out = {}
        for rid in header.get("handles") or ():
            req = self.service._requests.get(int(rid))
            if req is None:
                out[str(rid)] = "unknown"
            else:
                out[str(rid)] = "done" if req.done.is_set() \
                    else "pending"
        return self._ok("statuses", {"statuses": out})

    def _verb_health(self, header, payload):
        h = dict(self.service.health())
        # sets are not JSON: the parent-side ProcReplica restores this
        h["crash_suspects"] = sorted(h.get("crash_suspects") or ())
        h["replica_mode"] = "process"
        h["pid"] = os.getpid()
        h["cache"] = self.service.cache.stats()
        h["prewarm_loaded"] = self.prewarm_loaded
        h["boot_seconds"] = self.boot_seconds
        return self._ok("health", h)

    def _verb_drain(self, header, payload):
        info = self.service.drain(
            deadline=float(header.get("deadline", 1.0)),
            checkpoint_path=header.get("checkpoint_path"))
        ckpt = info.get("checkpoint")
        return self._ok("drain", {
            "drained": int(info.get("drained", 0)),
            "checkpoint": None if ckpt is None else str(ckpt)})

    def _verb_warm_from(self, header, payload):
        out = self.service.warm_from(header.get("path"))
        if isinstance(out, list):
            return self._ok("warm_from", {
                "adopted": [[int(sid), int(h.id)] for sid, h in out]})
        return self._ok("warm_from", {"error": P.jsonable(out)})

    def _verb_shutdown(self, header, payload):
        timeout = float(header.get("timeout", 5.0))

        def _finish():
            time.sleep(0.05)           # let the reply frame flush
            try:
                self.service.shutdown(timeout=timeout)
            finally:
                self.stop()

        threading.Thread(target=_finish, name="procworker-shutdown",
                         daemon=True).start()
        return self._ok("shutdown", {"stopping": True})


def _watch_parent():
    """Hard-exit when the parent disappears: the parent holds our stdin
    pipe open for our whole life, so EOF means it's gone.  `os._exit`
    on purpose — an orphan must not linger to flush anything."""
    try:
        sys.stdin.buffer.read()
    except Exception:
        pass
    os._exit(2)


def main(argv=None):
    argv = sys.argv[1:] if argv is None else list(argv)
    if len(argv) != 1:
        print("usage: python -m mpisppy_tpu.serve.procworker <cfg.json>",
              file=sys.stderr)
        return 2
    with open(argv[0]) as f:
        cfg = json.load(f)
    # x64 must be pinned BEFORE jax loads anywhere in this process
    x64 = cfg.get("x64")
    if x64 is not None:
        os.environ["JAX_ENABLE_X64"] = "1" if x64 else "0"
    threading.Thread(target=_watch_parent, name="procworker-watchdog",
                     daemon=True).start()
    from ..utils.platform import ensure_cpu_backend
    ensure_cpu_backend(force=bool(cfg.get("force_cpu")))
    server = WorkerServer(cfg.get("options") or {},
                          token=cfg.get("token", ""))
    server.start()
    portfile = cfg["portfile"]
    tmp = portfile + ".tmp"
    with open(tmp, "w") as f:
        json.dump({"port": server.port, "pid": os.getpid(),
                   "boot_seconds": server.boot_seconds,
                   "prewarm_loaded": server.prewarm_loaded}, f)
    os.replace(tmp, portfile)
    server.wait()
    return 0


if __name__ == "__main__":
    sys.exit(main())

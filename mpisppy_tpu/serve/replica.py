"""Replica — one supervised SolverService as an isolated fault domain.

The single in-process `SolverService` (serve/service.py) is the whole
blast radius: one worker crash, hang, or poison request takes every
tenant down with it.  This module splits the service tier into N
independent fault domains:

  * each `Replica` owns its OWN SolverService — its own dispatch
    thread, its own bounded queue, its own CompileCache handle, its
    own chaos injector — so nothing short of the process dying can
    couple two replicas' failures;
  * a `ReplicaSet` owns the slots: it builds the initial replicas,
    targets chaos at exactly one slot (`chaos_replica`), and replaces
    a dead replica with a fresh incarnation whose injected fault is
    CLEARED (a transient fault does not follow the slot) — only
    `poison_request` survives replacement, because poison follows the
    request, not the replica.

Layering: this module is jax-free at module level (AST-guarded in
tests/test_serve.py) and is driven only by serve/router.py; the heavy
service machinery loads on first replica construction.
"""

from __future__ import annotations

import time

# chaos keys that target ONE slot's FIRST incarnation only: the fault
# is an event that happened to that replica, not a property of the slot
_SLOT_CHAOS = ("replica_crash", "slow_replica", "crash_at_step",
               "crash_at_iter", "hang_at_step", "dispatch_delay_s")
# chaos keys that arm EVERY replica, every incarnation: the fault
# travels with the request, so a hedge or replay re-triggers it
_GLOBAL_CHAOS = ("poison_request",)


class Replica:
    """One fault domain: a SolverService plus the set bookkeeping.

    `name` is "r<slot>i<incarnation>" — stable across the replica's
    life, unique across replacements, and the label every router
    telemetry event carries."""

    def __init__(self, slot, incarnation, options, chaos=None):
        from .service import SolverService
        self.slot = int(slot)
        self.incarnation = int(incarnation)
        self.name = f"r{self.slot}i{self.incarnation}"
        o = dict(options or {})
        o["chaos"] = dict(chaos or {})
        # each replica gets its own compile-cache handle (cache=None:
        # the service builds one) — a wedged or corrupted cache dies
        # with its replica instead of poisoning the peers
        self.service = SolverService(o)
        self.condemned = False        # router: replacement in progress
        self.assigned = {}            # inner request id -> router rid

    # -- service passthrough ---------------------------------------------
    def start(self):
        self.service.start()
        return self

    def submit(self, batch, options=None, scenario_names=None,
               deadline=None, model=None):
        return self.service.submit(batch, options,
                                   scenario_names=scenario_names,
                                   deadline=deadline, model=model)

    def poll(self, handle):
        return self.service.poll(handle)

    def peek(self, handle):
        """Non-blocking terminal-result fetch: the result dict when the
        inner request is done, else None (never a timeout snapshot —
        the router's monitor loop polls, it does not wait)."""
        req = self.service._requests.get(handle.id)
        if req is None or not req.done.is_set():
            return None
        return self.service._results.get(handle.id)

    def health(self):
        return self.service.health()

    def cache_stats(self):
        """This replica's CompileCache.stats() dict — the duck-typed
        surface Router.stats() merges (process replicas report the same
        dict over the wire, so the router never touches a cache
        object)."""
        return self.service.cache.stats()

    @property
    def failed(self):
        return self.service._failed is not None

    def drain(self, deadline=1.0, checkpoint_path=None):
        return self.service.drain(deadline=deadline,
                                  checkpoint_path=checkpoint_path)

    def warm_from(self, path):
        return self.service.warm_from(path)

    def shutdown(self, timeout=5.0):
        self.service.shutdown(timeout=timeout)


class ReplicaSet:
    """The N slots behind the router.

    Chaos targeting: `options["chaos"]` may carry the serve-replica
    fault keys plus `chaos_replica` (default 0) naming the slot they
    hit.  Slot-targeted keys reach only that slot's FIRST incarnation;
    `poison_request` arms every replica (see module docstring)."""

    def __init__(self, options=None, n_replicas=None):
        o = dict(options or {})
        self.options = o
        self.n = int(n_replicas if n_replicas is not None
                     else o.get("serve_replicas", 2))
        if self.n < 1:
            raise ValueError(f"serve_replicas must be >= 1, got {self.n}")
        chaos = dict(o.get("chaos") or {})
        self.chaos_slot = int(chaos.pop("chaos_replica", 0))
        self.chaos = chaos
        self.incarnations = [0] * self.n
        self.replacements = 0
        self.replicas = [self._build(slot) for slot in range(self.n)]

    def _chaos_for(self, slot, incarnation):
        cfg = {k: self.chaos[k] for k in _GLOBAL_CHAOS if k in self.chaos}
        if slot == self.chaos_slot and incarnation == 0:
            cfg.update({k: self.chaos[k] for k in _SLOT_CHAOS
                        if k in self.chaos})
        return cfg

    def _build(self, slot):
        inc = self.incarnations[slot]
        return Replica(slot, inc, self.options,
                       chaos=self._chaos_for(slot, inc))

    def start(self):
        for r in self.replicas:
            r.start()
        return self

    def __iter__(self):
        return iter(self.replicas)

    def __len__(self):
        return self.n

    def __getitem__(self, slot):
        return self.replicas[slot]

    def replace(self, slot, drain_deadline=1.0, checkpoint_path=None):
        """Swap the slot's corpse for a fresh incarnation: drain the
        old service (leftovers checkpointed when a path is given),
        build + start the replacement, and warm it from the drain file
        when one was written.  Returns (new_replica, drain_info,
        adopted) where `adopted` is warm_from's (old_inner_id, handle)
        list — the router re-binds those to its own request table."""
        corpse = self.replicas[slot]
        corpse.condemned = True
        drain_info = corpse.drain(deadline=drain_deadline,
                                  checkpoint_path=checkpoint_path)
        corpse.shutdown(timeout=drain_deadline)
        self.incarnations[slot] += 1
        self.replacements += 1
        fresh = self._build(slot).start()
        self.replicas[slot] = fresh
        adopted = []
        saved = drain_info.get("checkpoint")
        if saved:
            out = fresh.warm_from(saved)
            # a corrupt drain file yields a structured error dict; the
            # replacement still goes live empty and the router replays
            # through its own table instead
            if isinstance(out, list):
                adopted = out
        return fresh, drain_info, adopted

    def shutdown(self, timeout=5.0):
        deadline = time.monotonic() + float(timeout)
        for r in self.replicas:
            r.shutdown(timeout=max(0.1, deadline - time.monotonic()))

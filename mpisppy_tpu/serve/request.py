"""Request/result envelope types for the serve layer.

Deliberately jax-free (like telemetry/): clients import these through
`serve.api` without paying backend initialization.  A request moves
through

    QUEUED -> RUNNING -> {OK, TIMEOUT, FAILED}
           -> REJECTED (admission control, never entered the queue)

and every terminal transition produces a STRUCTURED result dict (never
an exception into the dispatch thread, never a hang for the client):
the `status` key always holds one of the constants below, and on
success the remaining keys are exactly `PH.solution_dict()` — the same
values `PH.ph_main` returns.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Any

QUEUED = "queued"
RUNNING = "running"
OK = "ok"
TIMEOUT = "timeout"
REJECTED = "rejected"
FAILED = "failed"

TERMINAL = (OK, TIMEOUT, REJECTED, FAILED)


@dataclasses.dataclass(frozen=True)
class RequestHandle:
    """Opaque ticket returned by submit(); poll/result take it back."""
    id: int


@dataclasses.dataclass(frozen=True)
class RouterHandle:
    """Opaque ticket returned by Router.submit() (serve/router.py).
    Distinct from RequestHandle on purpose: one router request may map
    to SEVERAL inner service requests over its life (hedges, replays,
    warm_from adoptions), and only the router may translate between
    the two id spaces."""
    id: int


@dataclasses.dataclass
class SolveRequest:
    """One queued solve: the batch + options the client handed in,
    plus the service-side bookkeeping (deadline is ABSOLUTE monotonic
    seconds; bucket is filled lazily at dispatch time)."""
    id: int
    batch: Any
    options: dict
    scenario_names: Any = None
    model: str | None = None
    deadline: float | None = None
    submitted: float = 0.0
    bucket: Any = None
    attempts: int = 0
    status: str = QUEUED
    done: threading.Event = dataclasses.field(
        default_factory=threading.Event)

    def expired(self, now=None):
        if self.deadline is None:
            return False
        return (time.monotonic() if now is None else now) > self.deadline


def _base(req_id, status, **kw):
    d = {"status": status, "request_id": req_id}
    d.update(kw)
    return d


def timeout_result(req, where, **kw):
    """Deadline exceeded — `where` says at which stage (queued /
    dispatch / iteration / result_wait) the clock ran out."""
    return _base(req.id, TIMEOUT, where=where,
                 wall_s=time.monotonic() - req.submitted, **kw)


def rejected_result(req_id, reason):
    return _base(req_id, REJECTED, reason=reason)


def failed_result(req_id, reason, **kw):
    return _base(req_id, FAILED, reason=reason, **kw)

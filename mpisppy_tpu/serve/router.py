"""Router — the replica-set front door for the serve layer.

A jax-free traffic layer over `replica.ReplicaSet`: clients talk to
the router, the router talks to N supervised SolverService replicas
(each its own fault domain), and every production-robustness decision
lives here, above the solver:

  * **health-probed circuit breakers** — a three-state breaker per
    SLOT (closed / open / half-open), fed by the replica's telemetry
    counters through `SolverService.health()` (queue depth,
    last-dispatch age, terminal failure).  An open breaker sheds
    traffic; reopen probes follow the shared capped-backoff policy
    (`resilience.restart_delay`), and the breaker outlives the replica
    it judged: a replacement replica starts behind the still-open
    breaker and must pass a half-open probe to close it.
  * **hedged retries** — a request sitting unresolved past
    `router_hedge_threshold` is resubmitted to a second replica.
    Idempotency keys make this safe: duplicate completions resolve to
    ONE client result (first completion wins; the late twin is counted
    in `router.duplicate_completions`, never delivered).
  * **per-tenant token-bucket quotas** — `router_tenant_rate` /
    `router_tenant_burst` admission, structured `over_quota` rejects.
  * **brownout ladder** — sustained overload degrades in steps
    instead of collapsing: level 1 sheds hedges, level 2 widens the
    solve tolerance of ADMITTED requests (convthresh x factor + the
    PR 4 `eps_ladder` knobs — same compile bucket, looser answers),
    level 3 rejects the lowest-priority tenants.  Every transition is
    a `router.brownout` telemetry event.
  * **replace-and-replay** — a failed replica is drained
    (`drain(deadline=)`, leftovers checkpointed), a fresh incarnation
    is started and `warm_from`s the checkpoint, and every unresolved
    request that was on the corpse is replayed through the idempotency
    table.  A **poison budget** stops hedge amplification: a request
    that was dispatched at `router_poison_budget` worker crashes is
    quarantined (structured `failed`/`quarantined` result) instead of
    being replayed into the next replica.

Layering (AST-guarded in tests/test_serve.py): this module never
imports jax at module level — the router is pure Python over the
replica API, so the front door can run in a process that never
initializes a backend until a replica dispatches.
"""

from __future__ import annotations

import dataclasses
import itertools
import os
import tempfile
import threading
import time
from typing import Any

from .. import global_toc
from .. import telemetry as _telemetry
from ..resilience.supervisor import restart_delay
from .request import (FAILED, OK, QUEUED, REJECTED, RUNNING, TIMEOUT,
                      RouterHandle, failed_result, rejected_result)

CLOSED, OPEN, HALF_OPEN = "closed", "open", "half_open"


class TokenBucket:
    """Per-tenant admission quota: `burst` tokens refilled at `rate`
    per second; one token per admitted request."""

    def __init__(self, rate, burst):
        self.rate = float(rate)
        self.burst = float(burst)
        self.tokens = float(burst)
        self.stamp = time.monotonic()

    def take(self, now=None):
        now = time.monotonic() if now is None else now
        # clamp: a `now` captured before this bucket was lazily created
        # must not debit the fresh burst (negative elapsed)
        self.tokens = min(self.burst, self.tokens
                          + max(0.0, now - self.stamp) * self.rate)
        self.stamp = now
        if self.tokens >= 1.0:
            self.tokens -= 1.0
            return True
        return False


class CircuitBreaker:
    """Three-state breaker for one replica SLOT.

    closed --[fail_threshold consecutive probe/request failures]--> open
    open   --[capped-backoff reopen timer]--> half_open
    half_open --[success]--> closed   /   --[failure]--> open (longer)

    The reopen backoff reuses the shared restart-pacing policy
    (`resilience.restart_delay`) keyed on how many times this slot has
    tripped, so a flapping replica earns progressively longer time-outs
    up to the cap."""

    def __init__(self, fail_threshold=3, backoff=0.25, backoff_cap=5.0,
                 on_transition=None):
        self.fail_threshold = int(fail_threshold)
        self.backoff = float(backoff)
        self.backoff_cap = float(backoff_cap)
        self.state = CLOSED
        self.failures = 0
        self.opens = 0                 # lifetime transitions to OPEN
        self.reopen_at = 0.0
        self.transitions = [(CLOSED, time.monotonic())]
        self._notify = on_transition or (lambda old, new: None)

    def _to(self, state, now):
        if state == self.state:
            return
        old, self.state = self.state, state
        self.transitions.append((state, now))
        self._notify(old, state)

    def allow(self, now=None):
        """May traffic flow to this slot right now?  Also advances
        open -> half_open when the reopen timer expires (the caller's
        probe/routing attempt IS the reopen probe)."""
        now = time.monotonic() if now is None else now
        if self.state == CLOSED:
            return True
        if self.state == OPEN:
            if now >= self.reopen_at:
                self._to(HALF_OPEN, now)
                return True
            return False
        return True                    # HALF_OPEN: probes flow

    def record_success(self, now=None):
        now = time.monotonic() if now is None else now
        self.failures = 0
        if self.state == HALF_OPEN:
            self._to(CLOSED, now)

    def record_failure(self, now=None):
        now = time.monotonic() if now is None else now
        self.failures += 1
        if self.state == HALF_OPEN or (self.state == CLOSED
                                       and self.failures
                                       >= self.fail_threshold):
            self.trip(now)

    def trip(self, now=None):
        """Open immediately (replica death skips the failure count)."""
        now = time.monotonic() if now is None else now
        if self.state != OPEN:
            self.opens += 1
            self.reopen_at = now + restart_delay(
                self.opens, self.backoff, self.backoff_cap)
            self._to(OPEN, now)
        self.failures = 0

    def states_seen(self):
        return [s for s, _ in self.transitions]


@dataclasses.dataclass
class RouterRequest:
    """One client request in the router's table — possibly backed by
    several inner service requests over its life (hedge, replay,
    warm_from adoption)."""
    rid: int
    key: str                        # idempotency key (auto when absent)
    batch: Any
    options: dict
    scenario_names: Any
    model: str | None
    tenant: str
    priority: int
    deadline: float | None          # absolute monotonic
    submitted: float
    handles: list = dataclasses.field(default_factory=list)
    attempts: int = 0               # routings consumed
    hedged: bool = False
    hedge_shed: bool = False        # a brownout suppressed its hedge
    crash_count: int = 0            # worker crashes it was dispatched at
    status: str = QUEUED
    result: dict | None = None
    completions: int = 0            # terminal inner completions seen
    done: threading.Event = dataclasses.field(
        default_factory=threading.Event)

    def expired(self, now):
        return self.deadline is not None and now > self.deadline


class Router:
    """The replica-set front door (see module docstring).

    Options (all prefixed `router_` unless noted):
      serve_replicas              replica count                    (2)
      router_hedge_threshold      seconds before hedging (None=off)(0.5)
      router_max_attempts         routings per request             (3)
      router_poison_budget        crashes before quarantine        (1)
      router_tenant_rate          tokens/s per tenant (None=off)   (None)
      router_tenant_burst         bucket depth                     (8)
      router_tick                 monitor loop period seconds      (0.02)
      router_probe_interval       health-probe period seconds      (0.05)
      router_breaker_failures     consecutive fails to open        (3)
      router_breaker_backoff(_cap) reopen probe pacing         (0.25/5)
      router_breaker_queue_depth  probe-fail queue depth           (64)
      router_breaker_stall_s      probe-fail dispatch age          (30)
      router_replace_stall_s      stalled-this-long => replace     (120)
      router_drain_deadline       corpse drain budget seconds      (1.0)
      router_result_timeout/grace result() bounds             (600/30)
      router_brownout_high/low    load fractions (escalate/relax) (.75/.25)
      router_brownout_sustain     consecutive evals to move        (2)
      router_brownout_interval    eval period seconds              (0.25)
      router_brownout_conv_factor level-2 convthresh widening      (10)
      router_brownout_min_priority level-3 admission floor         (1)
      router_checkpoint_dir       drain checkpoint dir         (tmpdir)
      serve_replica_mode          "thread" | "process"        ("thread")
    plus every serve_* key, forwarded to each replica's service.
    In "process" mode each slot is its own OS process
    (serve/procpool.py) — device execution parallelizes past the
    in-process `_BACKEND_LOCK`; everything above the replica surface
    (breakers, hedging, quotas, brownout, replace-and-replay, roll)
    is mode-blind."""

    def __init__(self, options=None, replica_set=None):
        o = dict(options or {})
        self.options = o
        self.hedge_threshold = o.get("router_hedge_threshold", 0.5)
        self.max_attempts = int(o.get("router_max_attempts", 3))
        self.poison_budget = int(o.get("router_poison_budget", 1))
        self.tenant_rate = o.get("router_tenant_rate")
        self.tenant_burst = float(o.get("router_tenant_burst", 8))
        self.tick_interval = float(o.get("router_tick", 0.02))
        self.probe_interval = float(o.get("router_probe_interval", 0.05))
        self.breaker_failures = int(o.get("router_breaker_failures", 3))
        self.breaker_backoff = float(o.get("router_breaker_backoff", 0.25))
        self.breaker_backoff_cap = float(
            o.get("router_breaker_backoff_cap", 5.0))
        self.breaker_queue_depth = int(
            o.get("router_breaker_queue_depth", 64))
        self.breaker_stall_s = float(o.get("router_breaker_stall_s", 30.0))
        self.replace_stall_s = float(o.get("router_replace_stall_s", 120.0))
        self.drain_deadline = float(o.get("router_drain_deadline", 1.0))
        self.result_timeout = float(o.get("router_result_timeout", 600.0))
        self.result_grace = float(o.get("router_result_grace", 30.0))
        self.brownout_high = float(o.get("router_brownout_high", 0.75))
        self.brownout_low = float(o.get("router_brownout_low", 0.25))
        self.brownout_sustain = int(o.get("router_brownout_sustain", 2))
        self.brownout_interval = float(
            o.get("router_brownout_interval", 0.25))
        self.brownout_conv_factor = float(
            o.get("router_brownout_conv_factor", 10.0))
        self.brownout_min_priority = int(
            o.get("router_brownout_min_priority", 1))
        self.max_inflight = int(o.get("serve_max_inflight", 32))
        self._workdir = o.get("router_checkpoint_dir")
        self._tel = _telemetry.configure_from_options(o.get("telemetry"))
        if replica_set is None:
            mode = o.get("serve_replica_mode", "thread")
            if mode == "process":
                from .procpool import ProcReplicaSet
                replica_set = ProcReplicaSet(o)
            elif mode == "thread":
                from .replica import ReplicaSet
                replica_set = ReplicaSet(o)
            else:
                raise ValueError(
                    "serve_replica_mode must be 'thread' or "
                    f"'process', got {mode!r}")
        self.replica_set = replica_set
        self.breakers = [
            CircuitBreaker(self.breaker_failures, self.breaker_backoff,
                           self.breaker_backoff_cap,
                           on_transition=self._breaker_event(slot))
            for slot in range(len(replica_set))]
        self.brownout_level = 0
        self.brownout_transitions = []         # (level, monotonic)
        self._brownout_streak = 0
        self._last_brownout_eval = 0.0
        self._last_probe = 0.0
        self._lock = threading.RLock()
        self._replace_lock = threading.Lock()  # serialize _replace_slot
        self._rids = itertools.count(1)
        self._requests = {}            # rid -> RouterRequest (all)
        self._open = {}                # rid -> RouterRequest (unresolved)
        self._lingering = {}           # resolved but hedge-twin pending
        self._idempotency = {}         # key -> rid
        self._buckets = {}             # tenant -> TokenBucket
        self._suspects_seen = {}       # replica name -> counted ids
        self._starvation_seen = {}     # replica name -> counted total
        self._rr_offset = 0            # rotates equal-load pick ties
        self.counts = {}               # plain-int mirror of counters
        self.latencies = []            # ok-result router wall seconds
        self._monitor = None
        self._stopped = False
        self._started = False

    # -- small helpers ----------------------------------------------------
    def _count(self, name, n=1):
        self.counts[name] = self.counts.get(name, 0) + n
        self._tel.counter(f"router.{name}").inc(n)

    def _breaker_event(self, slot):
        def notify(old, new):
            self._tel.event("router.breaker", slot=slot, old=old, new=new)
            if new == OPEN:
                self._count("breaker_opens")
        return notify

    @property
    def workdir(self):
        if self._workdir is None:
            self._workdir = tempfile.mkdtemp(prefix="mpisppy_router_")
        return self._workdir

    # -- lifecycle --------------------------------------------------------
    def start(self):
        with self._lock:
            if self._started or self._stopped:
                return self
            self._started = True
        self.replica_set.start()
        t = threading.Thread(target=self._monitor_main,
                             name="serve-router", daemon=True)
        self._monitor = t
        t.start()
        return self

    def shutdown(self, timeout=30.0):
        with self._lock:
            self._stopped = True
        m = self._monitor
        if m is not None and m.is_alive():
            m.join(timeout)
        self.replica_set.shutdown(timeout=timeout)
        with self._lock:
            for rreq in list(self._open.values()):
                self._resolve_locked(
                    rreq, rejected_result(rreq.rid, "shutdown"))

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.shutdown()

    # -- client API -------------------------------------------------------
    def submit(self, batch, options=None, scenario_names=None,
               deadline=None, model=None, tenant="default", priority=1,
               idempotency_key=None):
        """Enqueue one solve; returns a RouterHandle immediately.
        Rejections (over_quota, brownout_shed, shutdown, no_replica)
        are structured results, never exceptions or blocks.  A repeat
        `idempotency_key` returns the ORIGINAL request's handle — the
        dedup half of the exactly-once contract."""
        self.start()
        now = time.monotonic()
        with self._lock:
            if idempotency_key is not None \
                    and idempotency_key in self._idempotency:
                return RouterHandle(self._idempotency[idempotency_key])
            rid = next(self._rids)
            key = idempotency_key if idempotency_key is not None \
                else f"_auto{rid}"
            opts = dict(options or {})
            if self.brownout_level >= 2:
                opts = self._degrade_options(opts)
                self._count("degraded_requests")
            rreq = RouterRequest(
                rid=rid, key=key, batch=batch, options=opts,
                scenario_names=scenario_names, model=model,
                tenant=str(tenant), priority=int(priority),
                deadline=(now + float(deadline)) if deadline is not None
                else None,
                submitted=now)
            self._requests[rid] = rreq
            self._idempotency[key] = rid
            reason = None
            if self._stopped:
                reason = "shutdown"
            elif self.brownout_level >= 3 \
                    and rreq.priority < self.brownout_min_priority:
                reason = "brownout_shed"
                self._count("shed_requests")
            elif not self._admit_tenant(rreq.tenant, now):
                reason = "over_quota"
                self._count("over_quota")
            if reason is not None:
                self._resolve_locked(
                    rreq, rejected_result(rid, reason))
                return RouterHandle(rid)
            self._count("requests_submitted")
        # route BEFORE exposing the request to the monitor's scan: a
        # wire submit takes milliseconds, and a scan tick landing in
        # that window would see an empty handle list and "replay" a
        # request that was never routed — a duplicate execution
        self._route(rreq)
        with self._lock:
            if not rreq.done.is_set():
                self._open[rid] = rreq
        return RouterHandle(rid)

    def poll(self, handle):
        with self._lock:
            rreq = self._requests.get(handle.id)
            if rreq is None:
                return "unknown"
            if rreq.done.is_set():
                return rreq.status
        for replica, h in list(rreq.handles):
            if replica.poll(h) == RUNNING:
                return RUNNING
        return QUEUED

    def result(self, handle, timeout=None):
        """Block for the result — ALWAYS time-bounded, mirroring
        SolverService.result: by `timeout`, else the request deadline +
        grace, else router_result_timeout."""
        rreq = self._requests.get(handle.id)
        if rreq is None:
            return {"status": "unknown", "request_id": handle.id}
        if timeout is None:
            if rreq.deadline is not None:
                timeout = max(rreq.deadline - time.monotonic(), 0.0) \
                    + self.result_grace
            else:
                timeout = self.result_timeout
        if not rreq.done.wait(timeout):
            return {"status": TIMEOUT, "request_id": rreq.rid,
                    "where": "router_wait",
                    "wall_s": time.monotonic() - rreq.submitted}
        return rreq.result

    def solve(self, batch, options=None, **kwargs):
        timeout = kwargs.pop("timeout", None)
        h = self.submit(batch, options, **kwargs)
        return self.result(h, timeout=timeout)

    # -- admission --------------------------------------------------------
    def _admit_tenant(self, tenant, now):
        if self.tenant_rate is None:
            return True
        bucket = self._buckets.get(tenant)
        if bucket is None:
            bucket = self._buckets[tenant] = TokenBucket(
                self.tenant_rate, self.tenant_burst)
        return bucket.take(now)

    def _degrade_options(self, opts):
        """Brownout level >= 2: widen the solve tolerances of admitted
        requests.  convthresh scales by the brownout factor and the
        PR 4 eps-ladder is engaged with loose knobs — both are
        host-side / traced-eps paths, so the degraded request stays in
        the SAME compile bucket as its full-accuracy twin."""
        o = dict(opts)
        o["convthresh"] = (float(o.get("convthresh", 1e-4))
                           * self.brownout_conv_factor)
        lad = o.get("eps_ladder")
        lad = dict(lad) if isinstance(lad, dict) else {}
        lad.setdefault("start", 1e-2)
        lad.setdefault("min", 1e-4)
        lad.setdefault("couple", 0.2)
        o["eps_ladder"] = lad
        return o

    # -- routing ----------------------------------------------------------
    def _pick_slot(self, exclude=()):
        """Deadline-aware least-loaded routing over allowed slots:
        breakers gate admission per slot, then the shallowest
        queue+inflight wins (the request waits the least there).
        Equal loads round-robin via a rotating scan offset — a fixed
        tie-break would dump every burst on slot 0, and uneven splits
        dispatch odd-width groups downstream (each width its own
        trace)."""
        now = time.monotonic()
        n = len(self.replica_set)
        with self._lock:
            off = self._rr_offset
            self._rr_offset = (off + 1) % max(n, 1)
        best, best_load = None, None
        for i in range(n):
            slot = (off + i) % n
            replica = self.replica_set[slot]
            if slot in exclude or replica.condemned or replica.failed:
                continue
            if not self.breakers[slot].allow(now):
                continue
            h = replica.health()
            load = h["queue_depth"] + h["inflight"]
            if best is None or load < best_load:
                best, best_load = slot, load
        return best

    def _route(self, rreq, exclude=(), hedge=False):
        """Submit (or resubmit) a router request to a replica.  Returns
        True when a slot accepted it; False leaves the request with its
        existing handles (the monitor retries next tick or resolves).
        Hedges do NOT consume the attempt budget — `attempts` bounds
        failure-driven replays (the ping-pong guard), and charging
        hedges against it would make an innocent request that hedged
        once unreplayable after a single crash-victim failure."""
        slot = self._pick_slot(exclude)
        if slot is None:
            return False
        replica = self.replica_set[slot]
        inner_deadline = None
        if rreq.deadline is not None:
            inner_deadline = max(rreq.deadline - time.monotonic(), 0.01)
        h = replica.submit(rreq.batch, rreq.options,
                           scenario_names=rreq.scenario_names,
                           deadline=inner_deadline, model=rreq.model)
        with self._lock:
            if not hedge:
                rreq.attempts += 1
            rreq.handles.append((replica, h))
            replica.assigned[h.id] = rreq.rid
        self._tel.event("router.route", request=rreq.rid,
                        replica=replica.name, hedge=hedge)
        return True

    # -- completion -------------------------------------------------------
    def _resolve_locked(self, rreq, res, replica=None):
        if rreq.done.is_set():
            self._count("duplicate_completions")
            return False
        res = dict(res)
        res["request_id"] = rreq.rid
        res["router_wall_s"] = time.monotonic() - rreq.submitted
        if replica is not None:
            res["replica"] = replica.name
        rreq.result = res
        rreq.status = res["status"]
        rreq.done.set()
        self._open.pop(rreq.rid, None)
        if rreq.handles:
            # hedge twins may still complete later: keep watching them
            # so duplicate completions are observed and counted
            self._lingering[rreq.rid] = rreq
        self._count(f"requests_{res['status']}")
        if res["status"] == OK:
            self.latencies.append(res["router_wall_s"])
        self._tel.event("router.done", request=rreq.rid,
                        status=res["status"])
        return True

    def _resolve(self, rreq, res, replica=None):
        with self._lock:
            return self._resolve_locked(rreq, res, replica)

    # -- monitor thread ---------------------------------------------------
    def _monitor_main(self):
        while True:
            with self._lock:
                if self._stopped:
                    return
            try:
                now = time.monotonic()
                self._probe_replicas(now)
                self._scan_requests(now)
                self._eval_brownout(now)
            except Exception as exc:   # pragma: no cover - belt+braces
                global_toc(f"WARNING: router monitor error: {exc!r}")
                self._tel.event("router.monitor_error", error=repr(exc))
            time.sleep(self.tick_interval)

    def _probe_replicas(self, now):
        if now - self._last_probe < self.probe_interval:
            return
        self._last_probe = now
        live = 0
        for slot, replica in enumerate(self.replica_set):
            br = self.breakers[slot]
            br.allow(now)              # advance open -> half_open
            if replica.condemned:
                continue
            h = replica.health()
            self._attribute_crashes(replica, h["crash_suspects"])
            self._note_starvation(replica, h)
            if h["failed"] is not None:
                br.trip(now)
                self._replace_slot(slot, reason=h["failed"])
                continue
            live += 1
            unhealthy = (
                h["queue_depth"] > self.breaker_queue_depth
                or (h["queue_depth"] > 0
                    and h["last_dispatch_age"] > self.breaker_stall_s))
            if unhealthy:
                br.record_failure(now)
            else:
                br.record_success(now)
            if h["queue_depth"] > 0 \
                    and h["last_dispatch_age"] > self.replace_stall_s:
                br.trip(now)
                self._replace_slot(
                    slot, reason=f"stalled {h['last_dispatch_age']:.1f}s")
        self._tel.gauge("router.replicas_live").set(live)

    def _note_starvation(self, replica, h):
        """Roll each replica's DRR rotation count (service-side
        `bucket_starvation`: dispatches where a colder bucket jumped
        the queue head) into the router-level `bucket_starvation`
        counter — deltas per replica NAME, so a replacement's fresh
        zero doesn't rewind the aggregate."""
        cur = int(h.get("bucket_starvation", 0) or 0)
        prev = self._starvation_seen.get(replica.name, 0)
        if cur > prev:
            self._count("bucket_starvation", cur - prev)
        self._starvation_seen[replica.name] = max(cur, prev)

    def _attribute_crashes(self, replica, suspects):
        """Feed a replica's crash_suspects (inner ids whose OWN
        execution killed the worker — service-side precise attribution)
        into router-request crash counts; a request charged with
        `poison_budget` crashes is quarantined: resolved with a
        structured failure, never hedged or replayed again."""
        with self._lock:
            seen = self._suspects_seen.setdefault(replica.name, set())
            fresh = set(suspects) - seen
            seen |= fresh
            for inner_id in fresh:
                rid = replica.assigned.get(inner_id)
                rreq = self._open.get(rid)
                if rreq is None:
                    continue
                rreq.crash_count += 1
                if rreq.crash_count >= self.poison_budget:
                    self._count("quarantined")
                    self._tel.event("router.quarantine", request=rid,
                                    crashes=rreq.crash_count)
                    self._resolve_locked(rreq, failed_result(
                        rid, "quarantined: this request's own "
                             f"execution crashed {rreq.crash_count} "
                             "worker(s) (poison budget "
                             f"{self.poison_budget})"))

    def _replace_slot(self, slot, reason=""):
        """The corpse path: quarantine poison suspects, drain the dead
        replica (leftovers checkpointed), start a fresh incarnation
        warmed from the checkpoint, adopt the warmed handles, and let
        the scan replay whatever is left without a live handle.
        Serialized across threads (monitor vs. roll()): a slot already
        condemned by the other caller is skipped, not replaced twice."""
        with self._replace_lock:
            return self._replace_slot_locked(slot, reason)

    def _replace_slot_locked(self, slot, reason=""):
        corpse = self.replica_set[slot]
        if corpse.condemned:
            return
        corpse.condemned = True
        self._tel.event("router.replica_down", slot=slot,
                        replica=corpse.name, reason=str(reason)[:500])
        global_toc(f"WARNING: router replacing replica {corpse.name}: "
                   f"{reason}")
        # poison attribution BEFORE replay: a quarantined request is
        # resolved here and never reaches the warm_from/re-route path
        self._attribute_crashes(corpse, corpse.health()["crash_suspects"])
        ckpt = os.path.join(self.workdir,
                            f"drain_{corpse.name}")
        fresh, drain_info, adopted = self.replica_set.replace(
            slot, drain_deadline=self.drain_deadline,
            checkpoint_path=ckpt)
        self._count("replica_restarts")
        self._tel.event("router.replica_replaced", slot=slot,
                        corpse=corpse.name, fresh=fresh.name,
                        drained=drain_info.get("drained", 0),
                        adopted=len(adopted))
        with self._lock:
            # re-bind warm_from resubmissions to their router requests
            for old_inner_id, new_h in adopted:
                rid = corpse.assigned.get(old_inner_id)
                rreq = self._open.get(rid)
                if rreq is None:
                    continue
                rreq.handles.append((fresh, new_h))
                fresh.assigned[new_h.id] = rid
            # drop every corpse handle; requests left bare get
            # re-routed by the scan (the replay half of exactly-once)
            for rreq in list(self._open.values()):
                rreq.handles = [(r, h) for r, h in rreq.handles
                                if r is not corpse]

    def roll(self, reason="rolling_restart", on_slot=None):
        """Zero-downtime rolling restart: condemn ONE slot at a time
        through the replace-and-replay machinery while the peers absorb
        traffic — in-flight requests on the condemned replica survive
        via warm_from adoption, bare-handle replay, and the idempotency
        table (re-submission of an already-rolled key returns the
        original handle).  Waits for each fresh incarnation to report
        healthy before condemning the next peer, so the set is never
        more than one replica down.  Returns the number of replicas
        replaced; counts `rolled_replicas` per slot."""
        self.start()
        rolled = 0
        for slot in range(len(self.replica_set)):
            self._replace_slot(slot, reason=reason)
            fresh = self.replica_set[slot]
            end = time.monotonic() + self.drain_deadline + 10.0
            while time.monotonic() < end:
                if fresh.health()["failed"] is None:
                    break
                time.sleep(self.tick_interval)
            rolled += 1
            self._count("rolled_replicas")
            self._tel.event("router.rolled_slot", slot=slot,
                            fresh=fresh.name)
            if on_slot is not None:
                on_slot(slot, fresh.name)
        return rolled

    def _scan_requests(self, now):
        with self._lock:
            open_reqs = list(self._open.values())
            lingering = list(self._lingering.values())
        for rreq in open_reqs:
            if rreq.done.is_set():
                continue
            self._scan_one(rreq, now)
        for rreq in lingering:
            self._scan_lingering(rreq)

    def _scan_one(self, rreq, now):
        for replica, h in list(rreq.handles):
            res = replica.peek(h)
            if res is None:
                continue
            st = res["status"]
            if st in (OK, TIMEOUT):
                if self._resolve(rreq, res, replica):
                    self.breakers[replica.slot].record_success(now)
                return
            # FAILED / REJECTED from a condemned replica: the
            # replacement path owns the replay — just drop the handle
            with self._lock:
                rreq.handles.remove((replica, h))
            if replica.condemned or replica.failed:
                continue
            if st == FAILED:
                self.breakers[replica.slot].record_failure(now)
            if rreq.attempts >= self.max_attempts:
                self._resolve(rreq, res, replica)
                return
        # deadline sweep: a request whose clock ran out while bouncing
        # between replicas resolves here instead of spinning forever
        if rreq.expired(now):
            self._resolve(rreq, {"status": TIMEOUT,
                                 "request_id": rreq.rid,
                                 "where": "router_deadline"})
            return
        if not rreq.handles:
            # replay: no live handle (replica died, or a healthy
            # replica failed/rejected it and attempts remain)
            if rreq.crash_count >= self.poison_budget:
                self._resolve(rreq, failed_result(
                    rreq.rid, "quarantined"))
                return
            if rreq.attempts >= self.max_attempts:
                self._resolve(rreq, failed_result(
                    rreq.rid, f"no replica could complete the request "
                              f"in {rreq.attempts} attempts"))
                return
            if self._route(rreq):
                self._count("replayed_requests")
            return
        self._maybe_hedge(rreq, now)

    def _maybe_hedge(self, rreq, now):
        if self.hedge_threshold is None or rreq.hedged \
                or len(rreq.handles) != 1 \
                or now - rreq.submitted <= float(self.hedge_threshold):
            return
        if self.brownout_level >= 1:
            if not rreq.hedge_shed:
                rreq.hedge_shed = True
                self._count("shed_hedges")
            return
        used = {replica.slot for replica, _ in rreq.handles}
        if self._route(rreq, exclude=used, hedge=True):
            rreq.hedged = True
            self._count("hedged_requests")

    def _scan_lingering(self, rreq):
        """Watch a resolved request's leftover hedge twins so duplicate
        completions are observed (and only counted, never delivered)."""
        for replica, h in list(rreq.handles):
            status = replica.poll(h)
            if status in (QUEUED, RUNNING):
                continue
            with self._lock:
                rreq.handles.remove((replica, h))
                if status == OK:
                    self._count("duplicate_completions")
        if not rreq.handles:
            with self._lock:
                self._lingering.pop(rreq.rid, None)

    # -- brownout ladder --------------------------------------------------
    def _eval_brownout(self, now):
        if now - self._last_brownout_eval < self.brownout_interval:
            return
        self._last_brownout_eval = now
        live = sum(1 for r in self.replica_set
                   if not (r.condemned or r.failed))
        capacity = max(1, live) * self.max_inflight
        with self._lock:
            load = len(self._open)
        frac = load / capacity
        if frac >= self.brownout_high:
            self._brownout_streak = max(1, self._brownout_streak + 1)
        elif frac <= self.brownout_low:
            self._brownout_streak = min(-1, self._brownout_streak - 1)
        else:
            self._brownout_streak = 0
        new = self.brownout_level
        if self._brownout_streak >= self.brownout_sustain \
                and self.brownout_level < 3:
            new = self.brownout_level + 1
        elif self._brownout_streak <= -self.brownout_sustain \
                and self.brownout_level > 0:
            new = self.brownout_level - 1
        if new != self.brownout_level:
            old, self.brownout_level = self.brownout_level, new
            self._brownout_streak = 0
            self.brownout_transitions.append((new, now))
            self._tel.event("router.brownout", old=old, new=new,
                            load_fraction=round(frac, 4))
            self._tel.gauge("router.brownout_level").set(new)
            global_toc(f"router brownout level {old} -> {new} "
                       f"(load {frac:.2f})")

    # -- introspection ----------------------------------------------------
    def latency_percentiles(self):
        """{p50, p99} over resolved-ok router wall times (None/None
        when nothing completed)."""
        with self._lock:
            lat = sorted(self.latencies)
        if not lat:
            return {"p50": None, "p99": None}
        def pct(p):
            i = min(len(lat) - 1, int(round(p * (len(lat) - 1))))
            return lat[i]
        return {"p50": pct(0.50), "p99": pct(0.99)}

    def _cache_stats_dicts(self):
        """Per-replica CompileCache stats through the duck-typed
        `cache_stats()` surface — works for thread replicas (a direct
        stats() call) and process replicas (the last health-reported
        dict) alike; a replica without the surface contributes
        nothing."""
        out = []
        for r in self.replica_set:
            fn = getattr(r, "cache_stats", None)
            if fn is None:
                continue
            try:
                out.append(fn())
            except Exception:          # pragma: no cover - dead worker
                out.append({})
        return out

    def stats(self):
        """One structured snapshot for tests / bench: counters,
        breaker state machines, brownout history, latencies."""
        from .compile_cache import merged_stats_dicts
        with self._lock:
            counts = dict(self.counts)
        extra = {}
        boot = getattr(self.replica_set, "boot_stats", None)
        if boot is not None:
            extra = boot()
        return {
            "counts": counts,
            "compile_cache": merged_stats_dicts(
                self._cache_stats_dicts()),
            "breakers": [{"slot": i, "state": b.state,
                          "opens": b.opens,
                          "states_seen": b.states_seen()}
                         for i, b in enumerate(self.breakers)],
            "brownout_level": self.brownout_level,
            "brownout_transitions": list(self.brownout_transitions),
            "replica_restarts": self.replica_set.replacements,
            "replicas": [r.name for r in self.replica_set],
            "replica_mode": self.options.get("serve_replica_mode",
                                             "thread"),
            **extra,
            **self.latency_percentiles(),
        }

"""SolverService — a persistent in-process solver service.

The one-shot batch-job shape (`WheelSpinner` / driver scripts) pays
backend init + XLA compiles per invocation and exits.  This service
keeps the process (and its jit caches + AOT executables) alive and
feeds it a queue of solve requests:

  client --submit()--> bounded queue --dispatch thread--> PH solves
          <--handle--                                      |
          <--poll/result (structured, never hangs) --------+

Dispatch (`_next_group`) pops the oldest request and COALESCES every
queued request in the same shape bucket (compile_cache.bucket_key)
with it, up to `serve_max_batch`.  A group of one runs exactly the
standalone `PH` path — the identical lowered superstep computation
`PH.ph_main` runs, so the result is bitwise identical (the api.py
parity guarantee).  A larger group runs Iter0 per request, then drives ALL
requests through ONE vmap-batched AOT superstep executable in
lockstep, swapping each finished request's state out on the host while
the rest keep iterating (finished elements keep computing inside the
batch — wasted lanes, bounded by `serve_max_batch`, the price of one
dispatch per iteration for the whole group).

Supervision mirrors resilience.SpokeSupervisor, adapted to a thread
worker: a crash (including injected `ChaosError` via
`options["chaos"]` — each dispatched group is one chaos "step", and
`crash_at_iter` counts dispatches) requeues the in-flight requests
(per-request attempt budget), restarts the dispatch thread after the
shared capped-exponential `restart_delay`, and fails the whole service
once the restart budget is spent — every queued request then gets a
structured FAILED result, and later submits are rejected.  A HUNG
worker (chaos `hang_at_step`) is covered by per-request deadlines:
`result()` is always time-bounded.

Options (all prefixed `serve_`):
  serve_max_queue       queue capacity, rejects beyond       (256)
  serve_max_inflight    queued+running admission cap         (32)
  serve_max_batch       max coalesced requests per dispatch  (8)
  serve_coalesce_window_s  batch-forming hold (seconds) while a
                        short group's bucket is still filling (0)
  serve_default_deadline  per-request seconds (None = none)  (None)
  serve_result_timeout  result() wait when no deadline       (600)
  serve_result_grace    extra result() wait past deadline    (30)
  serve_max_attempts    executions per request before FAILED (2)
  serve_max_restarts    worker restarts before service FAILED(2)
  serve_restart_backoff / serve_restart_backoff_cap          (0.1/5)
plus the standard `telemetry` and `chaos` keys.

Metrics (doc/src/serve.md): serve.queue_depth gauge,
serve.batch_size / serve.request_seconds histograms,
serve.compile_cache.{hit,miss} / serve.requests.* /
serve.worker_restarts counters, serve.request + serve.dispatch spans.
"""

from __future__ import annotations

import itertools
import threading
import time
from collections import deque

from .. import global_toc
from .. import telemetry as _telemetry
from ..resilience.chaos import ChaosError, ChaosInjector
from ..resilience.supervisor import restart_delay
from . import compile_cache as _cc
from .request import (FAILED, OK, QUEUED, REJECTED, RUNNING, RequestHandle,
                      SolveRequest, failed_result, rejected_result,
                      timeout_result)

# Process-wide backend execution lock.  In-process replicas (the
# serve.replica.ReplicaSet) are separate fault domains but share ONE
# jax backend: two dispatch threads launching collective-bearing
# computations concurrently interleave their per-device executions and
# deadlock XLA's cross-module all-reduce rendezvous (each participant
# waits for device peers that are running the OTHER replica's
# computation).  Real deployments give each replica its own process or
# disjoint mesh slice (see mpisppy_tpu/mpmd/); in-process replica sets
# must serialize device execution instead — queueing, draining,
# health probing, and crash handling all stay concurrent.  Opt out
# with serve_backend_lock=False (single-replica deployments where the
# uncontended acquire is still ~free, or genuinely disjoint backends).
_BACKEND_LOCK = threading.Lock()


def stack_superstep_args(phs):
    """Stack N same-bucket PH instances' superstep arguments along a
    leading request axis: the 9 positional args of
    `phbase.ph_superstep`, each leaf gaining a B-long leading axis —
    exactly what `CompiledBucket.batched_superstep` lowers over.
    Module-level so the bench's cold-start A/B and the AOT tests can
    build example args without a running service."""
    import jax
    import jax.numpy as jnp

    dtype = phs[0].batch.c.dtype

    def stack(trees):
        # flatten/unflatten (NOT tree_map over multiple trees):
        # meta equality on model_meta numpy arrays is ill-defined,
        # but same-bucket treedefs are structurally identical
        flat = [jax.tree_util.tree_flatten(t) for t in trees]
        treedef = flat[0][1]
        return jax.tree_util.tree_unflatten(
            treedef,
            [jnp.stack(leaves) for leaves in
             zip(*[f[0] for f in flat])])

    return (
        stack([ph.state for ph in phs]),
        jnp.stack([ph.rho for ph in phs]),
        jnp.asarray([ph.W_on for ph in phs], dtype),
        jnp.asarray([ph.prox_on for ph in phs], dtype),
        jnp.stack([ph.lb_eff for ph in phs]),
        jnp.stack([ph.ub_eff for ph in phs]),
        jnp.stack([jnp.asarray(ph.superstep_eps, dtype)
                   for ph in phs]),
        stack([ph.prep for ph in phs]),
        stack([ph.batch for ph in phs]),
    )


class SolverService:
    def __init__(self, options=None, cache=None):
        o = dict(options or {})
        self.options = o
        self.max_queue = int(o.get("serve_max_queue", 256))
        self.max_inflight = int(o.get("serve_max_inflight", 32))
        self.max_batch = int(o.get("serve_max_batch", 8))
        self.coalesce_window = float(
            o.get("serve_coalesce_window_s", 0.0) or 0.0)
        self.default_deadline = o.get("serve_default_deadline")
        self.result_timeout = float(o.get("serve_result_timeout", 600.0))
        self.result_grace = float(o.get("serve_result_grace", 30.0))
        self.max_attempts = int(o.get("serve_max_attempts", 2))
        self.max_restarts = int(o.get("serve_max_restarts", 2))
        self.backoff = float(o.get("serve_restart_backoff", 0.1))
        self.backoff_cap = float(o.get("serve_restart_backoff_cap", 5.0))
        self._tel = _telemetry.configure_from_options(o.get("telemetry"))
        self._chaos = ChaosInjector.from_options(o.get("chaos"))
        self.cache = cache if cache is not None else _cc.CompileCache(
            self._tel)
        self._lock = threading.Lock()
        self._work = threading.Condition(self._lock)
        self._queue = deque()
        self._requests = {}           # id -> SolveRequest
        self._results = {}            # id -> result dict
        self._inflight = []           # requests popped, not yet finished
        self._processing = 0
        self._ids = itertools.count(1)
        self._dispatches = 0
        self._stopped = False
        self._draining = False        # drain(): admission closed
        self._failed = None           # terminal service failure reason
        self.restarts = 0
        self._worker = None
        self._started = time.monotonic()
        self.last_dispatch = None     # monotonic time of last dispatch
        # poison attribution: _executing names the ONE request whose
        # own per-request work (chaos tick, PH build, Iter0, single
        # solve) the worker is inside; a crash there is precisely that
        # request's fault and lands its id in crash_suspects (the
        # router's quarantine signal).  Crashes in group-wide phases
        # (batched lockstep, chaos step_tick) are ambiguous and charge
        # nobody — blaming the whole group would quarantine innocents.
        self._executing = None
        self.crash_suspects = set()
        # deficit round-robin across compile-cache buckets: the bucket
        # served by the previous dispatch group, and how many times the
        # DRR rotation had to pass over the queue head (the starvation-
        # averted signal the router aggregates)
        self._last_bucket = None
        self.bucket_starvation = 0
        self._backend_lock = (_BACKEND_LOCK
                              if o.get("serve_backend_lock", True)
                              else threading.Lock())

    # -- lifecycle --------------------------------------------------------
    def start(self):
        """Start the dispatch thread (idempotent).  Also wires jax's
        persistent compilation cache so a warm process restart skips
        XLA entirely (utils.platform.enable_compile_cache)."""
        with self._lock:
            if self._failed is not None:
                return self
            running = self._worker is not None and self._worker.is_alive()
        if not running:
            from ..utils.platform import enable_compile_cache
            enable_compile_cache()
            # bound the shared AOT artifact dir before this process
            # starts adding to it (no-op unless a limit is configured)
            _cc.prune_aot_dir(
                max_age_s=self.options.get("serve_aot_max_age_s"),
                max_total_bytes=self.options.get("serve_aot_max_bytes"))
            self._spawn_worker()
        return self

    def _spawn_worker(self):
        t = threading.Thread(target=self._worker_main,
                             name="serve-dispatch", daemon=True)
        self._worker = t
        t.start()

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.shutdown()

    def shutdown(self, timeout=60.0):
        """Drain: the worker finishes the queue, then exits.  Anything
        still queued after `timeout` is rejected."""
        with self._work:
            self._stopped = True
            self._work.notify_all()
        w = self._worker
        if w is not None and w.is_alive():
            w.join(timeout)
        with self._lock:
            for req in list(self._queue):
                self._finish_locked(req, rejected_result(req.id, "shutdown"))
            self._queue.clear()

    def drain(self, deadline=30.0, checkpoint_path=None):
        """Graceful drain: close admission immediately (submits reject
        with reason "draining"), let the worker flush the queue and
        in-flight work for up to `deadline` seconds, then stop it and
        checkpoint whatever could not finish (resilience/checkpoint.py
        drain format) so a restarted service `warm_from()`s the file
        and resubmits.  Leftover requests get a structured
        rejected("drained") result — never a hang."""
        with self._work:
            self._draining = True
            self._work.notify_all()
        self._tel.event("serve.drain", deadline=deadline)
        end = time.monotonic() + float(deadline)
        while time.monotonic() < end:
            with self._lock:
                if not self._queue and not self._inflight:
                    break
            time.sleep(0.02)
        with self._work:
            self._stopped = True
            self._work.notify_all()
        w = self._worker
        if w is not None and w.is_alive():
            w.join(max(0.0, end - time.monotonic()) + 1.0)
        with self._lock:
            leftovers = list(self._queue) + list(self._inflight)
            self._queue.clear()
        saved = None
        if checkpoint_path is not None and leftovers:
            import jax
            import numpy as np

            from ..resilience.checkpoint import save_drain_checkpoint
            saved = save_drain_checkpoint(checkpoint_path, [
                {"id": req.id, "options": dict(req.options),
                 "scenario_names": req.scenario_names,
                 "model": req.model,
                 # device buffers do not pickle: host-round-trip leaves
                 "batch": jax.tree_util.tree_map(np.asarray, req.batch)}
                for req in leftovers])
            global_toc(f"serve: drained {len(leftovers)} request(s) to "
                       f"{saved}")
        for req in leftovers:
            self._finish(req, rejected_result(req.id, "drained"))
        self._tel.event("serve.drained", leftovers=len(leftovers),
                        checkpoint=str(saved))
        return {"drained": len(leftovers), "checkpoint": saved}

    def warm_from(self, path):
        """Resubmit the requests a previous incarnation drained to
        `path` (in their original submission order).  Returns a list of
        (saved_request_id, RequestHandle) pairs; saved deadlines are
        NOT carried over (absolute monotonic clocks do not survive a
        restart).

        A corrupted / truncated / wrong-format checkpoint produces a
        STRUCTURED error dict ({"status": "failed", "reason":
        "corrupt_drain_checkpoint", ...}) instead of an exception, and
        the whole file is validated BEFORE the first resubmit — a bad
        entry can never leave the service half-warmed.  The service
        keeps accepting either way."""
        from ..resilience.checkpoint import load_drain_checkpoint
        try:
            saved = load_drain_checkpoint(path)
        except Exception as exc:
            self._tel.event("serve.warm_from_rejected", path=str(path),
                            error=repr(exc))
            global_toc(f"WARNING: serve warm_from rejected {path}: "
                       f"{exc!r}")
            return {"status": FAILED,
                    "reason": "corrupt_drain_checkpoint",
                    "path": str(path), "error": repr(exc)}
        # validate every entry up front: raising mid-resubmit would
        # warm an arbitrary prefix and lose the rest
        required = ("id", "batch", "options", "scenario_names", "model")
        for pos, d in enumerate(saved):
            missing = [k for k in required
                       if not isinstance(d, dict) or k not in d]
            if missing:
                self._tel.event("serve.warm_from_rejected",
                                path=str(path), entry=pos)
                return {"status": FAILED,
                        "reason": "corrupt_drain_checkpoint",
                        "path": str(path),
                        "error": f"entry {pos} missing keys {missing}"}
        self.start()
        handles = []
        for d in saved:
            h = self.submit(d["batch"], options=d["options"],
                            scenario_names=d["scenario_names"],
                            model=d["model"])
            handles.append((d["id"], h))
        self._tel.event("serve.warm_from", path=str(path),
                        requests=len(handles))
        return handles

    # -- client API -------------------------------------------------------
    def submit(self, batch, options=None, scenario_names=None,
               deadline=None, model=None):
        """Enqueue one solve; returns a RequestHandle immediately.
        Admission control rejects (structured result, status
        "rejected") instead of blocking: full queue, inflight cap, a
        failed service, or a shut-down service."""
        now = time.monotonic()
        dl = deadline if deadline is not None else self.default_deadline
        with self._work:
            req = SolveRequest(
                id=next(self._ids), batch=batch,
                options=dict(options or {}),
                scenario_names=scenario_names, model=model,
                deadline=(now + float(dl)) if dl is not None else None,
                submitted=now)
            self._requests[req.id] = req
            reason = None
            if self._failed is not None:
                reason = "service_failed"
            elif self._stopped:
                reason = "shutdown"
            elif self._draining:
                reason = "draining"
            elif len(self._queue) >= self.max_queue:
                reason = "queue_full"
            elif len(self._queue) + self._processing >= self.max_inflight:
                reason = "max_inflight"
            if reason is not None:
                self._finish_locked(req, rejected_result(req.id, reason))
                return RequestHandle(req.id)
            self._queue.append(req)
            self._tel.counter("serve.requests.submitted").inc()
            self._tel.gauge("serve.queue_depth").set(len(self._queue))
            self._tel.event("serve.submit", request=req.id)
            self._work.notify()
        return RequestHandle(req.id)

    def poll(self, handle):
        """Current status string for the handle ("unknown" for an id
        this service never issued)."""
        with self._lock:
            req = self._requests.get(handle.id)
            return "unknown" if req is None else req.status

    def health(self):
        """One structured health snapshot — the router's probe input.
        `last_dispatch_age` is seconds since the worker last dispatched
        a group (since start() when it never has); a large age with a
        nonempty queue is the hang/slow signal, mirroring the wheel
        supervisor's write-id staleness heartbeat."""
        now = time.monotonic()
        with self._lock:
            ref = self.last_dispatch if self.last_dispatch is not None \
                else self._started
            return {
                "failed": self._failed,
                "draining": self._draining,
                "stopped": self._stopped,
                "queue_depth": len(self._queue),
                "inflight": len(self._inflight),
                "last_dispatch_age": now - ref,
                "restarts": self.restarts,
                "crash_suspects": set(self.crash_suspects),
                "bucket_starvation": self.bucket_starvation,
            }

    def result(self, handle, timeout=None):
        """Block for the result — ALWAYS time-bounded: by `timeout`,
        else by the request deadline + serve_result_grace, else by
        serve_result_timeout.  An expired wait returns a structured
        timeout snapshot WITHOUT finishing the request (a late
        completion still lands; ask again)."""
        req = self._requests.get(handle.id)
        if req is None:
            return {"status": "unknown", "request_id": handle.id}
        if timeout is None:
            if req.deadline is not None:
                timeout = max(req.deadline - time.monotonic(), 0.0) \
                    + self.result_grace
            else:
                timeout = self.result_timeout
        if not req.done.wait(timeout):
            return timeout_result(req, where="result_wait")
        return self._results[req.id]

    def solve(self, batch, options=None, scenario_names=None,
              deadline=None, timeout=None, model=None):
        """Synchronous convenience wrapper: submit + result.  On
        success the dict carries the same values `PH.ph_main` returns
        (PH.solution_dict keys)."""
        self.start()
        h = self.submit(batch, options, scenario_names=scenario_names,
                        deadline=deadline, model=model)
        return self.result(h, timeout=timeout)

    # -- completion bookkeeping -------------------------------------------
    def _finish_locked(self, req, res):
        if req.done.is_set():
            return
        if req.status == RUNNING:
            self._processing -= 1
        if req in self._inflight:
            self._inflight.remove(req)
        req.status = res["status"]
        self._results[req.id] = res
        req.done.set()
        self._tel.counter(f"serve.requests.{res['status']}").inc()
        self._tel.histogram("serve.request_seconds").observe(
            time.monotonic() - req.submitted)
        self._tel.event("serve.done", request=req.id,
                        status=res["status"])

    def _finish(self, req, res):
        with self._lock:
            self._finish_locked(req, res)

    # -- dispatch thread --------------------------------------------------
    def _worker_main(self):
        try:
            while True:
                group = self._next_group()
                if group is None:
                    return
                self._process_group(group)
        except Exception as exc:     # includes injected ChaosError
            self._on_worker_crash(exc)

    def _bucket(self, req):
        if req.bucket is None:
            req.bucket = _cc.bucket_key(req.batch, req.options,
                                        model=req.model)
        return req.bucket

    def _next_group(self):
        """Form the next dispatch group by deficit round-robin across
        compile-cache buckets: the queued buckets (in arrival order)
        form a ring, and each dispatch serves the bucket after the one
        served last — so a hot bucket streaming same-shape requests
        can't starve an interleaved cold one.  Within the chosen
        bucket, up to max_batch requests coalesce in arrival order;
        queue order is preserved for the rest.  Every rotation that
        passes over the queue head counts in `bucket_starvation` (one
        head-of-line wait averted).  Returns None only on drained
        shutdown."""
        with self._work:
            while True:
                now = time.monotonic()
                for req in [r for r in self._queue if r.expired(now)]:
                    self._queue.remove(req)
                    self._finish_locked(
                        req, timeout_result(req, where="queued"))
                if not self._queue:
                    if self._stopped:
                        return None
                    self._work.wait(0.25)
                    continue
                order = []
                for r in self._queue:
                    b = self._bucket(r)
                    if b not in order:
                        order.append(b)
                pick = order[0]
                if len(order) > 1 and self._last_bucket is not None \
                        and self._last_bucket in order:
                    # the ring: first queued bucket after the last-
                    # served one; a bucket no longer queued forfeits
                    # its slot and the turn falls back to the queue
                    # head
                    i = order.index(self._last_bucket)
                    pick = order[(i + 1) % len(order)]
                if self.coalesce_window > 0.0 and not self._stopped:
                    # batch-forming window: requests arriving one at a
                    # time (e.g. over the wire) would otherwise
                    # dispatch as odd-width groups, each width a fresh
                    # trace — hold a short group open until max_batch
                    # fills or the window (from the group head's
                    # arrival) expires
                    matching = [r for r in self._queue
                                if self._bucket(r) == pick]
                    if len(matching) < self.max_batch:
                        hold = (matching[0].submitted
                                + self.coalesce_window) - now
                        if hold > 0:
                            self._work.wait(min(hold, 0.25))
                            continue
                break
            if pick != order[0]:
                self.bucket_starvation += 1
                self._tel.counter("serve.bucket_starvation").inc()
            self._last_bucket = pick
            group = []
            rest = []
            while self._queue:
                r = self._queue.popleft()
                if len(group) < self.max_batch \
                        and self._bucket(r) == pick:
                    group.append(r)
                else:
                    rest.append(r)
            self._queue.extend(rest)
            for r in group:
                r.status = RUNNING
                self._inflight.append(r)
            self._processing += len(group)
            self._tel.gauge("serve.queue_depth").set(len(self._queue))
        return group

    def _process_group(self, group):
        self._dispatches += 1
        self.last_dispatch = time.monotonic()
        # chaos: each dispatched group is one "step" (crash/hang from
        # step N on, replica_crash from dispatch N on); crash_at_iter
        # counts dispatches and fires EXACTLY once — the
        # restart-and-recover test shape; slow_replica sleeps here
        self._chaos.pre_dispatch()
        self._chaos.step_tick()
        self._chaos.hub_iter_tick(self._dispatches)
        self._tel.histogram("serve.batch_size").observe(len(group))
        try:
            with self._tel.span("serve.dispatch", batch=len(group)):
                self._execute_group(group)
        except ChaosError:
            raise
        except Exception as exc:     # model/solver bug: fail the group,
            for req in group:        # keep the service alive
                self._finish(req, failed_result(req.id, repr(exc)))
        # no inflight cleanup here: _finish_locked removes each request
        # as it reaches a terminal state, and a ChaosError propagating
        # past this frame MUST leave the group in _inflight so the
        # crash handler can requeue it

    def _on_worker_crash(self, exc):
        global_toc(f"WARNING: serve dispatch worker crashed: {exc!r}")
        self._tel.event("serve.worker_crash", error=repr(exc))
        with self._lock:
            suspect = self._executing
            self._executing = None
            if suspect is not None:
                self.crash_suspects.add(suspect)
            for req in list(self._inflight):
                # the ATTEMPT budget is charged only to the request the
                # worker was executing (the precise suspect) — innocents
                # coalesced into the group requeue freely; the restart
                # budget still bounds total crashes either way
                if req.id == suspect:
                    req.attempts += 1
                if req.attempts >= self.max_attempts:
                    self._finish_locked(req, failed_result(
                        req.id, f"worker crashed ({exc!r}) and the "
                                f"attempt budget ({self.max_attempts}) "
                                f"is spent", attempts=req.attempts))
                else:
                    self._processing -= 1
                    req.status = QUEUED
                    self._inflight.remove(req)
                    self._queue.appendleft(req)
            exhausted = self.restarts >= self.max_restarts
            if exhausted:
                self._failed = (f"worker crashed {self.restarts + 1} "
                                f"times (restart budget "
                                f"{self.max_restarts}): {exc!r}")
                for req in list(self._queue):
                    self._finish_locked(
                        req, failed_result(req.id, self._failed))
                self._queue.clear()
            else:
                self.restarts += 1
        if exhausted:
            self._tel.event("serve.worker_prune", error=repr(exc))
            global_toc(f"WARNING: serve service FAILED: {self._failed}")
            return
        delay = restart_delay(self.restarts, self.backoff,
                              self.backoff_cap)
        self._tel.counter("serve.worker_restarts").inc()
        self._tel.event("serve.worker_restart", incarnation=self.restarts,
                        delay=delay)
        global_toc(f"WARNING: serve worker restart "
                   f"{self.restarts}/{self.max_restarts} in {delay:.2f}s")
        time.sleep(delay)
        with self._lock:
            if self._stopped:
                return
        self._spawn_worker()

    # -- execution --------------------------------------------------------
    def _build_ph(self, req):
        from ..opt.ph import PH
        names = req.scenario_names
        if names is None:
            names = [f"scen{i}" for i in range(req.batch.num_scens)]
        return PH(dict(req.options), list(names), batch=req.batch)

    def _execute_group(self, group):
        # serialize device execution across in-process services that
        # share one jax backend (see _BACKEND_LOCK above); a crash
        # (ChaosError, poison) unwinding through here releases it
        with self._backend_lock:
            self._execute_group_locked(group)

    def _execute_group_locked(self, group):
        live = []
        for req in group:
            if req.expired():
                self._finish(req, timeout_result(req, where="dispatch"))
                continue
            self._executing = req.id   # precise poison attribution
            # poison_request chaos: raising HERE (not inside the try)
            # makes the poison a worker crash — exactly what a
            # deterministically-lethal request does to a real replica
            self._chaos.request_tick(req.options)
            try:
                with self._tel.span("serve.request", request=req.id):
                    ph = self._build_ph(req)
                    engine = self.cache.get(req.batch, req.options,
                                            model=req.model)
                    ph.Iter0()
            except Exception as exc:  # e.g. certified-infeasible iter0
                self._finish(req, failed_result(req.id, repr(exc)))
                continue
            finally:
                self._executing = None
            live.append((req, ph))
        if not live:
            return
        if len(live) == 1:
            req, ph = live[0]
            self._executing = req.id
            try:
                self._run_single(req, ph)
            finally:
                self._executing = None
        else:
            # batched lockstep: a crash here is ambiguous (every
            # request is executing) — charge nobody
            self._run_batched(live, engine)

    def _run_single(self, req, ph):
        """One request: the standalone PH path itself.  `iterk_loop`
        drives the fused superstep — the identical lowered computation
        `PH.ph_main` runs — so this result is bitwise equal to a
        standalone run (parity test in tests/test_serve.py).  A
        deadline swaps in an equivalent loop with a per-iteration
        clock check."""
        if req.deadline is None:
            ph.iterk_loop()
        else:
            max_iters = int(ph.options.get("PHIterLimit", 100))
            convthresh = float(ph.options.get("convthresh", 1e-4))
            for k in range(int(ph.state.it) + 1, max_iters + 1):
                if req.expired():
                    self._finish(req, timeout_result(
                        req, where="iteration",
                        iterations=int(ph.state.it), conv=ph.conv))
                    return
                if ph.ph_iteration() < convthresh:
                    break
        self._finish_ok(req, ph)

    def _run_batched(self, live, engine):
        """Coalesced same-bucket requests in one vmap-batched AOT
        superstep executable, lockstep; each request leaves the batch
        (host-side state capture) at ITS stopping iteration."""
        import jax
        import numpy as np

        reqs = [req for req, _ in live]
        phs = [ph for _, ph in live]

        def unstack(tree, i):
            leaves, treedef = jax.tree_util.tree_flatten(tree)
            return jax.tree_util.tree_unflatten(
                treedef, [leaf[i] for leaf in leaves])

        args = stack_superstep_args(phs)
        exe = engine.batched_superstep(args)
        state, rest = args[0], args[1:]
        limits = [int(ph.options.get("PHIterLimit", 100)) for ph in phs]
        threshes = [float(ph.options.get("convthresh", 1e-4))
                    for ph in phs]
        iters = [int(ph.state.it) for ph in phs]
        active = set(range(len(phs)))
        while active:
            state = exe(state, *rest)
            jax.block_until_ready(state.conv)
            convs = np.asarray(state.conv)
            now = time.monotonic()
            for i in sorted(active):
                iters[i] += 1
                req, ph = reqs[i], phs[i]
                if convs[i] < threshes[i] or iters[i] >= limits[i]:
                    ph.state = unstack(state, i)
                    ph.conv = float(convs[i])
                    active.discard(i)
                    self._finish_ok(req, ph)
                elif req.deadline is not None and now > req.deadline:
                    active.discard(i)
                    self._finish(req, timeout_result(
                        req, where="iteration", iterations=iters[i],
                        conv=float(convs[i])))

    def _finish_ok(self, req, ph):
        res = ph.solution_dict()
        res["status"] = OK
        res["request_id"] = req.id
        res["wall_s"] = time.monotonic() - req.submitted
        self._finish(req, res)

"""SPBase — scenario manager (reference: mpisppy/spbase.py, 651 LoC).

Owns the lowered ScenarioBatch, its placement on the device mesh, and
the bookkeeping the reference does rank-locally: probability
normalization checks (spbase.py:457-502), nonant bookkeeping
(spbase.py:293-330), solution gathering/writing (spbase.py:547-651).

Scenario construction: either a fast vectorized `batch` is passed in
directly, or the per-scenario `scenario_creator` contract is honored
(reference spbase.py:255-273) and the results stacked.
"""

from __future__ import annotations

import csv
import os

import jax.numpy as jnp
import numpy as np

from . import global_toc
from .ir import ScenarioBatch, stack_scenarios
from .parallel.mesh import ScenarioMesh


class SPBase:
    # algorithms that index A by scenario (MIP dive, L-shaped cuts,
    # Schur-complement assembly) set this; SPBase then materializes the
    # per-scenario view of a shared-A batch (ir.ScenarioBatch.densify)
    # once at construction instead of each subclass repeating the guard
    _needs_dense_A = False

    def __init__(
        self,
        options,
        all_scenario_names,
        scenario_creator=None,
        scenario_denouement=None,
        all_nodenames=None,
        scenario_creator_kwargs=None,
        variable_probability=None,
        batch: ScenarioBatch | None = None,
        mesh: ScenarioMesh | None = None,
    ):
        self.options = dict(options or {})
        self.all_scenario_names = list(all_scenario_names)
        self.all_nodenames = all_nodenames  # multistage tree metadata
        self.scenario_creator = scenario_creator
        self.scenario_denouement = scenario_denouement
        self.scenario_creator_kwargs = scenario_creator_kwargs or {}
        self.mesh = mesh if mesh is not None else ScenarioMesh()

        if batch is None:
            if scenario_creator is None:
                raise ValueError("need either a batch or a scenario_creator")
            global_toc(f"Creating {len(self.all_scenario_names)} scenarios")
            scens = [
                scenario_creator(name, **self.scenario_creator_kwargs)
                for name in self.all_scenario_names
            ]
            batch = stack_scenarios(scens, scen_names=self.all_scenario_names)
        if self._needs_dense_A and (batch.shared_A or batch.split_A):
            batch = batch.densify()   # raises MemoryError at sizes
            # where a dense per-scenario A cannot exist (split-native)
        self.n_real_scens = len(self.all_scenario_names)
        if variable_probability is not None:
            # per-(scenario, nonant-slot) averaging weights (reference
            # spbase.py:394 _mpisppy_variable_probability): an (S, K)
            # array, or a callable batch -> (S, K)
            import dataclasses

            vp = (variable_probability(batch)
                  if callable(variable_probability)
                  else variable_probability)
            vp = jnp.asarray(np.asarray(vp), batch.c.dtype)
            if vp.shape != (batch.num_scens, batch.num_nonants):
                raise ValueError(
                    f"variable_probability must be (S, K) = "
                    f"({batch.num_scens}, {batch.num_nonants}), "
                    f"got {vp.shape}")
            batch = dataclasses.replace(batch, var_prob=vp)
        self.batch = self.mesh.shard_batch(batch)
        self._verify_probabilities()
        # sense: IR is always minimize (model.py negates for maximize);
        # reference analog spbase.py:122 _set_sense
        self.is_minimizing = True
        global_toc(
            f"SPBase: {self.n_real_scens} scenarios "
            f"(padded to {self.batch.num_scens}) x "
            f"{self.batch.num_vars} vars x {self.batch.num_rows} rows, "
            f"{self.batch.num_nonants} nonants, "
            f"{self.mesh.size} device(s)")

    # -- integrity checks (reference spbase.py:150-175, :457-502) ---------
    def _verify_probabilities(self):
        tot = float(jnp.sum(self.batch.prob))
        if abs(tot - 1.0) > 1e-6:
            raise RuntimeError(
                f"scenario probabilities sum to {tot}, not 1 "
                "(reference hard-quits here too, spbase.py:470)")
        if self.batch.var_prob is not None:
            # reference warns when per-variable probabilities don't sum
            # to 1 within a node (_check_variable_probabilities_sum,
            # spbase.py:457-502)
            from .ir import node_segment_sum
            tree = self.batch.tree
            _, segsum = node_segment_sum(tree.node_of, tree.num_nodes)
            sums = segsum(self.batch.var_prob)
            bad = jnp.max(jnp.abs(sums - 1.0))
            if float(bad) > 1e-6:
                global_toc(
                    f"WARNING: variable_probability sums deviate from 1 "
                    f"by up to {float(bad):.3g} within a node "
                    "(reference warns here too, spbase.py:483)")

    # -- gathering / reporting (reference spbase.py:547-651) --------------
    def gather_var_values_to_rank0(self, x=None):
        """Return {(scen_name, var_name): value} for nonant variables.
        Single-controller JAX: every host sees the global value; the MPI
        gather disappears."""
        if x is None:
            raise ValueError("pass the (S, N) primal solution")
        xn = np.asarray(self.batch.nonants(x))[: self.n_real_scens]
        names = self.batch.tree.nonant_names
        out = {}
        for si, sname in enumerate(self.all_scenario_names):
            for vi, vname in enumerate(names):
                out[(sname, vname)] = float(xn[si, vi])
        return out

    def report_var_values_at_rank0(self, x, max_vars=20):
        vals = self.gather_var_values_to_rank0(x)
        for k, v in list(vals.items())[:max_vars]:
            print(f"{k[0]:>12s} {k[1]:>28s} {v:12.4f}")

    def write_first_stage_solution(self, path, xbar_root):
        """CSV of root-node consensus values (reference spbase.py:618)."""
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        names = self.batch.tree.nonant_names
        arr = np.asarray(xbar_root)
        with open(path, "w", newline="") as f:
            w = csv.writer(f)
            for name, v in zip(names, arr.tolist()):
                w.writerow([name, v])
        global_toc(f"Wrote first-stage solution to {path}")

    def write_tree_solution(self, directory, x):
        """Per-scenario CSVs of all variables (reference spbase.py:633)."""
        os.makedirs(directory, exist_ok=True)
        xa = np.asarray(x)[: self.n_real_scens]
        for si, sname in enumerate(self.all_scenario_names):
            with open(os.path.join(directory, f"{sname}.csv"), "w",
                      newline="") as f:
                w = csv.writer(f)
                for vi, vname in enumerate(self.batch.var_names
                                           or range(xa.shape[1])):
                    w.writerow([vname, float(xa[si, vi])])
        global_toc(f"Wrote tree solution to {directory}/")

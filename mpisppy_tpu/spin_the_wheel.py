"""WheelSpinner — multi-cylinder orchestration (reference:
mpisppy/spin_the_wheel.py, 237 LoC).

The reference splits COMM_WORLD into a (cylinder x scenario-shard) rank
grid and runs hub+spokes as separate MPI programs tied by RMA windows
(spin_the_wheel.py:219-237).  The TPU-native default is **interleaved
single-program scheduling** (SURVEY.md §7.6): the hub's PH loop and
every spoke's batched solve share one device queue — after each hub
iteration, PHHub.sync() pushes W/nonants, drives each spoke's `step()`
inline, and pulls bounds.  A `threads` mode runs each spoke's `main()`
loop in a host thread against the same Window protocol — the layout
that extends to multi-host DCN exchange.

Dict schema mirrors the reference / vanilla factories:
    hub_dict  = {"hub_class": PHHub, "hub_kwargs": {"options": {...}},
                 "opt_class": PH,    "opt_kwargs": {...}}
    spoke_dict = {"spoke_class": ..., "spoke_kwargs": {"options": ...},
                  "opt_class": ...,   "opt_kwargs": {...}}
"""

from __future__ import annotations

import os
import threading

import numpy as np

from . import global_toc


class WheelSpinner:
    def __init__(self, hub_dict, list_of_spoke_dict=(), mode="interleaved",
                 keep_workdir=False, resume_from=None,
                 exchange_backend=None):
        self._validate(hub_dict, list_of_spoke_dict)
        self.hub_dict = hub_dict
        self.list_of_spoke_dict = list(list_of_spoke_dict)
        self.mode = mode
        # exchange seam: None/"auto" picks by device count and mode
        # (the fused "collective" fabric on a multi-device fleet,
        # "device" mailboxes in threads mode, host seqlock on one
        # device); "seqlock"/"native"/"device"/"collective" force a
        # backend.  An explicit window_backend in the hub options
        # always wins.
        self.exchange_backend = exchange_backend
        self.exchange_backend_used = None
        self.fabric = None
        self.spcomm = None
        self._ran = False
        # multiproc mode: keep the window/log tempdir for debugging
        self.options_keep_workdir = keep_workdir
        # crash-resume (resilience/checkpoint.py): restore the hub
        # optimizer's PH state AND the hub's best bounds/incumbent from
        # a run checkpoint before spinning.  A missing file falls
        # through to a fresh start, so drivers can pass the same path
        # they write with options["run_checkpoint"] unconditionally.
        self.resume_from = resume_from
        if resume_from is not None:
            kw = dict(self.hub_dict["opt_kwargs"])
            kw["options"] = dict(kw.get("options") or {},
                                 resume_from=resume_from)
            self.hub_dict = dict(self.hub_dict, opt_kwargs=kw)

    def _select_backend(self, hub_opt):
        """Resolve the exchange backend for the in-process modes.
        "auto" (the default) keeps the exchange on-device whenever the
        hub's mesh spans more than one device — the fused collective
        fabric (mpmd/collective.py) for the single-threaded interleaved
        schedule, the per-pair device mailboxes (mpmd/exchange.py) in
        `threads` mode (spoke threads would interleave fused
        collectives with the hub's own programs on the shared mesh) —
        and the host seqlock on a single device, so existing
        single-device runs are bit-identical.  Multiproc mode never
        lands here (it is always the native mmap seqlock: device
        buffers cannot cross a process boundary)."""
        req = self.exchange_backend or "auto"
        if req in ("seqlock", "python"):
            return "python"
        if req == "native":
            return "native"
        n = getattr(getattr(hub_opt, "mesh", None), "size", 1)
        if req in ("device", "collective") or (req == "auto" and n > 1):
            try:
                from . import mpmd  # noqa: F401 — registers "device"
                #                           and "collective"
            except Exception as e:  # pragma: no cover - degraded env
                global_toc(f"WheelSpinner: device exchange unavailable "
                           f"({e}); using the host seqlock")
                return "python"
            if req == "auto":
                return ("device" if self.mode == "threads"
                        else "collective")
            return req
        return "python"

    def _collective_kwargs(self, hub_opt, n_spokes):
        """Shared CollectiveFabric + per-pair backend_kwargs for the
        "collective" backend: one lane row per spoke, lane devices
        drawn from the hub mesh (the shared-mesh modes timeshare
        devices; MPMDWheel overrides this with per-slice placements).
        None means the fabric cannot be built here — the caller drops
        to the device-mailbox backend."""
        if n_spokes == 0:
            return None
        try:
            from .mpmd.collective import CollectiveFabric
            devs = list(getattr(getattr(hub_opt, "mesh", None),
                                "devices", None) or [])
            if not devs:
                return None
            self.fabric = CollectiveFabric(
                devices=devs[:min(len(devs), n_spokes)])
            return {j: {"fabric": self.fabric, "tag": f"pair{j}"}
                    for j in range(n_spokes)}
        except Exception as e:  # pragma: no cover - degraded env
            global_toc(f"WheelSpinner: collective fabric unavailable "
                       f"({e}); using device mailboxes")
            return None

    def _restore_hub_bounds(self, hub):
        from .resilience.checkpoint import checkpoint_exists, restore_hub
        if self.resume_from is not None \
                and checkpoint_exists(self.resume_from):
            restore_hub(self.resume_from, hub)
            global_toc(f"WheelSpinner: hub bounds restored from "
                       f"{self.resume_from}")

    @staticmethod
    def _validate(hub_dict, spoke_dicts):
        """Reference spin_the_wheel.py:48-78 dict validation."""
        for k in ("hub_class", "opt_class", "opt_kwargs"):
            if k not in hub_dict:
                raise RuntimeError(f"hub_dict missing key {k}")
        for sd in spoke_dicts:
            for k in ("spoke_class", "opt_class", "opt_kwargs"):
                if k not in sd:
                    raise RuntimeError(f"spoke_dict missing key {k}")

    # -- lifecycle (reference spin_the_wheel.py:119-144) ------------------
    def spin(self):
        if self.mode == "multiproc":
            return self._spin_multiproc()
        hd = self.hub_dict
        global_toc("WheelSpinner: constructing hub optimizer")
        hub_opt = hd["opt_class"](**hd["opt_kwargs"])

        spokes = []
        for sd in self.list_of_spoke_dict:
            kw = dict(sd["opt_kwargs"])
            # all cylinders share ONE lowered batch + mesh placement —
            # the analog of each cylinder building its own SPBase
            # (reference :106-108), minus the duplicate model build
            kw.setdefault("batch", hub_opt.batch)
            kw.setdefault("mesh", hub_opt.mesh)
            # share the hub's PreparedBatch too (Ruiz scaling + ||A||):
            # identical batch => identical prep, as long as the spoke's
            # opt class uses the same column-scaling mode AND accepts
            # the hub's prep representation (a class that tiles/indexes
            # prep.A densely must not receive an ir.SplitA prep)
            from .ir import SplitA
            if (kw.get("batch") is hub_opt.batch
                    and sd["opt_class"]._shared_cols
                    == hd["opt_class"]._shared_cols
                    and (getattr(sd["opt_class"], "_use_split_prep", True)
                         or not isinstance(hub_opt.prep.A, SplitA))):
                kw.setdefault("prep", hub_opt.prep)
            sp_opt = sd["opt_class"](**kw)
            spoke = sd["spoke_class"](
                sp_opt, options=sd.get("spoke_kwargs", {}).get("options"))
            # each in-process spoke gets its own row in the merged
            # trace timeline (telemetry/tracer.py track pids)
            spoke.telemetry_track = (
                f"spoke{len(spokes)}:{type(spoke).__name__}")
            spokes.append(spoke)

        hub_options = dict(hd.get("hub_kwargs", {}).get("options") or {})
        if "window_backend" not in hub_options:
            backend = self._select_backend(hub_opt)
            if backend == "collective" \
                    and "window_backend_kwargs" not in hub_options:
                bkw = self._collective_kwargs(hub_opt, len(spokes))
                if bkw is None:
                    backend = "device"
                else:
                    hub_options["window_backend_kwargs"] = bkw
            hub_options["window_backend"] = backend
        self.exchange_backend_used = hub_options["window_backend"]
        hub = hd["hub_class"](hub_opt, spokes, options=hub_options)
        hub.setup_hub()
        self._restore_hub_bounds(hub)
        self.spcomm = hub

        if self.mode == "threads" and spokes:
            hub.drive_spokes_inline = False

            def guarded_main(sp):
                try:
                    sp.main()
                except Exception as e:
                    # report to the hub thread (index pruning must not
                    # race the hub's own set iteration)
                    hub.report_spoke_failure(sp, e)

            threads = [threading.Thread(target=guarded_main, args=(sp,),
                                        daemon=True)
                       for sp in spokes]
            for t in threads:
                t.start()
            hub.main()
            hub.send_terminate()
            # BOUNDED join: a healthy spoke exits after its current
            # step (a bounded batched solve), but a spoke stuck in a
            # pathological solve must not block shutdown forever (the
            # reference's kill protocol always terminates,
            # spin_the_wheel.py:119-144).  A thread still alive at the
            # deadline is escalated through the same failure-pruning
            # path a crashed spoke takes: marked failed so finalize
            # skips it (its state is suspect, and finalizing a
            # still-running spoke would race its warm-start caches);
            # the daemon thread dies with the process.
            join_timeout = float((hub.options or {}).get(
                "shutdown_join_timeout", 120.0))
            # PER-THREAD budget (worst case n_spokes * timeout, still
            # bounded): one hung spoke must not eat the others'
            # join time — a healthy spoke finishing a long step would
            # then be falsely escalated and its results discarded
            for t, sp in zip(threads, spokes):
                t.join(timeout=join_timeout)
                if t.is_alive():
                    hub.report_spoke_failure(sp, TimeoutError(
                        f"spoke did not exit within {join_timeout:.0f}s "
                        "of the kill signal"))
            hub._drain_failures()
        else:
            hub.drive_spokes_inline = True
            hub.main()
            hub.send_terminate()

        # final spoke passes (reference :129-139 "finalize") — a spoke
        # that failed mid-run is fully out of the wheel: no final pass
        # (its state is suspect and its wiring is already pruned)
        for sp in spokes:
            if getattr(sp, "_failed", False):
                continue
            try:
                sp.finalize()
            except Exception as e:  # a failing final pass must not eat
                global_toc(f"spoke finalize failed: {e}")  # the results
        hub.hub_finalize()
        self._flush_telemetry()
        self._ran = True
        return self

    def _flush_telemetry(self, extra_trace_files=()):
        """Write trace.json (hub + every spoke row merged onto one
        timeline) + metrics.jsonl into the configured telemetry dir.
        No-op when telemetry is off or has no output dir."""
        from . import telemetry as _telemetry
        tel = (getattr(self.spcomm, "telemetry", None)
               or _telemetry.get())
        path = tel.flush(extra_trace_files=extra_trace_files)
        if path is not None:
            global_toc(f"WheelSpinner: telemetry written to "
                       f"{os.path.dirname(path)}")

    def _spin_multiproc(self):
        """Hub + spokes as SEPARATE OS processes over the native mmap
        seqlock exchange (reference spin_the_wheel.py:219-237 runs the
        cylinders as distinct MPI programs; here the strata boundary is
        a process boundary and the RMA window is runtime/exchange.cpp).

        Spoke dicts must carry a "proc" key:
            {"batch": {"module": ..., "builder": ..., "kwargs": {...}}}
        so the child process can reconstruct the scenario batch itself
        (a live jitted optimizer cannot cross an exec boundary).
        """
        import tempfile

        from .cylinders.proc import SpokeHandle

        hd = self.hub_dict
        workdir = tempfile.mkdtemp(prefix="mpisppy_tpu_wheel_")
        global_toc(f"WheelSpinner[multiproc]: workdir {workdir} "
                   "(window files + per-spoke logs)")
        hub_opt = hd["opt_class"](**hd["opt_kwargs"])

        handles, specs = [], []
        for i, sd in enumerate(self.list_of_spoke_dict):
            if "proc" not in sd:
                raise RuntimeError(
                    "multiproc mode needs spoke_dict['proc'] with a "
                    "declarative batch spec")
            scls = sd["spoke_class"]
            # lengths mirror the spoke-side formulas (cylinders/spoke.py
            # receive_length/send_length) computed on the hub's batch —
            # both sides lower the identical model so shapes agree
            b = hub_opt.batch
            recv = b.num_scens * b.num_nonants
            send = (2 * b.num_nonants + 1
                    if getattr(scls, "provides_cuts", False) else 1)
            prefix = f"{workdir}/pair{i}"
            handles.append(SpokeHandle(scls, send, recv,
                                       sol_path=prefix + ".sol.npy"))
            ocls = sd["opt_class"]
            okw = sd["opt_kwargs"]
            # the child must pad to the hub's (possibly device-padded)
            # scenario count or the W/nonant window reshape disagrees
            bspec = dict(sd["proc"]["batch"], pad_to=b.num_scens)
            spec = {
                "batch": bspec,
                "opt_class": f"{ocls.__module__}:{ocls.__name__}",
                "spoke_class": f"{scls.__module__}:{scls.__name__}",
                "opt_options": okw.get("options", {}),
                "spoke_options": sd.get("spoke_kwargs", {}).get("options"),
                "scenario_names": list(okw["all_scenario_names"]),
                "windows": {"prefix": prefix,
                            "hub_length": recv, "spoke_length": send},
            }
            # child-process telemetry: each spoke records into its own
            # trace file (real pid = own timeline row); the hub merges
            # them into the single trace.json after shutdown
            from . import telemetry as _telemetry
            tel = _telemetry.get()
            if tel.enabled and tel.out_dir:
                spec["telemetry"] = {
                    "enabled": True,
                    "phase_timing": tel.phase_timing,
                    "main_label": f"spoke{i}:{scls.__name__}",
                    "trace_path": os.path.join(
                        tel.out_dir, f"trace_spoke{i}.json"),
                    "metrics_path": os.path.join(
                        tel.out_dir, f"metrics_spoke{i}.jsonl"),
                }
            specs.append(spec)

        hub = hd["hub_class"](
            hub_opt, handles,
            options=dict(hd.get("hub_kwargs", {}).get("options") or {},
                         window_backend="native",
                         window_path_prefix=f"{workdir}/pair"))
        hub.setup_hub()       # creates + resets the window files
        self._restore_hub_bounds(hub)
        self.spcomm = hub

        # supervision (resilience/supervisor.py): spawns the children,
        # then — polled from hub.sync() every iteration — detects dead
        # (Popen.poll) and hung (stale window write_id) spokes,
        # restarts them from the spec with capped backoff, and prunes
        # them into _mark_spoke_failed once the restart budget is
        # spent.  The wheel always finishes: worst case hub-only.
        from .resilience.supervisor import SpokeSupervisor
        sup = SpokeSupervisor(hub, specs, workdir,
                              options=hub.options)
        hub.supervisor = sup
        sup.start()

        hub.drive_spokes_inline = False
        ok = False
        try:
            hub.main()
            sup.poll(force=True)   # catch deaths after the last sync
            hub.send_terminate()
            sup.shutdown(timeout=float(hub.options.get(
                "shutdown_join_timeout", 120.0)))
            ok = True
        finally:
            sup.kill_all()
        hub.spoke_exit_reports = sup.exit_reports
        hub.hub_finalize()
        # incumbent pairing: a spoke process writes its solution file
        # only at finalize (after the kill), long after the hub read the
        # matching bound from the window — re-pair now that children
        # have exited (the in-process modes pair live, hub.py:154-156)
        for i in hub.innerbound_idx:
            data, wid = hub.pairs[i].to_hub.read()
            sol = handles[i].best_solution
            if (wid > 0 and sol is not None
                    and float(data[0]) == hub.BestInnerBound):
                hub.best_nonant_solution = sol
        if ok and not self.options_keep_workdir \
                and not sup.exit_reports:
            # mmap windows/logs are debugging artifacts; clean on a
            # fully healthy run, keep whenever any spoke died/hung (the
            # logs are the post-mortem) or on failure (the raise above
            # skips this)
            import shutil
            for pair in hub.pairs:
                pair.to_spoke.close()
                pair.to_hub.close()
            shutil.rmtree(workdir, ignore_errors=True)
        elif ok and sup.exit_reports:
            global_toc(f"WheelSpinner[multiproc]: spoke failure logs "
                       f"kept in {workdir}")
        # merge every child's trace file (written by run_spoke_from_spec
        # after its kill signal) into the hub's single timeline
        child_traces = [s["telemetry"]["trace_path"] for s in specs
                        if "telemetry" in s
                        and os.path.exists(s["telemetry"]["trace_path"])]
        self._flush_telemetry(extra_trace_files=child_traces)
        self._ran = True
        return self

    # -- results (reference spin_the_wheel.py:152-217) --------------------
    @property
    def BestInnerBound(self):
        return self.spcomm.BestInnerBound

    @property
    def BestOuterBound(self):
        return self.spcomm.BestOuterBound

    def on_hub(self):
        return True  # single-controller: every caller sees the hub

    def best_nonant_solution(self):
        """Incumbent (S, K) or (K,) nonants from the winning inner-bound
        spoke, falling back to the hub's consensus xbar."""
        sol = self.spcomm.best_nonant_solution
        if sol is None and self.spcomm.opt.state is not None:
            sol = np.asarray(self.spcomm.opt.state.xbar)
        return sol

    def write_first_stage_solution(self, path):
        sol = self.best_nonant_solution()
        if sol is None:
            raise RuntimeError("no solution available")
        root = sol if sol.ndim == 1 else sol[0]
        K = self.spcomm.opt.batch.num_nonants
        self.spcomm.opt.write_first_stage_solution(path, root[:K])

    def write_tree_solution(self, directory):
        opt = self.spcomm.opt
        if opt.state is None:
            raise RuntimeError("hub has no solution state")
        opt.write_tree_solution(directory, opt.state.x)

"""WheelSpinner — multi-cylinder orchestration (reference:
mpisppy/spin_the_wheel.py, 237 LoC).

The reference splits COMM_WORLD into a (cylinder x scenario-shard) rank
grid and runs hub+spokes as separate MPI programs tied by RMA windows
(spin_the_wheel.py:219-237).  The TPU-native default is **interleaved
single-program scheduling** (SURVEY.md §7.6): the hub's PH loop and
every spoke's batched solve share one device queue — after each hub
iteration, PHHub.sync() pushes W/nonants, drives each spoke's `step()`
inline, and pulls bounds.  A `threads` mode runs each spoke's `main()`
loop in a host thread against the same Window protocol — the layout
that extends to multi-host DCN exchange.

Dict schema mirrors the reference / vanilla factories:
    hub_dict  = {"hub_class": PHHub, "hub_kwargs": {"options": {...}},
                 "opt_class": PH,    "opt_kwargs": {...}}
    spoke_dict = {"spoke_class": ..., "spoke_kwargs": {"options": ...},
                  "opt_class": ...,   "opt_kwargs": {...}}
"""

from __future__ import annotations

import threading

import numpy as np

from . import global_toc


class WheelSpinner:
    def __init__(self, hub_dict, list_of_spoke_dict=(), mode="interleaved"):
        self._validate(hub_dict, list_of_spoke_dict)
        self.hub_dict = hub_dict
        self.list_of_spoke_dict = list(list_of_spoke_dict)
        self.mode = mode
        self.spcomm = None
        self._ran = False

    @staticmethod
    def _validate(hub_dict, spoke_dicts):
        """Reference spin_the_wheel.py:48-78 dict validation."""
        for k in ("hub_class", "opt_class", "opt_kwargs"):
            if k not in hub_dict:
                raise RuntimeError(f"hub_dict missing key {k}")
        for sd in spoke_dicts:
            for k in ("spoke_class", "opt_class", "opt_kwargs"):
                if k not in sd:
                    raise RuntimeError(f"spoke_dict missing key {k}")

    # -- lifecycle (reference spin_the_wheel.py:119-144) ------------------
    def spin(self):
        hd = self.hub_dict
        global_toc("WheelSpinner: constructing hub optimizer")
        hub_opt = hd["opt_class"](**hd["opt_kwargs"])

        spokes = []
        for sd in self.list_of_spoke_dict:
            kw = dict(sd["opt_kwargs"])
            # all cylinders share ONE lowered batch + mesh placement —
            # the analog of each cylinder building its own SPBase
            # (reference :106-108), minus the duplicate model build
            kw.setdefault("batch", hub_opt.batch)
            kw.setdefault("mesh", hub_opt.mesh)
            # share the hub's PreparedBatch too (Ruiz scaling + ||A||):
            # identical batch => identical prep, as long as the spoke's
            # opt class uses the same column-scaling mode
            if (kw.get("batch") is hub_opt.batch
                    and sd["opt_class"]._shared_cols
                    == hd["opt_class"]._shared_cols):
                kw.setdefault("prep", hub_opt.prep)
            sp_opt = sd["opt_class"](**kw)
            spoke = sd["spoke_class"](
                sp_opt, options=sd.get("spoke_kwargs", {}).get("options"))
            spokes.append(spoke)

        hub = hd["hub_class"](
            hub_opt, spokes,
            options=hd.get("hub_kwargs", {}).get("options"))
        hub.setup_hub()
        self.spcomm = hub

        if self.mode == "threads" and spokes:
            hub.drive_spokes_inline = False
            threads = [threading.Thread(target=sp.main, daemon=True)
                       for sp in spokes]
            for t in threads:
                t.start()
            hub.main()
            hub.send_terminate()
            # unbounded join: spokes exit after their current step (a
            # bounded batched solve); finalizing while a spoke thread
            # still runs would race on its opt's warm-start caches
            for t in threads:
                t.join()
        else:
            hub.drive_spokes_inline = True
            hub.main()
            hub.send_terminate()

        # final spoke passes (reference :129-139 "finalize")
        for sp in spokes:
            try:
                sp.finalize()
            except Exception as e:  # a failing final pass must not eat
                global_toc(f"spoke finalize failed: {e}")  # the results
        hub.hub_finalize()
        self._ran = True
        return self

    # -- results (reference spin_the_wheel.py:152-217) --------------------
    @property
    def BestInnerBound(self):
        return self.spcomm.BestInnerBound

    @property
    def BestOuterBound(self):
        return self.spcomm.BestOuterBound

    def on_hub(self):
        return True  # single-controller: every caller sees the hub

    def best_nonant_solution(self):
        """Incumbent (S, K) or (K,) nonants from the winning inner-bound
        spoke, falling back to the hub's consensus xbar."""
        sol = self.spcomm.best_nonant_solution
        if sol is None and self.spcomm.opt.state is not None:
            sol = np.asarray(self.spcomm.opt.state.xbar)
        return sol

    def write_first_stage_solution(self, path):
        sol = self.best_nonant_solution()
        if sol is None:
            raise RuntimeError("no solution available")
        root = sol if sol.ndim == 1 else sol[0]
        K = self.spcomm.opt.batch.num_nonants
        self.spcomm.opt.write_first_stage_solution(path, root[:K])

    def write_tree_solution(self, directory):
        opt = self.spcomm.opt
        if opt.state is None:
            raise RuntimeError("hub has no solution state")
        opt.write_tree_solution(directory, opt.state.x)

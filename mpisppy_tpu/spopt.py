"""SPOpt — the solve engine (reference: mpisppy/spopt.py, 903 LoC).

Where the reference's `solve_loop` walks local subproblems serially and
crosses a process boundary into Gurobi per scenario (spopt.py:226, :85),
here one call = one jitted batched PDHG solve over ALL scenarios at
once.  Objective modifications (PH's W and prox, Lagrangian W-only,
xhat fixing) arrive as array arguments — the nonant fix/restore caches
of the reference (spopt.py:528-740) become pure functions of bounds
arrays.

Expectations (Eobjective spopt.py:310, Ebound :346) are probability-
weighted sums over the sharded scenario axis; XLA inserts the psum.
"""

from __future__ import annotations

import time

import jax
import jax.numpy as jnp
import numpy as np

from . import global_toc
from .ops.pdhg import PDHGSolver, prepare_batch
from .spbase import SPBase


class SPOpt(SPBase):
    # subclasses needing one column scaling shared across scenarios
    # (consensus/EF solves) set this so the batch is prepared once
    _shared_cols = False

    def __init__(self, *args, prep=None, **kwargs):
        super().__init__(*args, **kwargs)
        o = self.options
        self.solver = PDHGSolver(
            max_iters=int(o.get("pdhg_max_iters", 20000)),
            eps=float(o.get("pdhg_eps", 1e-6)),
            check_every=int(o.get("pdhg_check_every", 40)),
            restart_every=int(o.get("pdhg_restart_every", 4)),
            use_pallas=o.get("pdhg_use_pallas", "auto"),
            pallas_tile=int(o.get("pdhg_pallas_tile", 8)),
            pallas_interpret=bool(o.get("pdhg_pallas_interpret", False)),
        )
        if prep is not None:
            # shared PreparedBatch from a sibling cylinder over the SAME
            # batch (WheelSpinner passes the hub's — Ruiz scaling and the
            # norm estimate depend only on (A, row bounds, _shared_cols))
            self.prep = prep
        else:
            global_toc("Preparing batch (Ruiz scaling + ||A|| estimate)")
            self.prep = prepare_batch(
                self.batch.A, self.batch.row_lo, self.batch.row_hi,
                shared_cols=self._shared_cols)
        # warm-start caches (analog of persistent-solver state,
        # reference spopt.py:877 set_instance_retry — license logic gone)
        self._x_warm = None
        self._y_warm = None
        self._named_warm = {}
        self._solve_times = []
        # dynamic solver tolerance (Gapper schedules it) as a jnp
        # scalar — traced, so schedule changes never recompile
        self.solver_eps = jnp.asarray(self.solver.eps, self.batch.c.dtype)

    # -- hot path ---------------------------------------------------------
    def solve_loop(self, c=None, qdiag=None, lb=None, ub=None,
                   warm=True, dtiming=False):
        """Solve every scenario subproblem (batched).  Any of
        c/qdiag/lb/ub override the batch's own arrays (this is how PH,
        Lagrangian and xhat objectives/fixings are expressed).

        warm: True/False for the default warm-start cache, or a string
        TAG for a named cache — repeated bound evaluations (xhat,
        Lagrangian) warm-start from their own previous solve instead
        of going cold (the persistent-solver analog, spopt.py:877).

        Returns the ops.pdhg.SolveResult.
        """
        b = self.batch
        t0 = time.time()
        if isinstance(warm, str):
            cache = self._named_warm.get(warm, (None, None))
        else:
            cache = (self._x_warm, self._y_warm) if warm else (None, None)
        res = self.solver.solve(
            self.prep,
            b.c if c is None else c,
            b.qdiag if qdiag is None else qdiag,
            b.lb if lb is None else lb,
            b.ub if ub is None else ub,
            obj_const=b.obj_const,
            x0=cache[0],
            y0=cache[1],
            eps=self.solver_eps,
        )
        if isinstance(warm, str):
            self._named_warm[warm] = (res.x, res.y)
        elif warm:
            self._x_warm = res.x
            self._y_warm = res.y
        if dtiming or self.options.get("display_timing"):
            jax.block_until_ready(res.x)
            dt = time.time() - t0
            self._solve_times.append(dt)
            global_toc(f"solve_loop: {dt*1e3:8.1f} ms, "
                       f"iters={int(res.iters)}, "
                       f"conv={int(np.sum(np.asarray(res.converged)))}"
                       f"/{b.num_scens}")
        return res

    def clear_warmstart(self):
        self._x_warm = None
        self._y_warm = None
        self._named_warm = {}

    # -- expectations (Allreduce analogs) ---------------------------------
    def Eobjective(self, objs):
        """E[objective] over scenarios (reference spopt.py:310).  `objs`
        is the per-scenario (S,) objective; padding scenarios carry
        probability 0 so they vanish."""
        return jnp.sum(self.batch.prob * objs)

    def Ebound(self, dual_objs):
        """Valid expected outer bound from per-scenario dual objectives
        (reference spopt.py:346 uses solver bounds)."""
        return jnp.sum(self.batch.prob * dual_objs)

    def feas_prob(self, res, tol=None):
        """Probability mass of scenarios whose solve is feasible/
        converged (reference spopt.py:411 feas_prob; :175-194
        classifies solver status).  First-order analog: primal residual
        under tolerance."""
        tol = tol or 10 * self.solver.eps
        ok = res.pres < tol
        return float(jnp.sum(jnp.where(ok, self.batch.prob, 0.0)))

    def infeas_prob(self, res, tol=None):
        return 1.0 - self.feas_prob(res, tol)

    def avg_min_max(self, vals):
        """Prob>0-masked avg/min/max of a per-scenario quantity
        (reference spopt.py:469)."""
        mask = self.batch.prob > 0
        v = np.asarray(vals)
        vm = v[np.asarray(mask)]
        return float(np.mean(vm)), float(np.min(vm)), float(np.max(vm))

    def evaluate_xhat(self, nonant_values, upto_stage=None, tol=None,
                      warm="xhat_eval"):
        """Expected objective with nonants fixed to a candidate — the
        implementable inner bound (reference utils/xhat_eval.py:293).
        Returns (Eobj, feasible).  Successive evaluations warm-start
        from the named cache (candidates move slowly)."""
        lb, ub = self.fixed_nonant_bounds(nonant_values,
                                          upto_stage=upto_stage)
        res = self.solve_loop(lb=lb, ub=ub, warm=warm)
        feas = self.feas_prob(res, tol=tol) > 1.0 - 1e-6
        return float(self.Eobjective(res.obj)), feas

    # -- nonant fixing (reference spopt.py:592-740 _fix_nonants) ----------
    def fixed_nonant_bounds(self, values, upto_stage=None):
        """Bounds arrays that pin nonant slots to `values`.

        values: (K,) to pin all scenarios alike, or (S, K) per-scenario
        (multistage candidate trees).  upto_stage: only fix slots whose
        stage <= upto_stage (reference xhat_eval.py:326
        fix_nonants_upto_stage).
        Returns (lb, ub).
        """
        b = self.batch
        vals = jnp.asarray(values)
        if vals.ndim == 1:
            vals = jnp.broadcast_to(vals[None, :],
                                    (b.num_scens, b.num_nonants))
        lb = b.lb.at[:, b.nonant_idx].set(vals)
        ub = b.ub.at[:, b.nonant_idx].set(vals)
        if upto_stage is not None:
            stage = jnp.asarray(b.tree.stage_of, jnp.int32)
            keep = stage <= upto_stage
            lb = lb.at[:, b.nonant_idx].set(
                jnp.where(keep[None, :], vals, b.lb[:, b.nonant_idx]))
            ub = ub.at[:, b.nonant_idx].set(
                jnp.where(keep[None, :], vals, b.ub[:, b.nonant_idx]))
        return lb, ub

"""SPOpt — the solve engine (reference: mpisppy/spopt.py, 903 LoC).

Where the reference's `solve_loop` walks local subproblems serially and
crosses a process boundary into Gurobi per scenario (spopt.py:226, :85),
here one call = one jitted batched PDHG solve over ALL scenarios at
once.  Objective modifications (PH's W and prox, Lagrangian W-only,
xhat fixing) arrive as array arguments — the nonant fix/restore caches
of the reference (spopt.py:528-740) become pure functions of bounds
arrays.

Expectations (Eobjective spopt.py:310, Ebound :346) are probability-
weighted sums over the sharded scenario axis; XLA inserts the psum.
"""

from __future__ import annotations

import dataclasses
import time

import jax
import jax.numpy as jnp
import numpy as np

from . import global_toc
from . import telemetry as _telemetry
from .ir import (SparseSplitA, SplitA, bmatvec, delta_idx,
                 shared_density, sparsify_split)
from .ops.pdhg import (PDHGSolver, PreparedBatch, prepare_batch,
                       prepare_batch_split, prepare_split_native)
from .spbase import SPBase
from .utils import mfu as _mfu


class SPOpt(SPBase):
    # subclasses needing one column scaling shared across scenarios
    # (consensus/EF solves) set this so the batch is prepared once
    _shared_cols = False
    # subclasses that tile / index prep.A as a dense array (the MIP
    # dive's stacked bound-variants) opt out of the SplitA fast path
    _use_split_prep = True

    def __init__(self, *args, prep=None, **kwargs):
        super().__init__(*args, **kwargs)
        o = self.options
        self.solver = PDHGSolver.from_options(o)
        if prep is not None:
            # shared PreparedBatch from a sibling cylinder over the SAME
            # batch (WheelSpinner passes the hub's — Ruiz scaling and the
            # norm estimate depend only on (A, row bounds, _shared_cols))
            self.prep = prep
        else:
            global_toc("Preparing batch (Ruiz scaling + ||A|| estimate)")
            self.prep = self._build_prep(hot=self.solver.hot_dtype)
        # density of the shared constraint block actually carried by the
        # prep (None for non-split preps) — bench reports it, and the
        # FLOP accounting debits sparse matvecs by it
        self._shared_nnz_frac = (float(shared_density(self.prep.A))
                                 if isinstance(self.prep.A, SplitA)
                                 else None)
        # warm-start caches (analog of persistent-solver state,
        # reference spopt.py:877 set_instance_retry — license logic gone)
        self._x_warm = None
        self._y_warm = None
        self._named_warm = {}
        self._solve_times = []
        self._flops = 0.0          # accumulated kernel FLOPs (utils/mfu)
        self._solve_wall = 0.0     # accumulated timed solve seconds
        self._certify_wall = 0.0   # seconds inside f64 certified re-solves
        self._kernel_iters = 0     # accumulated PDHG kernel iterations
        self._restarts_total = 0   # accumulated PDHG restart events
        self._flops_saved = 0.0    # est. FLOPs avoided by compaction
        self._active_traj = []     # last compacted solve's trajectory
        self._active_fraction = 1.0  # last solve's final active fraction
        self._promotions = 0       # solves promoted hot-dtype -> full
        self._sparse_matvecs = 0   # matvecs routed through BCOO
        # telemetry (telemetry/): the options value configures the
        # process-global handle; every instrument lookup below is a
        # null no-op when disabled (zero-cost-when-off contract)
        self._tel = _telemetry.configure_from_options(o.get("telemetry"))
        # dynamic solver tolerance (Gapper schedules it) as a jnp
        # scalar — traced, so schedule changes never recompile
        self.solver_eps = jnp.asarray(self.solver.eps, self.batch.c.dtype)
        # f64 fallback solver for certified solves (lazily built)
        self._solver64 = None
        # full-precision (solver, prep) pair a hot-dtype run promotes
        # to once the tolerance crosses the dtype's eps floor
        self._promoted_cache = None
        self._np_cache = {}

    def _build_prep(self, hot=None, batch=None):
        """Ruiz scaling + ||A|| estimate over the batch constraint data.

        hot: a HOT_DTYPES key — cast A and the row bounds to that mode's
        COMPUTE dtype first, so the equilibration and the power-iteration
        norm estimate themselves run in low precision (the prep is an
        input to the hot loop only; certified paths build their own f64
        prep in `_certified_resolve`).  When the solver carries a
        sparse_threshold, a SplitA prep whose shared block is sparse
        enough is converted to the BCOO-backed SparseSplitA afterward —
        Ruiz row/column scaling preserves the zero pattern, so the
        density measured post-scaling equals the structural density.

        batch: prepare a DIFFERENT ScenarioBatch than self.batch with
        the same routing (split-native / delta-split / dense) — the
        streaming layer preps each sampled scenario block through here
        so every pow2 block width hits the per-shape jit caches of the
        prepare_* functions.
        """
        b = self.batch if batch is None else batch
        o = self.options
        A, row_lo, row_hi = b.A, b.row_lo, b.row_hi
        pair = (self.solver._hot_pair(jnp.asarray(b.c).dtype)
                if hot else None)
        if pair is not None:
            compute = pair[1]
            A = (A.astype(compute) if isinstance(A, SplitA)
                 else jnp.asarray(A, compute))
            row_lo = jnp.asarray(row_lo, compute)
            row_hi = jnp.asarray(row_hi, compute)
        delta = delta_idx(b)
        if b.split_A:
            # batch born split-native (no dense A exists, true-size
            # instances): the split prep is the ONLY prep
            prep = prepare_split_native(A, row_lo, row_hi)
        elif (delta is not None and self._use_split_prep
                and not b.shared_A and not o.get("no_split_prep")):
            # sparse matrix uncertainty (ir.SplitA): shared-scaling
            # Ruiz keeps the shared+delta structure, and shared
            # columns satisfy _shared_cols implicitly
            prep = prepare_batch_split(
                A, jnp.asarray(delta[0], jnp.int32),
                jnp.asarray(delta[1], jnp.int32), row_lo, row_hi)
        else:
            prep = prepare_batch(A, row_lo, row_hi,
                                 shared_cols=self._shared_cols)
        if self.solver.sparse_threshold > 0.0 \
                and isinstance(prep.A, SplitA):
            spA = sparsify_split(prep.A, self.solver.sparse_threshold)
            if spA is not prep.A:
                prep = dataclasses.replace(prep, A=spA)
        return prep

    def _promoted_pair(self):
        """The full-precision (solver, prep) pair used once a solve's
        tolerance crosses the hot dtype's eps floor.  Built lazily (one
        extra prep + at most one extra jit compile per run — promotion
        is monotone under the eps ladder) and cached."""
        if self._promoted_cache is None:
            self._promoted_cache = (self.solver.clone(hot_dtype=None),
                                    self._build_prep(hot=None))
        return self._promoted_cache

    def active_solver_prep(self, eps=None, count=True):
        """(solver, prep) for a solve at tolerance `eps`: the configured
        pair until `eps` crosses the hot dtype's floor (100x machine
        epsilon of the compute dtype), then the promoted full-precision
        pair.  With no hot_dtype this is always (self.solver, self.prep).
        count=True increments the promotion accounting when the
        promoted pair is selected."""
        e = float(self.solver_eps if eps is None else eps)
        if not self.solver.wants_promotion(e):
            return self.solver, self.prep
        solver, prep = self._promoted_pair()
        if count:
            self._promotions += 1
            if self._tel.enabled:
                self._tel.registry.counter("pdhg.promotions").inc()
        return solver, prep

    @staticmethod
    def _prep_density(prep):
        """FLOP discount for the matvec model: the BCOO path does
        ~density x the dense shared-block work; dense preps pay full
        price."""
        if isinstance(prep.A, SparseSplitA):
            return float(prep.A.shared_nnz_frac)
        return 1.0

    # -- hot path ---------------------------------------------------------
    def solve_loop(self, c=None, qdiag=None, lb=None, ub=None,
                   warm=True, dtiming=False, certify=False, eps=None,
                   iters_cap=None, batch=None, prep=None, x0=None,
                   y0=None):
        """Solve every scenario subproblem (batched).  Any of
        c/qdiag/lb/ub override the batch's own arrays (this is how PH,
        Lagrangian and xhat objectives/fixings are expressed).

        warm: True/False for the default warm-start cache, or a string
        TAG for a named cache — repeated bound evaluations (xhat,
        Lagrangian) warm-start from their own previous solve instead
        of going cold (the persistent-solver analog, spopt.py:877).

        batch/prep: solve a DIFFERENT ScenarioBatch than self.batch
        (the streaming layer's sampled blocks).  A block solve must
        bring its own prep (the Ruiz scaling belongs to the block's
        constraint data) and manages warm starts explicitly via
        x0/y0 — the instance warm caches are shaped for self.batch, so
        block solves neither read nor write them.  certify is
        unsupported on block solves (`_certified_resolve` scatters
        into self.batch-shaped results).

        x0/y0: explicit warm-start point; overrides the warm cache.

        certify: drive scenarios to the KKT tolerance via a float64
        re-solve.  Scenarios the fast (typically f32) batched solve
        leaves unconverged — the f32 primal-residual floor sits ~1e-4
        on ill-scaled instances — are gathered into a compact float64
        sub-batch and re-solved warm-started (on the CPU backend when
        the accelerator lacks f64).  This is the analog of the
        reference's solver-status classification + retry
        (spopt.py:175-194).  Modes:
          False  — never refine;
          True   — refine every non-converged prob>0 scenario;
          "feas" — refine only PRIMAL-infeasible scenarios (pres over
                   tolerance).  Dual-side non-convergence is left
                   alone — it only weakens bounds, which Ebound
                   handles (mask / finite-box validity) — so solves
                   that legitimately ride to a big artificial box
                   (e.g. an epigraph variable before its cuts exist)
                   are not chased to the bottom.

        Returns the ops.pdhg.SolveResult.
        """
        if batch is not None:
            if prep is None:
                raise ValueError(
                    "solve_loop(batch=...) requires an explicit prep "
                    "for the block's constraint data")
            if certify:
                raise ValueError(
                    "certify is not supported on block solves "
                    "(batch=...): _certified_resolve scatters into "
                    "self.batch-shaped results")
        b = self.batch if batch is None else batch
        t0 = time.time()
        tel = self._tel
        tn0 = time.monotonic_ns() if tel.enabled else 0
        if batch is not None:
            cache = (x0, y0)
        elif isinstance(warm, str):
            cache = self._named_warm.get(warm, (None, None))
        else:
            cache = (self._x_warm, self._y_warm) if warm else (None, None)
            if x0 is not None or y0 is not None:
                cache = (x0, y0)
        eps_arg = self.solver_eps if eps is None else eps
        if prep is not None:
            # explicit prep (streaming block solves): hot-dtype
            # promotion does not apply — the caller chose the prep's
            # dtype, and a promoted solver with a mismatched-dtype
            # prep would silently recompile per call
            solver = self.solver
        else:
            # hot-dtype promotion: once the requested tolerance crosses
            # the low-precision eps floor, route this solve through the
            # full-precision pair (monotone under the ladder/Gapper
            # schedules, so this re-routes at most once per run)
            solver, prep = self.active_solver_prep(eps_arg)
        dens = self._prep_density(prep)
        args = (prep,
                b.c if c is None else c,
                b.qdiag if qdiag is None else qdiag,
                b.lb if lb is None else lb,
                b.ub if ub is None else ub)
        kw = dict(obj_const=b.obj_const, x0=cache[0], y0=cache[1],
                  eps=eps_arg)
        # compaction (opt-in via pdhg_compact_threshold) applies only
        # to uncapped solves: an iters_cap caller is screening and owns
        # its own budget/shape discipline
        if solver.compact_threshold > 0.0 and iters_cap is None:
            traj = []
            res = solver.solve_compacted(
                *args, **kw, probs=b.prob, on_segment=traj.append)
            self._active_traj = traj
            full = float(max(int(np.sum(np.asarray(b.prob) > 0)), 1))
            self._active_fraction = (traj[-1]["active"] / full
                                     if traj else 0.0)
            # FLOPs the compacted segments did NOT spend on rows the
            # full-width solve would have carried
            saved = sum(
                _mfu.pdhg_flops(t["seg_iters"],
                                b.num_scens - t["width"],
                                b.num_rows, b.num_vars,
                                solver.check_every, density=dens)
                for t in traj if t["width"] < b.num_scens)
            self._flops_saved += saved
        else:
            res = solver.solve(*args, **kw, iters_cap=iters_cap)
            saved = 0.0
            self._active_fraction = float(
                np.sum(np.asarray(~res.converged)
                       & (np.asarray(b.prob) > 0))
                / max(int(np.sum(np.asarray(b.prob) > 0)), 1))
        it_n = int(res.iters)
        rst_n = int(np.sum(np.asarray(res.restarts)))
        # net of compaction savings: saved counts work NOT done
        self._flops += _mfu.pdhg_flops(
            it_n, b.num_scens, b.num_rows, b.num_vars,
            solver.check_every, density=dens) - saved
        if isinstance(prep.A, SparseSplitA):
            # two shared-block products (forward + transpose) per
            # inner iteration route through jax.experimental.sparse
            self._sparse_matvecs += 2 * it_n
        self._kernel_iters += it_n
        self._restarts_total += rst_n
        if certify:
            select = None
            if certify == "feas":
                tol = 10 * float(self.solver_eps)
                select = np.asarray(res.pres) >= tol
            res = self._certified_resolve(res, c, qdiag, lb, ub,
                                          select=select)
        if batch is not None:
            pass  # block solves never clobber the self.batch-shaped caches
        elif isinstance(warm, str):
            self._named_warm[warm] = (res.x, res.y)
        elif warm:
            self._x_warm = res.x
            self._y_warm = res.y
        jax.block_until_ready(res.x)
        dt = time.time() - t0
        self._solve_wall += dt
        if tel.enabled:
            tel.tracer.record_span("solve.loop", tn0,
                                   time.monotonic_ns())
            r = tel.registry
            r.counter("solve.calls").inc()
            r.counter("solve.kernel_iters").inc(it_n)
            r.histogram("solve.seconds").observe(dt)
            r.counter("pdhg.inner_iters_total").inc(it_n)
            r.counter("pdhg.restarts_total").inc(rst_n)
            r.gauge("pdhg.active_fraction").set(self._active_fraction)
            r.gauge("pdhg.active_scenarios").set(
                self._active_fraction
                * int(np.sum(np.asarray(b.prob) > 0)))
            if saved:
                r.counter("pdhg.flops_saved").inc(saved)
            if isinstance(prep.A, SparseSplitA):
                r.counter("pdhg.sparse_matvecs").inc(2 * it_n)
            if rst_n:
                # mean restart cycle length in inner iterations: total
                # iterate-steps taken across the batch over the number
                # of cycles those steps were split into
                r.event("pdhg.restart", count=rst_n,
                        mean_cycle=it_n * b.num_scens / max(
                            rst_n + b.num_scens, 1),
                        iters=it_n)
            _mfu.record_to_registry(r, self._flops, self._solve_wall,
                                    kernel_iters=self._kernel_iters)
        if dtiming or self.options.get("display_timing"):
            self._solve_times.append(dt)
            global_toc(f"solve_loop: {dt*1e3:8.1f} ms, "
                       f"iters={int(res.iters)}, "
                       f"conv={int(np.sum(np.asarray(res.converged)))}"
                       f"/{b.num_scens}")
        return res

    # -- certified fallback ----------------------------------------------
    def _np64(self, key, arr):
        """Cached float64 numpy view of a static batch array."""
        hit = self._np_cache.get(key)
        if hit is None:
            hit = np.asarray(arr, np.float64)
            self._np_cache[key] = hit
        return hit

    def _certified_resolve(self, res, c=None, qdiag=None, lb=None,
                           ub=None, A=None, row_lo=None, row_hi=None,
                           obj_const=None, prep_key="_prep64",
                           select=None):
        """Re-solve unconverged prob>0 scenarios in float64, warm-started
        from the fast solve, and scatter the refined solutions back.

        Float32 PDHG stalls at a primal-residual floor ~1e-4 on a small
        fraction of ill-scaled scenarios (measured: 155/1000 on
        farmer-1000, crops_mult=10); the same instances converge in
        ~2.5k f64 iterations.  This path refines INDEPENDENT
        per-scenario solves only (solve_loop never passes a
        ConsensusSpec); the coupled consensus (EF) solve has its own
        full-batch f64 fallback in opt/ef.py solve_extensive_form.

        A/row_lo/row_hi/obj_const override the batch constraint data
        (the reduced xhat path passes its eliminated-column system);
        prep_key names the cached f64 scaling for the given A —
        Ruiz/anorm depend only on A, so the full-batch f64 prep is
        computed once per key and indexed per call.
        """
        conv = np.asarray(res.converged)
        live = np.asarray(self.batch.prob) > 0
        pick = ~conv if select is None else np.asarray(select)
        idx = np.flatnonzero(pick & live)
        if idx.size == 0:
            return res
        t_cert = time.time()
        b = self.batch
        A = b.A if A is None else A
        row_lo = b.row_lo if row_lo is None else row_lo
        row_hi = b.row_hi if row_hi is None else row_hi
        obj_const = b.obj_const if obj_const is None else obj_const
        sub = {
            "obj_const": np.asarray(obj_const, np.float64)[idx],
            "row_lo": np.asarray(row_lo, np.float64)[idx],
            "row_hi": np.asarray(row_hi, np.float64)[idx],
            "c": np.asarray(b.c if c is None else c, np.float64)[idx],
            "qdiag": np.asarray(
                b.qdiag if qdiag is None else qdiag, np.float64)[idx],
            "lb": np.asarray(b.lb if lb is None else lb, np.float64)[idx],
            "ub": np.asarray(b.ub if ub is None else ub, np.float64)[idx],
            "x0": np.asarray(res.x, np.float64)[idx],
            "y0": np.asarray(res.y, np.float64)[idx],
        }
        # options["certify_max_iters"] bounds the f64 fallback's
        # budget: on accelerators without f64 this path runs on the
        # host CPU, and an uncapped 100k-iteration re-solve of a
        # large straggler set can dominate wall-clock (r4 UC-on-TPU
        # timeout); a capped certify still improves stragglers and
        # the Ebound mask keeps unrescued ones out of the bound.
        # Keyed on the RESOLVED budget so an extension rescheduling
        # the option mid-run gets a fresh solver, not a stale cache.
        cert_iters = int(self.options.get(
            "certify_max_iters", max(self.solver.max_iters, 100000)))
        if self._solver64 is None or \
                self._solver64.max_iters != cert_iters:
            # clone: keeps the restart policy/betas (and every future
            # knob) in lockstep with the fast solver's config; the f64
            # fallback typically runs on host CPU, where the Pallas
            # kernel has no business.  hot_dtype is pinned OFF: the
            # certified verdict is this path's whole purpose, so it
            # never inherits a low-precision hot loop (AST-guarded in
            # tests/test_precision.py).
            self._solver64 = self.solver.clone(
                max_iters=cert_iters, use_pallas=False, hot_dtype=None)
        try:
            cpu = jax.devices("cpu")[0]
        except RuntimeError:
            cpu = None
        from .utils.platform import enable_x64_scope
        with enable_x64_scope():
            put = ((lambda a: jax.device_put(a, cpu))
                   if cpu is not None else jnp.asarray)
            full = self._np_cache.get(prep_key)
            if full is None:
                if isinstance(A, SplitA):
                    # split-native constraint data: the f64 prep stays
                    # split too (the dense (S, M, N) tensor may be
                    # unmaterializable at true-size instances)
                    a64 = SplitA(
                        shared=put(np.asarray(A.shared, np.float64)),
                        rows=put(np.asarray(A.rows)),
                        cols=put(np.asarray(A.cols)),
                        vals=put(np.asarray(A.vals, np.float64)))
                    full = prepare_split_native(
                        a64,
                        put(np.asarray(row_lo, np.float64)),
                        put(np.asarray(row_hi, np.float64)))
                else:
                    full = prepare_batch(
                        put(np.asarray(A, np.float64)),
                        put(np.asarray(row_lo, np.float64)),
                        put(np.asarray(row_hi, np.float64)),
                        shared_cols=self._shared_cols)
                full = jax.tree.map(np.asarray, full)
                self._np_cache[prep_key] = full

            S_all = self.batch.num_scens

            def take(a):
                # shared-A batches keep singleton leaves (A, d_row,
                # d_col, anorm stay (1, ...)); per-scenario leaves are
                # gathered to the straggler sub-batch
                return a if (a.shape[0] == 1 and S_all > 1) else a[idx]

            if isinstance(full.A, SplitA):
                # only the per-scenario delta values gather; the shared
                # matrix and coordinates serve every straggler as-is
                sub_A = SplitA(shared=put(full.A.shared),
                               rows=put(full.A.rows),
                               cols=put(full.A.cols),
                               vals=put(full.A.vals[idx]))
                prep64 = PreparedBatch(
                    A=sub_A,
                    row_lo=put(take(full.row_lo)),
                    row_hi=put(take(full.row_hi)),
                    d_row=put(take(full.d_row)),
                    d_col=put(take(full.d_col)),
                    anorm=put(take(full.anorm)))
            else:
                prep64 = jax.tree.map(lambda a: put(take(a)), full)
            # row bounds may be call-specific (xhat candidates shift
            # them); rebuild the scaled fields from the raw bounds
            dr = np.asarray(take(np.asarray(full.d_row)))
            prep64 = dataclasses.replace(
                prep64,
                row_lo=put(np.where(np.isfinite(sub["row_lo"]),
                                    sub["row_lo"] * dr, sub["row_lo"])),
                row_hi=put(np.where(np.isfinite(sub["row_hi"]),
                                    sub["row_hi"] * dr, sub["row_hi"])))
            r64 = self._solver64.solve(
                prep64, put(sub["c"]), put(sub["qdiag"]),
                put(sub["lb"]), put(sub["ub"]),
                obj_const=put(sub["obj_const"]),
                x0=put(sub["x0"]), y0=put(sub["y0"]),
                eps=float(self.solver_eps))
            jax.block_until_ready(r64.x)
        self._flops += _mfu.pdhg_flops(
            int(r64.iters), idx.size, b.num_rows, b.num_vars,
            self.solver.check_every)
        dt_cert = time.time() - t_cert
        self._certify_wall += dt_cert
        if self._tel.enabled:
            r = self._tel.registry
            r.counter("solve.certify_calls").inc()
            r.counter("solve.certify_scenarios").inc(int(idx.size))
            r.histogram("solve.certify_seconds").observe(dt_cert)
        n_ok = int(np.sum(np.asarray(r64.converged)))
        if n_ok < idx.size:
            global_toc(f"WARNING: f64 fallback left {idx.size - n_ok} "
                       f"scenario(s) unconverged")
        dt = res.x.dtype
        ix = jnp.asarray(idx)

        def scat(a, a64, d=dt):
            return a.at[ix].set(jnp.asarray(np.asarray(a64), d))

        restarts = res.restarts
        if getattr(restarts, "ndim", 0):     # (S,) array, not the
            restarts = restarts.at[ix].add(  # scalar-0 pytree default
                jnp.asarray(np.asarray(r64.restarts), restarts.dtype))
        return dataclasses.replace(
            res,
            x=scat(res.x, r64.x), y=scat(res.y, r64.y),
            obj=scat(res.obj, r64.obj),
            dual_obj=scat(res.dual_obj, r64.dual_obj),
            pres=scat(res.pres, r64.pres), dres=scat(res.dres, r64.dres),
            gap=scat(res.gap, r64.gap),
            converged=scat(res.converged, r64.converged, bool),
            restarts=restarts)

    def clear_warmstart(self):
        self._x_warm = None
        self._y_warm = None
        self._named_warm = {}

    # -- expectations (Allreduce analogs) ---------------------------------
    def Eobjective(self, objs):
        """E[objective] over scenarios (reference spopt.py:310).  `objs`
        is the per-scenario (S,) objective; padding scenarios carry
        probability 0 so they vanish."""
        return jnp.sum(self.batch.prob * objs)

    def Ebound(self, dual_objs, converged=None):
        """Valid expected outer bound from per-scenario dual objectives
        (reference spopt.py:346 uses solver bounds).

        converged: optional (S,) bool certification mask.  A prob>0
        scenario without a certificate contributes -inf (minimization),
        so an uncertified solve can never publish a finite bound —
        the conservative analog of the reference's solver-status gate
        (spopt.py:175-194).  Use solve_loop(certify=True) to obtain
        the mask."""
        vals = self.batch.prob * dual_objs
        if converged is not None:
            bad = (~converged) & (self.batch.prob > 0)
            vals = jnp.where(bad, -jnp.inf, vals)
        return jnp.sum(vals)

    def reset_solve_stats(self):
        """Zero the FLOP/wall accumulators (benchmarks call this after
        compile warmup so the reported MFU covers the timed region)."""
        self._flops = 0.0
        self._solve_wall = 0.0
        self._certify_wall = 0.0
        self._kernel_iters = 0
        self._solve_times = []
        self._restarts_total = 0
        self._flops_saved = 0.0
        self._active_traj = []
        self._active_fraction = 1.0
        self._promotions = 0
        self._sparse_matvecs = 0

    def _kernel_dtype(self):
        """dtype the hot-loop matvec FLOPs actually execute in: the hot
        STORAGE dtype when configured (bf16 for bf16x — that is the
        multiply datapath), else the batch dtype."""
        from .ops.pdhg import HOT_DTYPES
        if self.solver.hot_dtype is not None:
            return HOT_DTYPES[self.solver.hot_dtype][0]
        return str(jnp.asarray(self.batch.c).dtype)

    def pdhg_stats(self):
        """Adaptive-work counters across all solve_loop calls since the
        last reset: total inner iterations, restart events, estimated
        FLOPs saved by compaction, the final active fraction, the last
        compacted solve's active-fraction trajectory (one entry per
        segment), plus the precision/sparsity state: the configured
        hot dtype, solves promoted to full precision, sparse matvec
        count and the shared-block density (None when the prep carries
        no split matrix).  bench.py surfaces these."""
        return {
            "inner_iters": int(self._kernel_iters),
            "restarts_total": int(self._restarts_total),
            "flops_saved": float(self._flops_saved),
            "active_fraction_final": float(self._active_fraction),
            "active_fraction_traj": list(self._active_traj),
            "hot_dtype": self.solver.hot_dtype,
            "promotions_total": int(self._promotions),
            "sparse_matvecs": int(self._sparse_matvecs),
            "shared_nnz_frac": self._shared_nnz_frac,
        }

    def solve_stats(self):
        """Accumulated kernel FLOPs / wall-clock / MFU across all
        solve_loop calls (dtiming analog, extended with hardware
        utilization — see utils/mfu.py).  The MFU peak is dtype-aware:
        a hot-dtype run is measured against the low-precision peak its
        matvecs actually target."""
        dev = jax.devices()[0]
        dt = self._kernel_dtype()
        u = _mfu.mfu(self._flops, self._solve_wall, dev, dtype=dt)
        _mfu.record_to_registry(self._tel.registry, self._flops,
                                self._solve_wall,
                                kernel_iters=self._kernel_iters,
                                device=dev, dtype=dt)
        return {
            "flops": self._flops,
            "solve_wall_s": self._solve_wall,
            "certify_wall_s": self._certify_wall,
            "mfu": u,
            "dtype": dt,
            "device": getattr(dev, "device_kind", dev.platform),
        }

    def feas_prob(self, res, tol=None):
        """Probability mass of scenarios whose solve is feasible/
        converged (reference spopt.py:411 feas_prob; :175-194
        classifies solver status).  First-order analog: primal residual
        under tolerance.  The tolerance tracks the DYNAMIC solver_eps
        (Gapper schedules it per iteration), not the construction-time
        eps — a deliberately loose early solve is not 'infeasible'."""
        tol = tol or 10 * float(self.solver_eps)
        ok = res.pres < tol
        return float(jnp.sum(jnp.where(ok, self.batch.prob, 0.0)))

    def infeas_prob(self, res, tol=None):
        return 1.0 - self.feas_prob(res, tol)

    @property
    def is_lp(self):
        """True when every subproblem is an LP (no quadratic term)."""
        hit = self._np_cache.get("_is_lp")
        if hit is None:
            hit = not bool(jnp.any(self.batch.qdiag != 0))
            self._np_cache["_is_lp"] = hit
        return hit

    def valid_Ebound(self, res):
        """Outer bound that is ALWAYS valid: for LPs with all-finite
        variable boxes the PDHG dual objective equals the Lagrangian
        g(y) exactly at ANY iterate, so no certificate is needed;
        otherwise uncertified scenarios are masked to -inf (Ebound)."""
        if self.is_lp and self.all_bounds_finite:
            return self.Ebound(res.dual_obj)
        return self.Ebound(res.dual_obj, converged=res.converged)

    def check_W_bound_supported(self):
        """W-based Lagrangian bounds are valid because the scenario-
        probability-weighted W sums to zero per node (phbase.update_W
        with probability-weighted xbar).  Under variable_probability
        the xbar weights differ from the scenario probabilities, that
        telescoping breaks, and a W-relaxation bound would be WRONG —
        fail loudly (the conservative stance this build takes wherever
        a bound would silently lose validity)."""
        if self.batch.var_prob is not None:
            raise NotImplementedError(
                "W-based Lagrangian bounds are not valid under "
                "variable_probability (prob-weighted W no longer "
                "telescopes to zero per node); use the EF consensus "
                "solve or Iter0's W-free bound instead")

    @property
    def all_bounds_finite(self):
        """True when every variable box is finite — then the PDHG dual
        objective is an exact Lagrangian value for ANY dual iterate
        (no infinite-bound reduced-cost mass to drop), so Ebound is
        valid without a convergence certificate."""
        hit = self._np_cache.get("_bounds_finite")
        if hit is None:
            hit = bool(jnp.all(jnp.isfinite(self.batch.lb))
                       and jnp.all(jnp.isfinite(self.batch.ub)))
            self._np_cache["_bounds_finite"] = hit
        return hit

    def avg_min_max(self, vals):
        """Prob>0-masked avg/min/max of a per-scenario quantity
        (reference spopt.py:469)."""
        mask = self.batch.prob > 0
        v = np.asarray(vals)
        vm = v[np.asarray(mask)]
        return float(np.mean(vm)), float(np.min(vm)), float(np.max(vm))

    # -- xhat evaluation (reduced second-stage solve) ---------------------
    #
    # Fixing nonants via lb=ub=v is how the reference does it (Pyomo
    # var.fix), but it is hostile to a first-order solver: every fixed
    # coordinate reads as "at bound" (blinding the dual residual), the
    # step sizes were tuned for the full operator norm, and — decisive —
    # a candidate averaged from tolerance-accurate scenario solutions
    # violates pure-first-stage rows by ~S*eps absolute, making the
    # equality-fixed problem literally infeasible (measured: xbar on
    # farmer-1000/f32 violates total-acreage by ~0.05; f64 PDHG then
    # pins pres at 1e-5 forever with gap ~0.7).  Commercial solvers
    # absorb this with an absolute feasibility tolerance; we do the
    # equivalent, structurally: ELIMINATE the fixed columns
    # (row bounds -= A_na @ v, objective const += c_na @ v), solve the
    # well-scaled reduced problem with its own Ruiz prep, and widen the
    # reduced row bounds by a relative feastol (option "xhat_feastol",
    # default 1e-5 — the analog of Gurobi FeasibilityTol).

    @staticmethod
    def _shift_and_widen_rows(prep, row_lo, row_hi, shift, ftol):
        """Shared by evaluate_xhat and evaluate_candidates: shift the
        row bounds by the fixed-column contribution and widen by the
        feastol slack (at the scale of |shift| ~ |A_na @ v|, see the
        block comment above _xhat_cache), then rebuild the scaled prep
        row bounds.  ONE implementation so the certified single-
        candidate path and the stacked screening path can never
        disagree about a candidate's feasibility."""
        slack = ftol * (1.0 + jnp.abs(shift))
        rlo = row_lo - shift
        rhi = row_hi - shift
        rlo = jnp.where(jnp.isfinite(rlo),
                        rlo - slack - ftol * (1.0 + jnp.abs(rlo)), rlo)
        rhi = jnp.where(jnp.isfinite(rhi),
                        rhi + slack + ftol * (1.0 + jnp.abs(rhi)), rhi)
        prep2 = dataclasses.replace(
            prep,
            row_lo=jnp.where(jnp.isfinite(rlo), rlo * prep.d_row, rlo),
            row_hi=jnp.where(jnp.isfinite(rhi), rhi * prep.d_row, rhi))
        return prep2, rlo, rhi

    def _xhat_cache(self, upto_stage=None):
        key = ("xhat_red", upto_stage)
        hit = self._np_cache.get(key)
        if hit is not None:
            return hit
        b = self.batch
        na = np.asarray(b.nonant_idx)
        pos = np.arange(na.size)
        if upto_stage is not None:
            stage = np.asarray(b.tree.stage_of)
            pos = np.flatnonzero(stage <= upto_stage)
            na = na[pos]
        nai = jnp.asarray(na, jnp.int32)
        delta = delta_idx(b)
        if b.split_A:
            # split-native batch: the reduced system exists only if
            # every scenario-varying entry sits in an ELIMINATED column
            # (farmer: yields multiply the nonant acreages) — then
            # A_red is the scenario-independent shared matrix with the
            # nonant columns dropped, and the per-scenario part lives
            # entirely in the A_na row-bound shift, expressed as a
            # SplitA over the REDUCED (Kf-wide) column space
            cols_np = np.asarray(b.A.cols)
            if not np.all(np.isin(cols_np, na)):
                raise NotImplementedError(
                    "xhat evaluation on a split-native batch requires "
                    "all A-delta columns to be eliminated (nonant) "
                    "columns; this batch has deltas in kept columns")
            pos_of = np.zeros(b.num_vars, np.int64)
            pos_of[na] = np.arange(na.size)
            A_na = SplitA(
                shared=jnp.asarray(b.A.shared)[:, nai],   # (M, Kf)
                rows=jnp.asarray(b.A.rows, jnp.int32),
                cols=jnp.asarray(pos_of[cols_np], jnp.int32),
                vals=b.A.vals)
            A_red = jnp.asarray(b.A.shared)[None].at[:, :, nai].set(0.0)
        elif (delta is not None and not b.shared_A
                and not self.options.get("no_split_prep")
                and np.all(np.isin(np.asarray(delta[1]), na))):
            # every scenario-varying matrix entry sits in an ELIMINATED
            # column (farmer: yields multiply the nonant acreages), so
            # the reduced system is scenario-independent — store it
            # (1, M, N) and every downstream solve rides the shared-A
            # matmul fast path (the per-scenario part lives entirely in
            # the A_na shift of the row bounds)
            A_na = jnp.take(b.A, nai, axis=2)          # (S, M, Kf)
            A_red = jnp.asarray(b.A[0:1]).at[:, :, nai].set(0.0)
        else:
            A_na = jnp.take(b.A, nai, axis=2)          # (S, M, Kf)
            A_red = jnp.asarray(b.A).at[:, :, nai].set(0.0)
        c_na = jnp.take(b.c, nai, axis=1)
        q_na = jnp.take(b.qdiag, nai, axis=1)
        c_red = jnp.asarray(b.c).at[:, nai].set(0.0)
        q_red = jnp.asarray(b.qdiag).at[:, nai].set(0.0)
        lb_red = jnp.asarray(b.lb).at[:, nai].set(0.0)
        ub_red = jnp.asarray(b.ub).at[:, nai].set(0.0)
        prep = prepare_batch(A_red, b.row_lo, b.row_hi)
        # FeasibilityTol analog, scaled to the accuracy of the solves
        # that GENERATE candidates (the loosest of the solver eps and
        # the PH hot-loop superstep_eps): a candidate averaged from
        # eps-accurate solutions violates first-stage rows by ~eps
        # relative, so a few eps of slack absorbs it; a fixed large
        # default would grant the reduced LP real objective slack
        # (measured: ftol=1e-5 at f64/eps=1e-7 made inner bounds
        # ~4e-5 optimistic)
        gen_eps = max(self.solver.eps,
                      float(self.options.get("superstep_eps") or 0.0))
        ftol = float(self.options.get(
            "xhat_feastol", min(1e-3, 3.0 * gen_eps)))

        def impl(vals, x0, y0, eps):
            vals2 = jnp.broadcast_to(
                jnp.atleast_2d(vals), (b.num_scens, na.size)
            ).astype(b.c.dtype)
            shift = bmatvec(A_na, vals2)
            prep2, rlo, rhi = self._shift_and_widen_rows(
                prep, b.row_lo, b.row_hi, shift, ftol)
            oc = (b.obj_const + jnp.sum(c_na * vals2, axis=1)
                  + 0.5 * jnp.sum(q_na * vals2 * vals2, axis=1))
            return self.solver._solve_impl(
                prep2, c_red, q_red, lb_red, ub_red, oc, x0, y0,
                None, eps), (rlo, rhi, oc)

        hit = {"na": na, "pos": pos, "A_na": A_na, "A_red": A_red,
               "c_red": c_red,
               "q_red": q_red, "lb_red": lb_red, "ub_red": ub_red,
               "prep": prep, "jit": jax.jit(impl), "impl": impl,
               "ftol": ftol}
        self._np_cache[key] = hit
        return hit

    def evaluate_xhat(self, nonant_values, upto_stage=None, tol=None,
                      warm="xhat_eval", certify="auto"):
        """Expected objective with nonants fixed to a candidate — the
        implementable inner bound (reference utils/xhat_eval.py:293).
        Returns (Eobj, feasible).  Successive evaluations warm-start
        from the named cache (candidates move slowly).

        Validity: the objective at any PRES-FEASIBLE point upper-bounds
        the subproblem optimum regardless of dual convergence, so the
        inner bound needs only primal feasibility (within the
        documented xhat_feastol, the FeasibilityTol analog).
        certify="auto" runs the f64 fallback only when the fast solve
        fails the feasibility check; certify=True always refines
        stragglers."""
        t0 = time.time()
        cache = self._xhat_cache(upto_stage)
        b = self.batch
        # callers pass full-K candidate vectors; slice to the slots the
        # cache eliminates (upto_stage filters to early-stage slots)
        vals = jnp.asarray(nonant_values)[..., jnp.asarray(cache["pos"])]
        x0, y0 = self._named_warm.get(warm, (None, None))
        if x0 is None:
            x0 = jnp.zeros_like(b.c)
            y0 = jnp.zeros_like(b.row_lo)
        res, (rlo, rhi, oc) = cache["jit"](
            vals, x0, y0, self.solver_eps)
        self._flops += _mfu.pdhg_flops(
            int(res.iters), b.num_scens, b.num_rows, b.num_vars,
            self.solver.check_every)
        if certify == "auto":
            certify = not (self.feas_prob(res, tol=tol) > 1.0 - 1e-6)
        if certify:
            res = self._certified_resolve(
                res, c=cache["c_red"], qdiag=cache["q_red"],
                lb=cache["lb_red"], ub=cache["ub_red"],
                A=cache["A_red"], row_lo=rlo, row_hi=rhi,
                obj_const=oc, prep_key=("_prep64_xhat", upto_stage))
        self._named_warm[warm] = (res.x, res.y)
        feas = self.feas_prob(res, tol=tol) > 1.0 - 1e-6
        eobj = float(self.Eobjective(res.obj))
        self._solve_wall += time.time() - t0
        return eobj, feas

    def evaluate_candidates(self, candidates, tol=None,
                            warm="xhat_candidates", eps=None,
                            iters_cap=None, return_mass=False):
        """Evaluate k candidates in ONE stacked kernel launch:
        candidates (k, K) -> (Eobjs (k,), feas (k,)).

        The reduced problem is tiled k-fold along the scenario axis —
        the speculative-parallelism axis of the reference's xhat spokes
        (SURVEY.md §2.10) made literal batching.

        This is a SCREENING pass (no f64 certification on the stacked
        system): pres-based feasibility only.  Certify the winning
        candidate's bound with evaluate_xhat — calculate_incumbent
        (utils/xhat_eval.py) does exactly that.

        eps / iters_cap: per-call solver tolerance and traced
        iteration budget.  Rank-only callers (uc.one_opt_commitment
        sweeps) pass a loose eps and a small cap so one launch costs a
        fraction of a full-accuracy solve; pair with a looser `tol` so
        a capped solve's residuals still count as feasible."""
        cands = np.asarray(candidates)
        k, K = cands.shape
        b = self.batch
        cache = self._xhat_cache(None)
        tkey = ("xhat_stack", k)
        # one live stack only: each holds a k-fold tiling of the full
        # constraint tensor, so letting every distinct k accrete its
        # own copy would grow device memory without bound
        for stale in [key for key in self._np_cache
                      if isinstance(key, tuple) and key
                      and key[0] == "xhat_stack" and key != tkey]:
            del self._np_cache[stale]
        stack = self._np_cache.get(tkey)
        if stack is None:
            S_all = b.num_scens

            def tile(a):
                # shared-A leaves (shape (1, ...)) serve every stacked
                # candidate as-is; per-scenario leaves tile k-fold.
                # A SplitA tiles its per-scenario delta values only
                if isinstance(a, SplitA):
                    return SplitA(shared=a.shared, rows=a.rows,
                                  cols=a.cols, vals=tile(a.vals))
                if a.shape[0] == 1 and S_all > 1:
                    return a
                return jnp.tile(a, (k,) + (1,) * (a.ndim - 1))
            prep = cache["prep"]
            nai = jnp.asarray(cache["na"], jnp.int32)
            stack = {
                "A_na": tile(cache["A_na"]),
                "c_na": tile(jnp.take(b.c, nai, axis=1)),
                "q_na": tile(jnp.take(b.qdiag, nai, axis=1)),
                "c_red": tile(cache["c_red"]), "q_red": tile(cache["q_red"]),
                "lb_red": tile(cache["lb_red"]), "ub_red": tile(cache["ub_red"]),
                "row_lo": tile(b.row_lo), "row_hi": tile(b.row_hi),
                "obj_const": tile(b.obj_const),
                "prob": tile(b.prob),
                "prep": dataclasses.replace(
                    prep, A=tile(prep.A), row_lo=tile(prep.row_lo),
                    row_hi=tile(prep.row_hi), d_row=tile(prep.d_row),
                    d_col=tile(prep.d_col), anorm=tile(prep.anorm)),
            }
            ftol = cache["ftol"]

            def impl(vals_ks, x0, y0, eps, iters_cap=None):
                # vals_ks: (k, K) -> (k*S, K)
                vals2 = jnp.repeat(vals_ks, b.num_scens, axis=0).astype(
                    b.c.dtype)
                shift = bmatvec(stack["A_na"], vals2)
                prep2, rlo, rhi = self._shift_and_widen_rows(
                    stack["prep"], stack["row_lo"], stack["row_hi"],
                    shift, ftol)
                oc = (stack["obj_const"]
                      + jnp.sum(stack["c_na"] * vals2, axis=1)
                      + 0.5 * jnp.sum(stack["q_na"] * vals2 * vals2,
                                      axis=1))
                res = self.solver._solve_impl(
                    prep2, stack["c_red"], stack["q_red"],
                    stack["lb_red"], stack["ub_red"], oc, x0, y0, None,
                    eps, iters_cap)
                objs = jnp.sum(
                    (stack["prob"] * res.obj).reshape(k, b.num_scens),
                    axis=1)
                return res, objs

            stack["jit"] = jax.jit(impl)
            self._np_cache[tkey] = stack
        t0 = time.time()
        x0, y0 = self._named_warm.get(warm, (None, None))
        if x0 is None or x0.shape[0] != k * b.num_scens:
            x0 = jnp.zeros_like(stack["c_red"])
            y0 = jnp.zeros_like(stack["row_lo"])
        if eps is None:
            eps = self.solver_eps
        else:
            eps = jnp.asarray(eps, b.c.dtype)
        if iters_cap is not None:
            iters_cap = jnp.asarray(iters_cap, jnp.int32)
        res, objs = stack["jit"](jnp.asarray(cands), x0, y0,
                                 eps, iters_cap)
        jax.block_until_ready(res.x)
        self._flops += _mfu.pdhg_flops(
            int(res.iters), k * b.num_scens, b.num_rows, b.num_vars,
            self.solver.check_every)
        self._solve_wall += time.time() - t0
        self._named_warm[warm] = (res.x, res.y)
        tol = tol or 10 * float(self.solver_eps)
        ok = (np.asarray(res.pres) < tol).reshape(k, b.num_scens)
        live = np.asarray(b.prob) > 0
        feas = np.all(ok | ~live[None, :], axis=1)
        if return_mass:
            # per-candidate feasible probability mass — the diagnostic
            # for "feasible for MOST scenarios but screened out":
            # near-1 mass with feas=False means straggler solves, not
            # an infeasible candidate.  Mass is the fraction of TOTAL
            # probability mass: batch builders normalize prob to 1
            # (stack_scenarios does so explicitly; pads carry 0), so
            # this is a probability — the divisor guard only protects
            # degenerate all-zero test batches, where mass is 0 anyway
            prob = np.asarray(b.prob)
            mass = (ok * prob[None, :]).sum(axis=1) / max(prob.sum(),
                                                          1e-12)
            return np.asarray(objs), feas, mass
        return np.asarray(objs), feas

    # -- nonant fixing (reference spopt.py:592-740 _fix_nonants) ----------
    def fixed_nonant_bounds(self, values, upto_stage=None):
        """Bounds arrays that pin nonant slots to `values`.

        values: (K,) to pin all scenarios alike, or (S, K) per-scenario
        (multistage candidate trees).  upto_stage: only fix slots whose
        stage <= upto_stage (reference xhat_eval.py:326
        fix_nonants_upto_stage).
        Returns (lb, ub).
        """
        b = self.batch
        vals = jnp.asarray(values)
        if vals.ndim == 1:
            vals = jnp.broadcast_to(vals[None, :],
                                    (b.num_scens, b.num_nonants))
        lb = b.lb.at[:, b.nonant_idx].set(vals)
        ub = b.ub.at[:, b.nonant_idx].set(vals)
        if upto_stage is not None:
            stage = jnp.asarray(b.tree.stage_of, jnp.int32)
            keep = stage <= upto_stage
            lb = lb.at[:, b.nonant_idx].set(
                jnp.where(keep[None, :], vals, b.lb[:, b.nonant_idx]))
            ub = ub.at[:, b.nonant_idx].set(
                jnp.where(keep[None, :], vals, b.ub[:, b.nonant_idx]))
        return lb, ub

"""streaming — minibatch randomized PH for million-scenario problems.

The scenario universe never materializes on device (or even on host):
a `ScenarioSource` builds scenario blocks on demand from their index
sets, a `ScenarioStream` double-buffers block build + host->device
transfer behind the solves, an `AdaptiveSampler` grows the active
sample along a BM/BPL sequential-sampling schedule, and `StreamingPH`
runs randomized PH supersteps over sampled blocks with full-S dual
weights host-resident — stopping when the gap estimate certifies a
confidence interval.  doc/src/streaming.md is the chapter.

Import layering (AST-guarded in tests/test_streaming.py): this package
and its host-path modules (source, stream, sampler) never import jax
at module level — `StreamingPH` itself is loaded lazily on first
attribute access.
"""

from .sampler import AdaptiveSampler
from .source import (BatchSource, GeneratorSource, ScenarioSource,
                     gather_block, source_for_module)
from .stream import ScenarioStream, StreamClosed

__all__ = [
    "AdaptiveSampler",
    "BatchSource",
    "GeneratorSource",
    "ScenarioSource",
    "ScenarioStream",
    "StreamClosed",
    "StreamingPH",
    "gather_block",
    "source_for_module",
]


def __getattr__(name):
    if name == "StreamingPH":
        from .streaming_ph import StreamingPH
        return StreamingPH
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")

"""streaming — minibatch randomized PH for million-scenario problems.

The scenario universe never materializes on device (or even on host):
a `ScenarioSource` builds scenario blocks on demand from their index
sets, a `ScenarioStream` double-buffers block build + host->device
transfer behind the solves, an `AdaptiveSampler` grows the active
sample along a BM/BPL sequential-sampling schedule, and `StreamingPH`
runs randomized PH supersteps over sampled blocks with full-S dual
weights host-resident — stopping when the gap estimate certifies a
confidence interval.  doc/src/streaming.md is the chapter.

At storage scale, `write_corpus` persists a source's universe as
checksummed fixed-width shard files and `ShardSource` streams sampled
blocks back off disk through a bounded readahead, with per-shard
retry/quarantine and certified-gap accounting for lost mass
(store.py / readahead.py — the durable-corpus layer).

Import layering (AST-guarded in tests/test_streaming.py and
tests/test_shard_store.py): this package and its host-path modules
(source, stream, sampler, store, readahead) never import jax at
module level — `StreamingPH` itself is loaded lazily on first
attribute access.
"""

from .readahead import ReadaheadCache, ShardSource
from .sampler import AdaptiveSampler
from .source import (BatchSource, GeneratorSource, ScenarioSource,
                     gather_block, source_for_module)
from .store import (QuarantinedCorpusError, ShardIntegrityError,
                    ShardQuarantinedError, ShardStore, ShardStoreError,
                    write_corpus)
from .stream import ScenarioStream, StreamClosed

__all__ = [
    "AdaptiveSampler",
    "BatchSource",
    "GeneratorSource",
    "QuarantinedCorpusError",
    "ReadaheadCache",
    "ScenarioSource",
    "ScenarioStream",
    "ShardIntegrityError",
    "ShardQuarantinedError",
    "ShardSource",
    "ShardStore",
    "ShardStoreError",
    "StreamClosed",
    "StreamingPH",
    "gather_block",
    "source_for_module",
    "write_corpus",
]


def __getattr__(name):
    if name == "StreamingPH":
        from .streaming_ph import StreamingPH
        return StreamingPH
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")

"""Bounded readahead over a ShardStore + the ShardSource adapter.

`ReadaheadCache` is the storage analog of ScenarioStream's double
buffer: a single daemon reader pulls upcoming shard ids off a bounded
prefetch queue and parks validated batches in a small LRU, so by the
time the stream worker gathers a block, its shards are (ideally)
already resident — shard reads hide behind solves exactly like block
builds do.  Effectiveness is measured, not assumed:

  * `store.readahead_hits` / `store.readahead_misses` — was the shard
    already known to the prefetcher when demanded?
  * `store.readahead_hit_rate` gauge — running hit fraction;
  * `store.read_wait_seconds` histogram — seconds the demanding thread
    actually blocked per shard fetch (~0 when readahead fully overlaps).

`ShardSource` adapts a ShardStore to the ScenarioSource protocol: it
substitutes quarantined seed indices deterministically, groups the
served indices by shard, drives every read through the cache (which
drives every read through `ShardStore.read_checked` — no unvalidated
bytes reach a block), gathers each shard's contribution and
concatenates them block-uniform.  Its `block_with_indices` returns the
indices ACTUALLY served so the stream absorbs substituted blocks under
the right scatter rows.

Laziness contract (AST-guarded in tests/test_shard_store.py): no
module-level jax import — same rule as the rest of streaming/.
"""

from __future__ import annotations

import collections
import threading
import time

import numpy as np

from .. import telemetry as _telemetry
from .source import ScenarioSource, gather_block
from .store import (QuarantinedCorpusError, ShardQuarantinedError,
                    ShardStore, ShardStoreError, concat_blocks)


class ReadaheadCache:
    """Depth-bounded prefetch queue + LRU of validated shard batches,
    serviced by ONE daemon reader (the store's reads are serialized by
    construction, matching its thread-safety contract).

    `schedule(sids)` is the best-effort HINT path (drops work past the
    depth cap rather than queueing unboundedly); `get(sid)` is the
    DEMAND path (enqueues unconditionally and blocks until the read
    lands).  Read errors are cached as poisoned entries, re-raised to
    the demander, and dropped — a later substitution pass never sees a
    stale failure."""

    def __init__(self, store, depth=4, capacity=None, telemetry=None):
        self.store = store
        self.depth = max(1, int(depth))
        self.capacity = (int(capacity) if capacity
                         else max(2 * self.depth, 8))
        self._tel = (telemetry if telemetry is not None
                     else _telemetry.get())
        self._cond = threading.Condition()
        self._queue = collections.deque()
        self._pending = set()          # queued or in-flight shard ids
        self._cache = collections.OrderedDict()  # sid -> (kind, value)
        self._closed = False
        self.hits = 0
        self.misses = 0
        self.wait_seconds = 0.0
        self._thread = threading.Thread(
            target=self._run, name="shard-readahead", daemon=True)
        self._thread.start()

    # -- reader thread ----------------------------------------------------
    def _run(self):
        while True:
            with self._cond:
                while not self._queue and not self._closed:
                    self._cond.wait()
                if self._closed:
                    return
                sid = self._queue.popleft()
            try:
                entry = ("ok", self.store.read_checked(sid))
            except BaseException as e:     # noqa: BLE001 - relayed
                entry = ("err", e)
            with self._cond:
                self._cache[sid] = entry
                self._cache.move_to_end(sid)
                # LRU-evict, but never a shard someone still awaits
                while len(self._cache) > self.capacity:
                    for old in self._cache:
                        if old != sid:
                            del self._cache[old]
                            break
                    else:
                        break
                self._pending.discard(sid)
                self._cond.notify_all()

    # -- hint path --------------------------------------------------------
    def schedule(self, sids):
        """Queue upcoming shard ids for prefetch; silently drops the
        tail past the depth cap (a hint is best-effort — demand reads
        bypass the cap)."""
        with self._cond:
            if self._closed:
                return
            for sid in sids:
                sid = int(sid)
                if sid in self._cache or sid in self._pending:
                    continue
                if len(self._pending) >= self.depth:
                    break
                self._pending.add(sid)
                self._queue.append(sid)
            self._cond.notify_all()

    # -- demand path ------------------------------------------------------
    def get(self, sid):
        """Return shard `sid`'s validated batch, blocking until the
        reader lands it.  Counts a HIT when the shard was already
        known to the prefetcher (resident or in flight) — the signal
        that the hint pipeline saw this demand coming."""
        sid = int(sid)
        t0 = time.monotonic()
        with self._cond:
            if sid in self._cache or sid in self._pending:
                self.hits += 1
                hit = True
            else:
                self.misses += 1
                hit = False
            while True:
                entry = self._cache.get(sid)
                if entry is not None:
                    break
                if self._closed:
                    raise ShardStoreError(
                        "readahead cache closed while a demand read "
                        f"for shard {sid} was outstanding")
                if sid not in self._pending:
                    self._pending.add(sid)
                    self._queue.append(sid)
                self._cond.notify_all()
                self._cond.wait()
            kind, value = entry
            self._cache.move_to_end(sid)
            if kind == "err":
                del self._cache[sid]
        waited = time.monotonic() - t0
        self.wait_seconds += waited
        if self._tel.enabled:
            r = self._tel.registry
            r.counter("store.readahead_hits" if hit
                      else "store.readahead_misses").inc()
            r.gauge("store.readahead_hit_rate").set(self.hit_rate)
            r.histogram("store.read_wait_seconds").observe(waited)
        if kind == "err":
            raise value
        return value

    @property
    def hit_rate(self):
        n = self.hits + self.misses
        return self.hits / n if n else 0.0

    def stats(self):
        return {
            "readahead_hits": int(self.hits),
            "readahead_misses": int(self.misses),
            "readahead_hit_rate": float(self.hit_rate),
            "read_wait_seconds": float(self.wait_seconds),
            "readahead_depth": int(self.depth),
        }

    def close(self):
        with self._cond:
            self._closed = True
            self._queue.clear()
            self._pending.clear()
            self._cond.notify_all()
        self._thread.join(timeout=5.0)


class ShardSource(ScenarioSource):
    """ScenarioSource over an on-disk shard corpus.

    Block service pipeline (all host-side, runs on the stream worker):
      1. `substitute_quarantined` — indices in quarantined shards are
         deterministically resampled from healthy ones;
      2. group the served indices by shard, `schedule` them all, then
         `get` each (validated, readahead-overlapped);
      3. `gather_block` each shard's contribution, `concat_blocks`
         into ONE block-uniform batch.
    A shard quarantined MID-block restarts the pipeline from the
    ORIGINAL index set against the grown quarantine set — substitution
    is a pure function of (indices, quarantine set), which is what
    makes a crash-resumed run (quarantine set restored from the
    storage cursor) replay identical blocks."""

    def __init__(self, store, depth=4, name=None, telemetry=None,
                 **store_kw):
        if not isinstance(store, ShardStore):
            store = ShardStore(store, telemetry=telemetry, **store_kw)
        self.store = store
        self.name = str(name if name is not None else store.model)
        self.total_scens = int(store.total_scens)
        self.readahead = ReadaheadCache(store, depth=depth,
                                        telemetry=telemetry)

    # -- ScenarioSource protocol ------------------------------------------
    def block_with_indices(self, indices):
        orig = np.sort(np.asarray(indices, dtype=np.int64))
        if orig.size == 0:
            raise ValueError("empty scenario block")
        if orig[0] < 0 or orig[-1] >= self.total_scens:
            raise IndexError(
                f"block indices out of range [0, {self.total_scens})")
        store = self.store
        for _ in range(store.n_shards + 1):
            served = store.substitute_quarantined(orig)
            sids = np.unique(served // store.shard_width)
            self.readahead.schedule(int(s) for s in sids)
            parts = []
            try:
                for sid in sids:
                    sid = int(sid)
                    shard = self.readahead.get(sid)
                    lo, _hi = store.shard_range(sid)
                    local = served[served // store.shard_width
                                   == sid] - lo
                    parts.append(gather_block(shard, local))
            except ShardQuarantinedError:
                continue       # re-substitute against the grown set
            return served, concat_blocks(parts)
        raise QuarantinedCorpusError(
            "block service could not converge: every substitution "
            "round quarantined another shard")

    def block(self, indices):
        return self.block_with_indices(indices)[1]

    def note_upcoming(self, indices):
        """Readahead hint: schedule the shards the NEXT block will
        demand.  Substitution runs in dry-run mode (count=False) so
        the hint path never double-counts resampled indices."""
        idx = np.sort(np.asarray(indices, dtype=np.int64))
        if idx.size == 0:
            return
        served = self.store.substitute_quarantined(idx, count=False)
        self.readahead.schedule(
            int(s) for s in np.unique(served // self.store.shard_width))

    def names(self, indices):
        fmt = self.store.meta.get("name_format")
        if fmt:
            return [fmt.format(i=int(i), i1=int(i) + 1)
                    for i in np.asarray(indices)]
        return super().names(indices)

    def stats(self):
        out = self.store.stats()
        out.update(self.readahead.stats())
        return out

    def close(self):
        self.readahead.close()

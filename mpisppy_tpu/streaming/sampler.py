"""AdaptiveSampler — the active sample and its growth schedule.

Adaptive-sampling PH (PAPERS.md, arXiv:2407.20944) maintains an ACTIVE
sample of the scenario universe and grows it with the same sequential-
sampling schedules that certify the stop: the BM (Bayraksan-Morton)
and BPL (Bayraksan-Pierre-Louis) rules, refactored standalone into
`confidence_intervals.seqsampling.SamplingRule` exactly so this class
can inject externally-estimated gaps into them.

The active sample is the index PREFIX [0, active_n) of the universe —
sources draw scenario i's data from seed i, so a prefix is an i.i.d.
sample and GROWING it preserves every already-streamed scenario
(monotone growth = no wasted solves, and the monotonicity test rides
on it).  Blocks are uniform without-replacement draws from the active
prefix via a PCG64 generator whose full state round-trips through
checkpoints as JSON — bit-equal resume of the draw sequence.

No jax anywhere (AST-guarded): this is pure host bookkeeping.
"""

from __future__ import annotations

import json

import numpy as np

from .. import telemetry as _telemetry


class AdaptiveSampler:
    """Block draws from a growing active prefix of the universe."""

    def __init__(self, rule, total_scens, block_size, seed=0,
                 telemetry=None):
        self.rule = rule
        self.total_scens = int(total_scens)
        self.block_size = int(block_size)
        self._tel = (telemetry if telemetry is not None
                     else _telemetry.get())
        self._rng = np.random.Generator(np.random.PCG64(int(seed)))
        # first-round sample size from the rule's own schedule
        # (BM: ceil(c/h'h''); BPL: the fixed-width floor) — clamped so
        # at least one full block is active when the universe allows
        n1 = int(rule.sample_size(1, None, None, None))
        self.active_n = min(self.total_scens,
                            max(n1, min(self.block_size,
                                        self.total_scens)))
        self.est_rounds = 0        # completed gap-estimate rounds
        self.growth_events = 0
        self._gauge()

    def _gauge(self):
        if self._tel.enabled:
            self._tel.registry.gauge(
                "stream.active_sample_size").set(self.active_n)

    # -- draws ------------------------------------------------------------
    def draw_block(self):
        """Uniform without-replacement draw from the active prefix,
        sorted ascending (gathers like monotone index sets; sampling-
        theoretic properties are permutation-invariant)."""
        b = min(self.block_size, self.active_n)
        idx = self._rng.choice(self.active_n, size=b, replace=False)
        idx.sort()
        return idx.astype(np.int64)

    # -- growth -----------------------------------------------------------
    def observe(self, G, s):
        """Feed one gap estimate (G, s) measured on the current active
        sample.  Returns True when the rule says STOP (certified);
        otherwise grows the active prefix along the rule's schedule
        (monotone, capped at the universe) and returns False."""
        self.est_rounds += 1
        nk = self.active_n
        if not self.rule.should_continue(G, s, nk):
            return True
        new_n = int(self.rule.sample_size(
            self.est_rounds + 1, G, s, nk))
        new_n = min(max(new_n, nk), self.total_scens)
        if new_n > nk:
            self.active_n = new_n
            self.growth_events += 1
            self._gauge()
            if self._tel.enabled:
                self._tel.registry.counter(
                    "stream.sample_growth_events").inc()
                self._tel.event("stream.sample_growth",
                                from_n=nk, to_n=new_n, G=float(G),
                                s=float(s))
        return False

    # -- checkpoint round-trip --------------------------------------------
    def state(self):
        """JSON-serializable state: active size, estimate round count,
        and the FULL PCG64 state (bit-equal draw replay on restore)."""
        return {
            "active_n": int(self.active_n),
            "est_rounds": int(self.est_rounds),
            "rng_state": json.dumps(self._rng.bit_generator.state),
        }

    def restore(self, state):
        self.active_n = int(state["active_n"])
        self.est_rounds = int(state["est_rounds"])
        self._rng.bit_generator.state = json.loads(state["rng_state"])
        self._gauge()

"""ScenarioSource — the seed-indexed scenario factory protocol.

The models already carry the pattern informally: `farmer.
scenario_yields(scennum, seedoffset)` draws scenario `scennum`'s data
from `RandomState(scennum + seedoffset)`, so ANY subset of the
scenario universe can be materialized from its index set alone.  This
module promotes that into a protocol the streaming layer can drive:

  * `ScenarioSource`    — abstract: `block(indices) -> ScenarioBatch`
    materializing exactly those scenarios (block-uniform probabilities
    summing to 1, so each block is a valid sampled batch on its own);
  * `GeneratorSource`   — wraps an index-parameterized builder (the
    `scenario_block(indices, **kw)` functions in models/farmer.py and
    models/uc.py); the full S-scenario tensor NEVER materializes, which
    is what opens S=1,000,000 runs;
  * `BatchSource`       — wraps an already-built ScenarioBatch (host-
    resident shard) and gathers blocks out of it — the fallback for
    models without an index-parameterized builder (models/aircond.py's
    tree build) and for tests comparing streamed vs. resident runs.

Laziness contract (AST-guarded in tests/test_streaming.py): this
module never imports jax at module level — block construction runs on
the stream's worker thread against host numpy, and the host side of
the pipeline must be importable (and cheap) without touching the
accelerator runtime.  The `ScenarioBatch` container type is imported
lazily inside the functions that construct one.
"""

from __future__ import annotations

import dataclasses

import numpy as np


class ScenarioSource:
    """Protocol: materialize scenario blocks of a fixed universe on
    demand.  `total_scens` is the size of the scenario universe S;
    `block(indices)` returns a ScenarioBatch holding exactly those
    scenarios with BLOCK-uniform probabilities (each block is a valid
    sampled batch: probs sum to 1, so SPBase accepts it and
    expectations over a block are sample means)."""

    name = "source"
    total_scens = 0

    def block(self, indices):
        raise NotImplementedError

    def names(self, indices):
        """Scenario names of an index set (default: the batch's own)."""
        return list(self.block(np.asarray(indices)).tree.scen_names)


class GeneratorSource(ScenarioSource):
    """A source backed by an index-parameterized builder function —
    `block_fn(indices) -> ScenarioBatch` (models expose these as
    `scenario_block`; `source_for_module` wires the kwargs).  Blocks
    are pure functions of the index set: the builders seed per-scenario
    RNG from the GLOBAL index (`RandomState(i + seedoffset)`), so
    scenario i's data is identical no matter which block it rides in —
    the property checkpoint/resume and the parity tests lean on."""

    def __init__(self, name, total_scens, block_fn, name_fn=None):
        self.name = name
        self.total_scens = int(total_scens)
        self._block_fn = block_fn
        self._name_fn = name_fn

    def block(self, indices):
        idx = np.asarray(indices, dtype=np.int64)
        if idx.size == 0:
            raise ValueError("empty scenario block")
        if idx.min() < 0 or idx.max() >= self.total_scens:
            raise IndexError(
                f"block indices out of range [0, {self.total_scens})")
        return self._block_fn(idx)

    def names(self, indices):
        if self._name_fn is not None:
            return [self._name_fn(int(i)) for i in np.asarray(indices)]
        return super().names(indices)


def gather_block(batch, indices):
    """Gather a scenario block out of a materialized ScenarioBatch —
    host-side numpy throughout (no jax); probabilities renormalized to
    the block, tree node ids relabeled to the block's own compact node
    universe.

    Leaf policy mirrors parallel/mesh.py's sharding table: scenario-
    leading arrays gather on axis 0; a shared constraint block
    (A.shape[0]==1) passes through unreplicated; a SplitA gathers its
    per-scenario delta values ONLY (the shared matrix + coordinates —
    dense or BCOO — serve every block as-is, the 'never replicate the
    shared block' residency contract); stage_cost_c gathers on its
    scenario axis 1."""
    from ..ir import ScenarioBatch, SplitA, TreeInfo

    idx = np.asarray(indices, dtype=np.int64)
    A = batch.A
    if isinstance(A, SplitA):
        A = dataclasses.replace(A, vals=np.asarray(A.vals)[idx])
    elif np.asarray(A).shape[0] == 1 and batch.num_scens > 1:
        pass                                   # shared: no gather
    else:
        A = np.asarray(A)[idx]
    tree = batch.tree
    node_sub = np.asarray(tree.node_of)[idx]
    uniq, inv = np.unique(node_sub, return_inverse=True)
    prob_sub = np.asarray(tree.prob, np.float64)[idx]
    tot = prob_sub.sum()
    prob_sub = (prob_sub / tot if tot > 0
                else np.full(idx.size, 1.0 / idx.size))
    sub_tree = TreeInfo(
        node_of=inv.reshape(node_sub.shape).astype(np.int32),
        prob=prob_sub,
        num_nodes=int(uniq.size),
        stage_of=tree.stage_of,
        nonant_names=tree.nonant_names,
        scen_names=tuple(np.asarray(tree.scen_names, dtype=object)[idx])
        if tree.scen_names else (),
    )
    take = lambda a: None if a is None else np.asarray(a)[idx]  # noqa: E731
    return ScenarioBatch(
        c=take(batch.c), qdiag=take(batch.qdiag), A=A,
        row_lo=take(batch.row_lo), row_hi=take(batch.row_hi),
        lb=take(batch.lb), ub=take(batch.ub),
        obj_const=take(batch.obj_const),
        nonant_idx=np.asarray(batch.nonant_idx),
        integer_mask=take(batch.integer_mask),
        tree=sub_tree,
        stage_cost_c=(np.asarray(batch.stage_cost_c)[:, idx]
                      if batch.stage_cost_c is not None else None),
        var_prob=take(batch.var_prob),
        var_names=batch.var_names,
        model_meta=batch.model_meta,
    )


class BatchSource(ScenarioSource):
    """A source over an already-materialized ScenarioBatch: blocks are
    gathered views (host numpy copies) of the resident arrays.  This
    is the adapter for models whose scenario universe is built as one
    coupled object (aircond's scenario tree) and the reference source
    for full-S vs. streamed parity tests."""

    def __init__(self, batch, name="batch"):
        self.name = name
        self.batch = batch
        self.total_scens = int(batch.num_scens)

    def block(self, indices):
        idx = np.asarray(indices, dtype=np.int64)
        if idx.size == 0:
            raise ValueError("empty scenario block")
        if idx.min() < 0 or idx.max() >= self.total_scens:
            raise IndexError(
                f"block indices out of range [0, {self.total_scens})")
        return gather_block(self.batch, idx)

    def names(self, indices):
        names = self.batch.tree.scen_names
        return [names[int(i)] for i in np.asarray(indices)]


class SourceBuildError(RuntimeError):
    """A scenario block could not be built within the retry budget.
    Carries the structured failure context — the index set, attempt
    count, the last underlying error, and the full per-attempt
    `retry_state` (attempt number, error string, backoff delay, as
    recorded by RetryingSource.retry_log) — so drivers can log/requeue
    the block instead of parsing a message string."""

    def __init__(self, message, indices=None, attempts=0, last_error=None,
                 retry_state=()):
        super().__init__(message)
        self.indices = (tuple(int(i) for i in np.asarray(indices).ravel())
                        if indices is not None else ())
        self.attempts = int(attempts)
        self.last_error = last_error
        # the attempt/backoff ladder the wrapper actually walked —
        # one {"attempt", "error", "delay"} dict per retried attempt
        self.retry_state = tuple(dict(r) for r in retry_state)


def backoff_delay(attempt, backoff, backoff_cap, jitter=0.0, rng=None):
    """The supervisor restart-ladder value for `attempt`, spread by
    multiplicative +/- `jitter` and re-capped (jitter never pushes a
    delay past backoff_cap).  The ONE backoff policy shared by
    RetryingSource (transient block-build failures) and the shard
    store's read retries (streaming/store.py)."""
    from ..resilience.supervisor import restart_delay
    base = restart_delay(attempt, backoff, backoff_cap)
    if jitter <= 0 or rng is None:
        return base
    spread = base * rng.uniform(-jitter, jitter)
    return min(backoff_cap, max(0.0, base + spread))


class RetryingSource(ScenarioSource):
    """Retry-with-capped-backoff wrapper for transient block build
    failures (a flaky scenario store, an injected chaos fault).  Blocks
    are pure functions of their index set, so a retry is always safe;
    after `retries` failed re-attempts the structured SourceBuildError
    surfaces.  StreamingPH wires this automatically when the options
    carry `source_retries` (with `source_backoff`/`source_backoff_cap`
    shaping the delay like the supervisor's restart ladder).

    Delays carry multiplicative JITTER (default +/- `jitter`=0.25 of
    the ladder value, capped at backoff_cap): a fixed ladder makes
    every concurrent block retry at the same instants, turning one
    transient store hiccup into a synchronized retry storm.  Every
    retry increments the `stream.source_retries` telemetry counter."""

    def __init__(self, source, retries=2, backoff=0.05, backoff_cap=5.0,
                 chaos=None, jitter=0.25, jitter_seed=None):
        import random
        self.inner = source
        self.name = source.name
        self.total_scens = int(source.total_scens)
        self.retries = int(retries)
        self.backoff = float(backoff)
        self.backoff_cap = float(backoff_cap)
        self.chaos = chaos             # block_build_fail injection point
        self.jitter = float(jitter)
        self._rng = random.Random(jitter_seed)
        self.retry_log = []

    def _delay(self, attempt):
        return backoff_delay(attempt, self.backoff, self.backoff_cap,
                             self.jitter, self._rng)

    def _with_retries(self, fn, indices):
        """Run `fn()` under the capped-backoff retry loop.  Every
        retry increments stream.source_retries; a terminal give-up
        increments stream.source_giveups (retries alone would leave
        exhaustion invisible to telemetry) and raises the structured
        SourceBuildError carrying this call's retry ladder."""
        import time

        from .. import telemetry as _telemetry

        log_start = len(self.retry_log)
        last = None
        for attempt in range(1, self.retries + 2):
            try:
                if self.chaos is not None:
                    self.chaos.block_build_tick()
                return fn()
            except Exception as e:
                if getattr(e, "non_retryable", False):
                    raise      # terminal by contract (e.g. a corpus
                    #            past its quarantine budget)
                last = e
                if attempt > self.retries:
                    break
                delay = self._delay(attempt)
                self.retry_log.append(
                    {"attempt": attempt, "error": str(e),
                     "delay": delay})
                _telemetry.get().counter("stream.source_retries").inc()
                time.sleep(delay)
        _telemetry.get().counter("stream.source_giveups").inc()
        raise SourceBuildError(
            f"scenario block build failed after {self.retries} "
            f"retr{'y' if self.retries == 1 else 'ies'}: {last}",
            indices=indices, attempts=self.retries + 1,
            last_error=last, retry_state=self.retry_log[log_start:])

    def block(self, indices):
        return self._with_retries(lambda: self.inner.block(indices),
                                  indices)

    def block_with_indices(self, indices):
        """Delegates the served-indices protocol (a quarantining
        ShardSource may substitute unreadable indices; the stream must
        absorb the block under the indices actually served)."""
        fn = getattr(self.inner, "block_with_indices", None)
        if fn is None:
            return (np.asarray(indices, dtype=np.int64),
                    self.block(indices))
        return self._with_retries(lambda: fn(indices), indices)

    def note_upcoming(self, indices):
        """Readahead hint pass-through (no retry semantics: a hint is
        best-effort)."""
        fn = getattr(self.inner, "note_upcoming", None)
        if fn is not None:
            fn(indices)

    def close(self):
        fn = getattr(self.inner, "close", None)
        if fn is not None:
            fn()

    def names(self, indices):
        return self.inner.names(indices)


def source_for_module(module, num_scens, cfg=None):
    """Build a ScenarioSource for a model module: the module's own
    `scenario_source(num_scens, cfg)` hook when it has one (farmer, uc,
    aircond define it), else materialize the full batch once via the
    module's `build_batch` and wrap it in a BatchSource."""
    cfg = dict(cfg or {})
    hook = getattr(module, "scenario_source", None)
    if hook is not None:
        return hook(num_scens, cfg)
    from ..confidence_intervals.ciutils import sample_batch
    batch = sample_batch(module, num_scens, cfg.get("start_seed", 0),
                         cfg, {})
    return BatchSource(batch, name=getattr(module, "__name__", "batch"))

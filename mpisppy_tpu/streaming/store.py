"""ShardStore — the durable, checksummed on-disk scenario corpus.

ROADMAP item 3's storage rung: a corpus too big to GENERATE per block
(or whose generator lives elsewhere entirely) is persisted once as
fixed-width shard files and streamed back through `ShardSource`
(streaming/readahead.py).  Robustness is the headline — storage is the
first layer of this stack that can return bytes that are *wrong*
rather than merely late:

  * every shard file carries a header (shard_format version, model
    ident, seed range, dtype) and a CRC32 over the payload bytes;
    `read_checked` re-validates all of it on every read, mirroring the
    window layer's `PayloadGuard` contract (resilience/bounds.py);
  * shard files and the corpus manifest are written atomically via the
    shared tmp-rename helper (`resilience.checkpoint.atomic_write`),
    so a crashed exporter never leaves a torn corpus;
  * transient read failures retry through the same capped seeded-jitter
    backoff as `RetryingSource` (`source.backoff_delay`);
  * a shard that fails validation past `max_shard_retries` is
    QUARANTINED: its seed indices are deterministically resampled from
    healthy shards (`substitute_quarantined`) and the lost probability
    mass is debited into the certified confidence interval
    (`ciutils.debit_quarantined_mass`, wired by StreamingPH) — a
    certified verdict is never silently claimed over a corpus that was
    partially unreadable.  Once quarantined mass exceeds
    `max_quarantined_frac` (default 1%) the store HARD-FAILS
    (`QuarantinedCorpusError`): past that point resampling would bias
    the sample more than the certificate can absorb;
  * the storage cursor (quarantine set, retry/resample counters, the
    retry-jitter RNG state) round-trips through `state()`/`restore()`
    so a stream checkpoint replays quarantine substitutions bit-equally
    after a crash.

Shard file layout (all integers little-endian):

    bytes 0..8    magic  b"MTSHARD1"
    bytes 8..12   uint32 header length H
    bytes 12..12+H  header JSON: shard_format, model, seed_lo,
                    seed_hi, dtype, num_scens, payload_len,
                    payload_crc32
    rest          payload: an .npz of the shard's ScenarioBatch
                  fields (`_batch_payload`/`_batch_from_payload`)

Scope: two-stage corpora only — cross-shard node identity for
multistage trees is the same open problem as StreamingPH's cross-block
consensus, so `write_corpus` rejects multistage batches loudly.

Laziness contract (AST-guarded in tests/test_shard_store.py): no
module-level jax import — `mpisppy_tpu.ir` types are imported lazily
inside the (de)serialization functions, exactly like
`source.gather_block`.
"""

from __future__ import annotations

import dataclasses
import io
import json
import os
import random
import struct
import time
import zlib

import numpy as np

from .. import telemetry as _telemetry
from ..resilience.checkpoint import atomic_write
from .source import backoff_delay

MAGIC = b"MTSHARD1"
SHARD_FORMAT = 1
CORPUS_FORMAT = 1
MANIFEST = "manifest.json"


class ShardStoreError(RuntimeError):
    """Base: the corpus (not a transient read) is unusable."""


class ShardIntegrityError(ShardStoreError):
    """A shard file failed header/CRC validation on read."""


class ShardQuarantinedError(ShardStoreError):
    """A shard exhausted its retry budget and was quarantined; callers
    (ShardSource) resample its indices from healthy shards."""

    def __init__(self, sid, last_error=None):
        super().__init__(
            f"shard {int(sid)} quarantined after retry exhaustion: "
            f"{last_error}")
        self.sid = int(sid)
        self.last_error = last_error


class QuarantinedCorpusError(ShardStoreError):
    """Quarantined mass exceeded max_quarantined_frac — the corpus is
    too degraded for the certificate to absorb; the run must fail
    loudly instead of resampling its way to a biased verdict.
    `non_retryable` tells RetryingSource to propagate it unchanged:
    retrying a terminal corpus failure only delays (and disguises)
    the hard fail."""

    non_retryable = True


# -- ScenarioBatch (de)serialization ---------------------------------------

def _batch_payload(batch):
    """Host-numpy npz payload dict for one shard's ScenarioBatch.
    Optional fields are encoded by key PRESENCE; the A representation
    (dense / shared (1,M,N) / SplitA) is preserved exactly — a split-
    native corpus never densifies on disk."""
    from ..ir import SplitA

    out = {}
    for k in ("c", "qdiag", "row_lo", "row_hi", "lb", "ub",
              "obj_const", "nonant_idx", "integer_mask"):
        v = getattr(batch, k)
        if v is not None:
            out[k] = np.asarray(v)
    A = batch.A
    if isinstance(A, SplitA):
        out["A_shared"] = np.asarray(A.shared)
        out["A_rows"] = np.asarray(A.rows)
        out["A_cols"] = np.asarray(A.cols)
        out["A_vals"] = np.asarray(A.vals)
    else:
        out["A"] = np.asarray(A)
    t = batch.tree
    out["tree_node_of"] = np.asarray(t.node_of)
    out["tree_prob"] = np.asarray(t.prob)
    out["tree_num_nodes"] = np.int64(t.num_nodes)
    if t.stage_of is not None:
        out["tree_stage_of"] = np.asarray(t.stage_of)
    out["tree_nonant_names"] = np.array(list(t.nonant_names or ()),
                                        dtype=object)
    out["tree_scen_names"] = np.array(list(t.scen_names or ()),
                                      dtype=object)
    if batch.stage_cost_c is not None:
        out["stage_cost_c"] = np.asarray(batch.stage_cost_c)
    if batch.var_prob is not None:
        out["var_prob"] = np.asarray(batch.var_prob)
    out["var_names"] = np.array(list(batch.var_names or ()),
                                dtype=object)
    if batch.model_meta is not None:
        out["model_meta"] = np.array([batch.model_meta], dtype=object)
    return out


def _batch_from_payload(z):
    """Inverse of _batch_payload: an npz mapping -> ScenarioBatch."""
    from ..ir import ScenarioBatch, SplitA, TreeInfo

    def opt(k):
        return np.asarray(z[k]) if k in z else None

    if "A_shared" in z:
        A = SplitA(shared=np.asarray(z["A_shared"]),
                   rows=np.asarray(z["A_rows"]),
                   cols=np.asarray(z["A_cols"]),
                   vals=np.asarray(z["A_vals"]))
    else:
        A = np.asarray(z["A"])
    tree = TreeInfo(
        node_of=np.asarray(z["tree_node_of"]),
        prob=np.asarray(z["tree_prob"]),
        num_nodes=int(z["tree_num_nodes"]),
        # stage_of is pytree AUX data (TreeInfo meta field): restore
        # the canonical tuple-of-ints form every model builds, not an
        # ndarray — array aux breaks treedef equality (and with it the
        # jit caches) when a decoded batch meets a fresh one
        stage_of=(tuple(np.asarray(z["tree_stage_of"]).tolist())
                  if "tree_stage_of" in z else None),
        nonant_names=tuple(np.asarray(z["tree_nonant_names"]).tolist()),
        scen_names=tuple(np.asarray(z["tree_scen_names"]).tolist()),
    )
    meta = (np.asarray(z["model_meta"], dtype=object)[0]
            if "model_meta" in z else None)
    return ScenarioBatch(
        c=np.asarray(z["c"]), qdiag=opt("qdiag"), A=A,
        row_lo=opt("row_lo"), row_hi=opt("row_hi"),
        lb=opt("lb"), ub=opt("ub"), obj_const=opt("obj_const"),
        nonant_idx=np.asarray(z["nonant_idx"]),
        integer_mask=opt("integer_mask"), tree=tree,
        stage_cost_c=opt("stage_cost_c"), var_prob=opt("var_prob"),
        var_names=tuple(np.asarray(z["var_names"]).tolist()),
        model_meta=meta)


def concat_blocks(parts):
    """Concatenate per-shard sub-blocks (each a gather_block result)
    into ONE block with BLOCK-UNIFORM probabilities — the same prob-
    renorm contract as gather_block, extended across shards.  Two-
    stage only; a SplitA's shared matrix (and a shared (1,M,N) A) is
    taken from the first part, never replicated."""
    from ..ir import ScenarioBatch, SplitA, TreeInfo

    if len(parts) == 1:
        return parts[0]
    first = parts[0]
    if any(p.tree.num_nodes > 1 for p in parts):
        raise NotImplementedError(
            "shard corpora are two-stage only: cross-shard node "
            "identity for multistage trees is not defined")

    def cat(field, axis=0):
        vs = [getattr(p, field) for p in parts]
        if vs[0] is None:
            return None
        return np.concatenate([np.asarray(v) for v in vs], axis=axis)

    A0 = first.A
    if isinstance(A0, SplitA):
        A = dataclasses.replace(
            A0, vals=np.concatenate(
                [np.asarray(p.A.vals) for p in parts], axis=0))
    elif np.asarray(A0).shape[0] == 1 and first.num_scens > 1:
        A = A0                                       # shared: one copy
    elif (np.asarray(A0).shape[0] == 1
          and all(np.asarray(p.A).shape[0] == 1 for p in parts)
          and sum(p.num_scens for p in parts) > 1):
        # each part is a single-scenario gather of a shared-A corpus
        A = A0
    else:
        A = np.concatenate([np.asarray(p.A) for p in parts], axis=0)
    B = sum(p.num_scens for p in parts)
    tree = TreeInfo(
        node_of=np.concatenate(
            [np.asarray(p.tree.node_of) for p in parts], axis=0),
        prob=np.full(B, 1.0 / B),
        num_nodes=1,
        stage_of=first.tree.stage_of,
        nonant_names=first.tree.nonant_names,
        scen_names=tuple(n for p in parts
                         for n in (p.tree.scen_names or ())),
    )
    return ScenarioBatch(
        c=cat("c"), qdiag=cat("qdiag"), A=A,
        row_lo=cat("row_lo"), row_hi=cat("row_hi"),
        lb=cat("lb"), ub=cat("ub"), obj_const=cat("obj_const"),
        nonant_idx=np.asarray(first.nonant_idx),
        integer_mask=cat("integer_mask"), tree=tree,
        stage_cost_c=cat("stage_cost_c", axis=1),
        var_prob=cat("var_prob"),
        var_names=first.var_names, model_meta=first.model_meta)


# -- shard file encode/decode ----------------------------------------------

def _shard_name(sid):
    return f"shard-{int(sid):06d}.mts"


def _encode_shard(batch, model, lo, hi):
    """One shard's byte image: magic + header JSON + npz payload, with
    an honest CRC32 over the payload bytes stamped into the header."""
    buf = io.BytesIO()
    np.savez_compressed(buf, **_batch_payload(batch))
    payload = buf.getvalue()
    header = json.dumps({
        "shard_format": SHARD_FORMAT,
        "model": str(model),
        "seed_lo": int(lo), "seed_hi": int(hi),
        "num_scens": int(batch.num_scens),
        "dtype": str(np.asarray(batch.c).dtype),
        "payload_len": len(payload),
        "payload_crc32": zlib.crc32(payload) & 0xFFFFFFFF,
    }).encode("utf-8")
    return MAGIC + struct.pack("<I", len(header)) + header + payload


def _decode_shard(data, *, expect_model=None, expect_range=None):
    """Parse + validate one shard byte image.  EVERY read goes through
    here (`ShardStore.read_checked`): magic, header JSON, payload
    length, CRC32 over payload bytes, and — when expectations are
    given — model ident and seed range.  Any mismatch raises
    ShardIntegrityError (never a partially-decoded batch)."""
    if len(data) < len(MAGIC) + 4 or data[:len(MAGIC)] != MAGIC:
        raise ShardIntegrityError("bad shard magic (torn or foreign file)")
    (hlen,) = struct.unpack("<I", data[len(MAGIC):len(MAGIC) + 4])
    hoff = len(MAGIC) + 4
    if hoff + hlen > len(data):
        raise ShardIntegrityError("truncated shard header")
    try:
        header = json.loads(data[hoff:hoff + hlen].decode("utf-8"))
    except (ValueError, UnicodeDecodeError) as e:
        raise ShardIntegrityError(f"unparseable shard header: {e}")
    if int(header.get("shard_format", -1)) != SHARD_FORMAT:
        raise ShardIntegrityError(
            f"unsupported shard_format {header.get('shard_format')!r}")
    payload = data[hoff + hlen:]
    if len(payload) != int(header["payload_len"]):
        raise ShardIntegrityError(
            f"payload length {len(payload)} != header "
            f"{header['payload_len']} (truncated shard)")
    crc = zlib.crc32(payload) & 0xFFFFFFFF
    if crc != int(header["payload_crc32"]):
        raise ShardIntegrityError(
            f"payload CRC mismatch: computed {crc:#010x}, header "
            f"{int(header['payload_crc32']):#010x}")
    if expect_model is not None and header["model"] != expect_model:
        raise ShardIntegrityError(
            f"shard model ident {header['model']!r} != corpus "
            f"{expect_model!r}")
    if expect_range is not None:
        lo, hi = expect_range
        if (int(header["seed_lo"]), int(header["seed_hi"])) != (lo, hi):
            raise ShardIntegrityError(
                f"shard seed range [{header['seed_lo']}, "
                f"{header['seed_hi']}) != expected [{lo}, {hi})")
    try:
        batch = _batch_from_payload(
            np.load(io.BytesIO(payload), allow_pickle=True))
    except Exception as e:
        raise ShardIntegrityError(
            f"undecodable shard payload (CRC passed): {e!r}")
    if batch.num_scens != int(header["num_scens"]):
        raise ShardIntegrityError(
            f"decoded {batch.num_scens} scenarios, header says "
            f"{header['num_scens']}")
    return header, batch


# -- the corpus exporter ---------------------------------------------------

def write_corpus(source, path, shard_width, model=None, meta=None):
    """Persist `source`'s full scenario universe under `path` as
    fixed-width shard files plus a manifest — every file written via
    the atomic tmp-rename discipline.  Shard j holds the contiguous
    seed range [j*w, min((j+1)*w, S)); blocks are pure functions of
    their index set, so the shards reproduce exactly what the source
    would generate.  Returns the corpus path."""
    S = int(source.total_scens)
    w = int(shard_width)
    if S <= 0 or w <= 0:
        raise ValueError("write_corpus needs total_scens > 0 and "
                         "shard_width > 0")
    model = str(model if model is not None else source.name)
    os.makedirs(path, exist_ok=True)
    n_shards = (S + w - 1) // w
    dtype = None
    names = []
    for j in range(n_shards):
        lo, hi = j * w, min((j + 1) * w, S)
        batch = source.block(np.arange(lo, hi, dtype=np.int64))
        if batch.tree.num_nodes > 1:
            raise NotImplementedError(
                "shard corpora are two-stage only (cross-shard node "
                "identity for multistage trees is not defined)")
        if dtype is None:
            dtype = str(np.asarray(batch.c).dtype)
        fname = _shard_name(j)
        atomic_write(os.path.join(path, fname),
                     _encode_shard(batch, model, lo, hi))
        names.append(fname)
    manifest = {
        "corpus_format": CORPUS_FORMAT,
        "model": model,
        "total_scens": S,
        "shard_width": w,
        "n_shards": n_shards,
        "dtype": dtype,
        "shards": names,
        "meta": dict(meta or {}),
    }
    atomic_write(os.path.join(path, MANIFEST),
                 json.dumps(manifest, indent=1).encode("utf-8"))
    return path


# -- the store -------------------------------------------------------------

class ShardStore:
    """Validated random access to one on-disk corpus, with per-shard
    retry, quarantine, and certified-gap accounting hooks.

    Thread-safety note: reads are issued by the readahead worker ONE
    AT A TIME (streaming/readahead.py), and `substitute_quarantined`
    runs on the stream worker — the quarantine set is only ever grown,
    and growth is published before the raising read returns, so the
    substitution pass that follows a quarantine always sees it."""

    def __init__(self, path, *, max_shard_retries=2, backoff=0.05,
                 backoff_cap=5.0, jitter=0.25, jitter_seed=None,
                 max_quarantined_frac=0.01, resample_seed=0,
                 chaos=None, telemetry=None):
        from ..resilience.chaos import ChaosInjector

        self.path = str(path)
        mpath = os.path.join(self.path, MANIFEST)
        try:
            with open(mpath, "rb") as f:
                m = json.loads(f.read().decode("utf-8"))
        except (OSError, ValueError) as e:
            raise ShardStoreError(
                f"unreadable corpus manifest {mpath}: {e!r}")
        if int(m.get("corpus_format", -1)) != CORPUS_FORMAT:
            raise ShardStoreError(
                f"unsupported corpus_format {m.get('corpus_format')!r}")
        self.manifest = m
        self.model = str(m["model"])
        self.total_scens = int(m["total_scens"])
        self.shard_width = int(m["shard_width"])
        self.n_shards = int(m["n_shards"])
        self.meta = dict(m.get("meta") or {})
        self._shard_files = list(m["shards"])

        self.max_shard_retries = int(max_shard_retries)
        self.backoff = float(backoff)
        self.backoff_cap = float(backoff_cap)
        self.jitter = float(jitter)
        self._retry_rng = random.Random(jitter_seed)
        self.max_quarantined_frac = float(max_quarantined_frac)
        self.resample_seed = int(resample_seed)
        # an injector is shared (counters visible to the owner); a
        # dict/None goes through from_options so the MPISPPY_TPU_CHAOS
        # env override applies here too
        self.chaos = (chaos if isinstance(chaos, ChaosInjector)
                      else ChaosInjector.from_options(chaos))

        self.quarantined = set()
        self.shards_read = 0
        self.read_retries = 0
        self.resampled = 0
        self._tel = (telemetry if telemetry is not None
                     else _telemetry.get())

    # -- geometry ---------------------------------------------------------
    def shard_of(self, i):
        return int(i) // self.shard_width

    def shard_range(self, sid):
        lo = int(sid) * self.shard_width
        return lo, min(lo + self.shard_width, self.total_scens)

    def shard_path(self, sid):
        return os.path.join(self.path, self._shard_files[int(sid)])

    # -- validated reads --------------------------------------------------
    def _read_once(self, sid):
        """One read ATTEMPT: chaos ticks, disk read, chaos byte-flip,
        full header+CRC validation."""
        if self.chaos is not None:
            self.chaos.shard_read_tick(sid)
        with open(self.shard_path(sid), "rb") as f:
            data = f.read()
        if self.chaos is not None:
            data = self.chaos.corrupt_shard_bytes(sid, data)
        _, batch = _decode_shard(data, expect_model=self.model,
                                 expect_range=self.shard_range(sid))
        return batch

    def read_checked(self, sid):
        """Read + validate shard `sid`, retrying transient failures
        through the capped seeded-jitter backoff.  Retry exhaustion
        quarantines the shard (which may hard-fail the corpus) and
        raises ShardQuarantinedError."""
        sid = int(sid)
        if sid in self.quarantined:
            raise ShardQuarantinedError(sid, "already quarantined")
        last = None
        for attempt in range(1, self.max_shard_retries + 2):
            try:
                batch = self._read_once(sid)
            except (ShardIntegrityError, OSError) as e:
                last = e
                if attempt > self.max_shard_retries:
                    break
                self.read_retries += 1
                if self._tel.enabled:
                    self._tel.registry.counter(
                        "store.read_retries").inc()
                time.sleep(backoff_delay(
                    attempt, self.backoff, self.backoff_cap,
                    self.jitter, self._retry_rng))
                continue
            self.shards_read += 1
            if self._tel.enabled:
                self._tel.registry.counter("store.shards_read").inc()
            return batch
        self.quarantine(sid, reason=repr(last))
        raise ShardQuarantinedError(sid, last)

    # -- quarantine + certified-gap accounting ----------------------------
    @property
    def quarantined_scens(self):
        return sum(self.shard_range(s)[1] - self.shard_range(s)[0]
                   for s in self.quarantined)

    @property
    def quarantined_frac(self):
        return self.quarantined_scens / max(self.total_scens, 1)

    def quarantine(self, sid, reason=""):
        """Mark shard `sid` permanently unreadable.  Its indices will
        be resampled from healthy shards; the lost mass feeds the CI
        debit.  HARD-FAILS (QuarantinedCorpusError) once the
        quarantined fraction exceeds max_quarantined_frac."""
        sid = int(sid)
        if sid in self.quarantined:
            return
        self.quarantined.add(sid)
        if self._tel.enabled:
            r = self._tel.registry
            r.counter("store.shards_quarantined").inc()
            r.gauge("store.quarantined_frac").set(self.quarantined_frac)
            self._tel.event("store.shard_quarantined", sid=sid,
                            reason=str(reason)[:200],
                            quarantined_frac=self.quarantined_frac)
        if self.quarantined_frac > self.max_quarantined_frac:
            raise QuarantinedCorpusError(
                f"quarantined mass {self.quarantined_frac:.4f} "
                f"({len(self.quarantined)}/{self.n_shards} shards) "
                f"exceeds max_quarantined_frac="
                f"{self.max_quarantined_frac}; the corpus is too "
                f"degraded for a certified verdict")

    def substitute_quarantined(self, indices, count=True):
        """Deterministically replace indices that fall in quarantined
        shards with fresh draws from healthy shards (probability
        renormalization happens downstream in gather/concat — blocks
        stay block-uniform).  A pure function of (index set,
        quarantine set, resample_seed): a resumed run with the
        restored quarantine set replays the SAME substitutions, which
        is what makes crash-resume bit-equal through storage faults.

        Substitutes are drawn below max(indices)+1 when possible so a
        sampler's active-prefix discipline is preserved.  `count=False`
        is the dry-run form for readahead hints: same answer, no
        resampled-counter side effects."""
        idx = np.asarray(indices, dtype=np.int64)
        if not self.quarantined:
            return idx
        bad = np.isin(idx // self.shard_width,
                      np.fromiter(self.quarantined, dtype=np.int64))
        if not bad.any():
            return idx
        limit = int(idx.max()) + 1
        healthy = [s for s in range(self.n_shards)
                   if s not in self.quarantined]
        if not healthy:
            raise QuarantinedCorpusError(
                "every shard of the corpus is quarantined")
        seed = np.random.SeedSequence([
            zlib.crc32(idx.tobytes()) & 0xFFFFFFFF,
            zlib.crc32(json.dumps(sorted(self.quarantined))
                       .encode()) & 0xFFFFFFFF,
            self.resample_seed & 0xFFFFFFFF,
        ])
        rng = np.random.Generator(np.random.PCG64(seed))
        pool = np.concatenate([np.arange(*self.shard_range(s))
                               for s in healthy])
        in_prefix = pool[pool < limit]
        if in_prefix.size:
            pool = in_prefix
        # prefer DISTINCT substitutes (avail shrinks as draws land);
        # once the healthy pool is exhausted — e.g. the block spans
        # the whole corpus — fall back to with-replacement draws: the
        # block keeps its shape and the quarantine CI debit covers
        # the induced duplication bias
        avail = np.setdiff1d(pool, idx[~bad])
        out = idx.copy()
        for pos in np.flatnonzero(bad):
            if avail.size:
                k = int(rng.integers(avail.size))
                out[pos] = avail[k]
                avail = np.delete(avail, k)
            else:
                out[pos] = pool[int(rng.integers(pool.size))]
        out.sort()
        if count:
            n = int(bad.sum())
            self.resampled += n
            if self._tel.enabled:
                self._tel.registry.counter(
                    "store.resampled_indices").inc(n)
        return out

    # -- storage cursor (stream-checkpoint round-trip) --------------------
    def state(self):
        """JSON-serializable storage cursor: the quarantine set (what
        substitution determinism depends on), the retry-jitter RNG
        state, and the read/retry/resample counters."""
        st = self._retry_rng.getstate()
        return {
            "quarantined": sorted(int(s) for s in self.quarantined),
            "shards_read": int(self.shards_read),
            "read_retries": int(self.read_retries),
            "resampled": int(self.resampled),
            "resample_seed": int(self.resample_seed),
            "retry_rng": [st[0], list(st[1]), st[2]],
        }

    def restore(self, state):
        self.quarantined = {int(s) for s in state["quarantined"]}
        self.shards_read = int(state["shards_read"])
        self.read_retries = int(state["read_retries"])
        self.resampled = int(state["resampled"])
        self.resample_seed = int(state.get("resample_seed",
                                           self.resample_seed))
        rr = state.get("retry_rng")
        if rr:
            self._retry_rng.setstate((rr[0], tuple(rr[1]), rr[2]))
        if self._tel.enabled:
            self._tel.registry.gauge(
                "store.quarantined_frac").set(self.quarantined_frac)

    def stats(self):
        return {
            "shards_read": int(self.shards_read),
            "read_retries": int(self.read_retries),
            "shards_quarantined": len(self.quarantined),
            "quarantined_shards": sorted(int(s)
                                         for s in self.quarantined),
            "quarantined_frac": float(self.quarantined_frac),
            "resampled_indices": int(self.resampled),
        }

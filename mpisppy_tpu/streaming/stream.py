"""ScenarioStream — double-buffered block materialization.

One daemon worker thread turns index sets into ready-to-solve blocks:

    prefetch(indices)          enqueue a block build (non-blocking)
    next_block()               blocking take of the OLDEST prefetched
                               block -> (indices, block)

The worker runs `source.block(indices)` (host numpy, models' RNG) and
then the caller-supplied `transfer` callable — StreamingPH injects
"pad to the compiled block width + place on the device mesh" there, so
block i+1's host build AND its host->device transfer overlap block i's
solve (the double-buffering the tentpole asks for).  A bounded output
queue (default depth 2) backpressures the worker so at most two blocks
ever sit in flight — peak host memory stays O(block), never O(S).

Ordering: a single worker draining a FIFO — blocks come out in
prefetch order, which is what makes the streamed trajectory a pure
function of the prefetch sequence (checkpoint/resume replays it).

Laziness contract (AST-guarded): no module-level jax import.  Any jax
work happens inside the injected `transfer` callable, owned by the
driver that runs on the accelerator anyway.  Telemetry instruments are
null no-ops when disabled (zero-cost-when-off).
"""

from __future__ import annotations

import queue
import threading
import time

import numpy as np

from .. import telemetry as _telemetry


class StreamClosed(RuntimeError):
    pass


class ScenarioStream:
    """Prefetching block pipeline over a ScenarioSource."""

    def __init__(self, source, transfer=None, max_prefetch=2,
                 telemetry=None):
        self.source = source
        self.transfer = transfer
        self._tel = (telemetry if telemetry is not None
                     else _telemetry.get())
        self._in = queue.Queue()
        self._out = queue.Queue(maxsize=max(int(max_prefetch), 1))
        self._closed = False
        self.blocks_loaded = 0
        self.scenarios_streamed = 0
        self.prefetch_wait_s = 0.0
        self._worker = threading.Thread(
            target=self._run, name=f"scenario-stream-{source.name}",
            daemon=True)
        self._worker.start()

    # -- worker -----------------------------------------------------------
    def _run(self):
        while True:
            item = self._in.get()
            if item is None:
                self._out.put(None)
                return
            indices = item
            try:
                # served-indices protocol: a quarantining ShardSource
                # may substitute unreadable indices — the consumer must
                # absorb the block under the indices ACTUALLY served
                fn = getattr(self.source, "block_with_indices", None)
                if fn is not None:
                    indices, block = fn(indices)
                else:
                    block = self.source.block(indices)
                if self.transfer is not None:
                    block = self.transfer(block)
                self._out.put((indices, block, None))
            except BaseException as e:  # surfaced on next_block()
                self._out.put((indices, None, e))

    # -- consumer API -----------------------------------------------------
    def prefetch(self, indices):
        """Enqueue a block build; returns immediately.  The worker
        builds blocks in prefetch order."""
        if self._closed:
            raise StreamClosed("stream is closed")
        self._in.put(np.asarray(indices, dtype=np.int64))

    def next_block(self):
        """Blocking take of the oldest prefetched block.  Records the
        time spent waiting (stream.prefetch_wait_seconds — ~0 when the
        build/transfer fully overlapped the previous solve) and
        re-raises any worker-side build failure."""
        if self._closed:
            raise StreamClosed("stream is closed")
        t0 = time.monotonic()
        item = self._out.get()
        wait = time.monotonic() - t0
        if item is None:
            raise StreamClosed("stream worker exited")
        indices, block, err = item
        if err is not None:
            raise err
        self.prefetch_wait_s += wait
        self.blocks_loaded += 1
        self.scenarios_streamed += int(indices.size)
        if self._tel.enabled:
            r = self._tel.registry
            r.counter("stream.blocks_loaded").inc()
            r.counter("stream.scenarios_streamed").inc(int(indices.size))
            r.histogram("stream.prefetch_wait_seconds").observe(wait)
        return indices, block

    def close(self):
        """Stop the worker (idempotent).  Pending prefetches are
        abandoned."""
        if self._closed:
            return
        self._closed = True
        self._in.put(None)

    def stats(self):
        return {
            "blocks_loaded": int(self.blocks_loaded),
            "scenarios_streamed": int(self.scenarios_streamed),
            "prefetch_wait_seconds": float(self.prefetch_wait_s),
        }

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

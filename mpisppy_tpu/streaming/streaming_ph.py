"""StreamingPH — minibatch randomized PH over a ScenarioSource.

Randomized PH (PAPERS.md, arXiv:2009.12186) converges when each
iteration updates only a SAMPLED block of scenarios against the
current consensus; adaptive-sampling PH (arXiv:2407.20944) grows the
sample with a statistical gap estimate and stops when the BM/BPL rule
certifies it.  This driver composes both over the streaming stack:

  * device residency is ONE block of `block_width` scenarios — the
    pow2 `serve.compile_cache.width_bucket` of `stream_block_size`
    (rounded to a device-mesh multiple), so every superstep hits the
    per-shape jit caches and peak device scenario residency never
    exceeds the configured width (asserted in tests/test_streaming.py);
  * the FULL-universe algorithm state — W (S, K), last nonant values,
    the solved mask, warm starts — lives host-resident in numpy;
  * per superstep: consume the prefetched block (its host build and
    host->device transfer overlapped the previous solve), immediately
    draw + prefetch the next one, solve the block's PH subproblems
    against host W and the global consensus xbar, then apply the
    randomized W/xbar correction on the host for the sampled rows only;
  * every `stream_check_every` supersteps the consensus candidate is
    scored by `ciutils.gap_estimators` on a fresh estimator sample
    (disjoint seed region, exactly SeqSampling's discipline) and fed
    to the `SamplingRule`: stop certified, or grow the active sample.

Superstep order of operations is what makes crash-resume bit-equal
(the streamed analog of resilience/checkpoint.py's PH contract): the
next block is drawn from the sampler RNG at the START of superstep k
and the certification (RNG-free, seed-cursor driven) runs INSIDE the
superstep, so the checkpoint written after superstep k captures
post-draw RNG state + the pending index set + post-certification
cursors — resume re-prefetches the pending block (blocks are pure
functions of their index set) and replays superstep k+1 onward
bit-for-bit.

Scope: two-stage sources (root-node consensus).  Multistage streaming
needs node-id-stable cross-block consensus — the per-block node
relabeling of `source.gather_block` deliberately breaks that, so the
constructor rejects multistage sources loudly.
"""

from __future__ import annotations

import time

import jax.numpy as jnp
import numpy as np

from .. import global_toc
from ..confidence_intervals import ciutils
from ..confidence_intervals.seqsampling import SamplingRule
from ..ir import pad_scenarios
from ..ops.pdhg import reprep_row_bounds
from ..parallel.mesh import ScenarioMesh
from ..phbase import PHBase, PHState, ph_objective_arrays
from ..serve.compile_cache import width_bucket
from .sampler import AdaptiveSampler
from .stream import ScenarioStream


class _StreamCertifier:
    """ph_converger-API adapter: `is_converged()` is True once the
    certification step (run inside ph_iteration, so it precedes the
    checkpoint) has recorded a certified CI."""

    def __init__(self, sph):
        self.sph = sph

    def is_converged(self):
        return self.sph.certified is not None


class StreamingPH(PHBase):
    """Randomized PH with adaptive sampling over a ScenarioSource.

    Options (beyond PHBase's):
      stream_block_size   — real scenarios per sampled block (default 64)
      stream_seed         — sampler RNG seed; the gap-estimator seed
                            region is stream_seed + 10_000_000 (the
                            SeqSampling disjointness discipline)
      stream_check_every  — supersteps between gap certifications (5)
      stream_warm_bytes   — host warm-start store budget (default 1 GiB;
                            0 disables block warm starts)
      stopping_criterion  — "BM" (default) or "BPL"
      BM_*/BPL_*/n0min    — SamplingRule knobs (seqsampling.py)
    """

    def __init__(self, options, source, module=None, mesh=None,
                 extensions=None, extension_kwargs=None):
        o = dict(options or {})
        # the certified rule is the stopping criterion; PH's consensus
        # threshold would otherwise end the loop uncertified
        o.setdefault("convthresh", 0.0)
        # transient-build resilience: source_retries wraps the source
        # in a capped-backoff retry loop (resilience/supervisor ladder)
        # before ANY block builds — the template block included
        retries = int(o.get("source_retries", 0))
        if retries > 0:
            from ..resilience.chaos import ChaosInjector
            from .source import RetryingSource
            source = RetryingSource(
                source, retries=retries,
                backoff=float(o.get("source_backoff", 0.05)),
                backoff_cap=float(o.get("source_backoff_cap", 5.0)),
                chaos=ChaosInjector.from_options(o.get("chaos")),
                jitter=float(o.get("source_jitter", 0.25)),
                jitter_seed=o.get("source_jitter_seed"))
        self.source = source
        self.module = module
        self.total_scens = int(source.total_scens)
        mesh = mesh if mesh is not None else ScenarioMesh()
        block = max(1, min(int(o.get("stream_block_size", 64)),
                           self.total_scens))
        w = width_bucket(block, floor=mesh.size)
        self.block_width = ((w + mesh.size - 1) // mesh.size) * mesh.size

        self.rule = SamplingRule(
            o, stochastic_sampling=bool(o.get("stochastic_sampling",
                                              False)),
            stopping_criterion=o.get("stopping_criterion", "BM"))
        self.sampler = AdaptiveSampler(
            self.rule, self.total_scens, block_size=block,
            seed=int(o.get("stream_seed", 0)))

        # template block: scenarios [0, block) padded to the compiled
        # width — defines every per-superstep shape (solver, prep, rho)
        tmpl_idx = np.arange(block)
        raw = source.block(tmpl_idx)
        # check the RAW block: pad_scenarios adds a dummy pad node
        if raw.tree.num_nodes > 1:
            raise NotImplementedError(
                "StreamingPH consensus is two-stage (root node) only: "
                "block-local node relabeling breaks cross-block node "
                "identity for multistage trees")
        tmpl = pad_scenarios(raw, self.block_width)
        super().__init__(o, list(source.names(tmpl_idx)), batch=tmpl,
                         mesh=mesh, extensions=extensions,
                         extension_kwargs=extension_kwargs)

        K = self.batch.num_nonants
        S = self.total_scens
        hdt = np.dtype(np.asarray(tmpl.c).dtype)
        self._host_dtype = hdt
        self.W_host = np.zeros((S, K), hdt)       # full-S dual weights
        self.x_na_host = np.zeros((S, K), hdt)    # last nonant values
        self.solved = np.zeros(S, bool)           # ever-solved mask
        self.xbar_host = np.zeros(K, hdt)         # root consensus
        self._rho_host = float(self.options.get("defaultPHrho", 1.0))
        warm_bytes = int(self.options.get("stream_warm_bytes", 1 << 30))
        need = (S * (self.batch.num_vars + self.batch.num_rows)
                * hdt.itemsize)
        self._warm_host = (
            (np.zeros((S, self.batch.num_vars), hdt),
             np.zeros((S, self.batch.num_rows), hdt))
            if 0 < need <= warm_bytes else None)

        self._check_every = max(1, int(
            self.options.get("stream_check_every", 5)))
        self._est_seed = int(self.options.get("stream_seed", 0)) \
            + 10_000_000
        self._est_history = []
        self.certified = None
        self._pending_indices = None
        self._cur_prob = None
        self.peak_block_scens = 0
        self.convobject = _StreamCertifier(self)

        def _transfer(blk):
            return self.mesh.shard_batch(
                pad_scenarios(blk, self.block_width))

        self.stream = ScenarioStream(source, transfer=_transfer,
                                     telemetry=self._tel)

    # -- storage plumbing --------------------------------------------------
    def _shard_store(self):
        """The ShardStore behind this run's source, unwrapping retry
        wrappers — None for generator/batch sources.  Feeds the
        certified-gap quarantine debit, the stream checkpoint's
        storage cursor, and stream_stats."""
        src = self.source
        for _ in range(8):
            store = getattr(src, "store", None)
            if store is not None:
                return store
            inner = getattr(src, "inner", None)
            if inner is None:
                return None
            src = inner
        return None

    def _prefetch(self, indices):
        """Prefetch an index set, hinting the source's readahead first
        (a shard-backed source starts its disk reads before the stream
        worker even dequeues the build)."""
        hint = getattr(self.source, "note_upcoming", None)
        if hint is not None:
            hint(indices)
        self.stream.prefetch(indices)

    # -- invalid inherited surfaces ---------------------------------------
    def check_W_bound_supported(self):
        raise NotImplementedError(
            "W-based Lagrangian bounds are not valid under randomized "
            "PH: the host-resident W is updated block-wise against a "
            "SAMPLED consensus, so the prob-weighted W does not "
            "telescope to zero over the universe; the certified BM/BPL "
            "gap CI is the streaming bound")

    # -- per-block machinery ----------------------------------------------
    def _block_prep(self, blk):
        """Prep for one padded block.  Shared-A sources (uncertainty in
        row bounds only, e.g. UC wind) reuse the template prep's Ruiz
        scaling/anorm — they depend only on the shared matrix — paying
        one `reprep_row_bounds` rescale; per-scenario-A sources rebuild
        through `_build_prep`, whose prepare_* calls jit-cache per
        (pow2) block shape."""
        if blk.shared_A and self.batch.shared_A:
            dt = self.prep.row_lo.dtype
            return reprep_row_bounds(self.prep,
                                     jnp.asarray(blk.row_lo, dt),
                                     jnp.asarray(blk.row_hi, dt))
        return self._build_prep(hot=self.solver.hot_dtype, batch=blk)

    def _block_warm(self, idx):
        if self._warm_host is None:
            return None, None
        b = idx.size
        x0 = np.zeros((self.block_width, self.batch.num_vars),
                      self._host_dtype)
        y0 = np.zeros((self.block_width, self.batch.num_rows),
                      self._host_dtype)
        x0[:b] = self._warm_host[0][idx]
        y0[:b] = self._warm_host[1][idx]
        dt = self.batch.c.dtype
        return jnp.asarray(x0, dt), jnp.asarray(y0, dt)

    def _absorb_block(self, idx, blk, res):
        """Scatter a solved block's results into the host-resident
        full-S state (pads sliced off)."""
        b = idx.size
        self.x_na_host[idx] = np.asarray(
            blk.nonants(res.x), self._host_dtype)[:b]
        self.solved[idx] = True
        if self._warm_host is not None:
            self._warm_host[0][idx] = np.asarray(
                res.x, self._host_dtype)[:b]
            self._warm_host[1][idx] = np.asarray(
                res.y, self._host_dtype)[:b]
        self.peak_block_scens = max(self.peak_block_scens,
                                    int(blk.num_scens))
        self._cur_prob = np.asarray(blk.prob)

    def _recompute_consensus(self):
        """Root consensus = mean nonant value over every solved
        scenario of the active prefix (sources are uniform-probability,
        so the sample mean IS the probability-weighted xbar)."""
        act = np.flatnonzero(self.solved[:self.sampler.active_n])
        if act.size:
            self.xbar_host = self.x_na_host[act].mean(axis=0)

    def _host_conv(self):
        """Streamed convergence metric: mean over solved active
        scenarios of ||x_na - xbar||_1 / K (the sampled analog of
        phbase.convergence_metric)."""
        act = np.flatnonzero(self.solved[:self.sampler.active_n])
        if not act.size:
            return float("inf")
        K = max(self.batch.num_nonants, 1)
        d = np.abs(self.x_na_host[act] - self.xbar_host[None, :])
        return float(d.sum(axis=1).mean() / K)

    def _install_state(self, res, blk, it):
        dt = self.batch.c.dtype
        x_na = blk.nonants(res.x)
        from ..phbase import _active_fraction
        self.state = PHState(
            x=res.x, y=res.y,
            W=jnp.zeros_like(x_na),
            xbar=jnp.broadcast_to(
                jnp.asarray(self.xbar_host, dt)[None, :], x_na.shape),
            xsqbar=jnp.zeros_like(x_na),
            obj=res.obj, dual_obj=res.dual_obj,
            conv=jnp.asarray(self.conv, dt),
            it=jnp.asarray(it, jnp.int32),
            solve_iters=res.iters,
            active_frac=_active_fraction(blk, res.converged),
            solve_restarts=jnp.sum(res.restarts))

    def _install_resumed_state(self, it):
        """Minimal PHState after a stream-checkpoint restore (the
        device-side block state is transient; only `it`/`conv` feed the
        loop) — load_stream_checkpoint calls this."""
        b = self.batch
        dt = b.c.dtype
        z = jnp.zeros
        self.state = PHState(
            x=z((b.num_scens, b.num_vars), dt),
            y=z((b.num_scens, b.num_rows), dt),
            W=z((b.num_scens, b.num_nonants), dt),
            xbar=jnp.broadcast_to(
                jnp.asarray(self.xbar_host, dt)[None, :],
                (b.num_scens, b.num_nonants)),
            xsqbar=z((b.num_scens, b.num_nonants), dt),
            obj=z((b.num_scens,), dt), dual_obj=z((b.num_scens,), dt),
            conv=jnp.asarray(self.conv, dt),
            it=jnp.asarray(it, jnp.int32))

    # -- expectations over the CURRENT block ------------------------------
    def Eobjective(self, objs):
        """Sampled E[objective]: block-uniform probabilities of the
        block the objs came from (self.batch.prob is the TEMPLATE
        block's and can disagree in real-row count)."""
        p = self._cur_prob
        if p is not None and p.shape[0] == np.shape(objs)[0]:
            return jnp.sum(jnp.asarray(p, self.batch.c.dtype) * objs)
        return super().Eobjective(objs)

    # -- Iter0: sweep the initial active sample ---------------------------
    def Iter0(self):
        self._ext("pre_iter0")
        n0 = self.sampler.active_n
        bsz = self.sampler.block_size
        global_toc(f"StreamingPH Iter0: sweeping {n0} of "
                   f"{self.total_scens} scenarios in blocks of {bsz}")
        chunks = [np.arange(i, min(i + bsz, n0))
                  for i in range(0, n0, bsz)]
        self._prefetch(chunks[0])
        dual_sum = 0.0
        res = blk = None
        for j in range(len(chunks)):
            if j + 1 < len(chunks):
                self._prefetch(chunks[j + 1])
            idx, blk = self.stream.next_block()
            res = self.solve_loop(
                warm=False, batch=blk, prep=self._block_prep(blk),
                eps=self.superstep_eps,
                dtiming=self.options.get("display_timing"))
            self._absorb_block(idx, blk, res)
            dual_sum += float(np.sum(
                np.asarray(res.dual_obj)[:idx.size]))
        self._recompute_consensus()
        act = np.flatnonzero(self.solved)
        self.W_host[act] = self._rho_host * (
            self.x_na_host[act] - self.xbar_host[None, :])
        self.conv = self._host_conv()
        # SAMPLED trivial bound: the mean no-penalty dual objective over
        # the swept sample — an ESTIMATE of the full-S trivial bound
        # (unbiased for uniform scenarios), not a deterministic bound;
        # the certified CI is the streaming run's rigorous statement
        self.trivial_bound = dual_sum / max(n0, 1)
        self.best_bound = self.trivial_bound
        self._install_state(res, blk, it=0)
        # draw + prefetch the first sampled block (RNG consumption #1)
        self._pending_indices = self.sampler.draw_block()
        self._prefetch(self._pending_indices)
        global_toc(f"StreamingPH Iter0 sampled trivial bound = "
                   f"{self.trivial_bound:.6g}, conv = {self.conv:.6g}")
        if self._tel.enabled:
            self._tel.event("stream.iter0",
                            trivial_bound=self.trivial_bound,
                            active_n=n0, conv=self.conv)
        self._ext("post_iter0")
        return self.trivial_bound

    # -- one randomized superstep -----------------------------------------
    def ph_iteration(self):
        self._ext("pre_solve_loop")
        t0 = time.time()
        k = int(self.state.it) + 1
        idx, blk = self.stream.next_block()   # drawn last superstep
        # draw + prefetch superstep k+1's block NOW so its host build
        # and transfer overlap this solve (double-buffering); growth
        # from this superstep's certification takes effect at k+2
        self._pending_indices = self.sampler.draw_block()
        self._prefetch(self._pending_indices)

        b = idx.size
        dt = self.batch.c.dtype
        W_blk = np.zeros((self.block_width, self.batch.num_nonants),
                         self._host_dtype)
        W_blk[:b] = self.W_host[idx]
        xbar_b = np.broadcast_to(
            self.xbar_host[None, :],
            (self.block_width, self.batch.num_nonants))
        c_eff, q_eff = ph_objective_arrays(
            blk, jnp.asarray(W_blk, dt), self.rho,
            jnp.asarray(xbar_b, dt),
            W_on=self.W_on, prox_on=self.prox_on)
        x0, y0 = self._block_warm(idx)
        res = self.solve_loop(
            c=c_eff, qdiag=q_eff, warm=False, batch=blk,
            prep=self._block_prep(blk), x0=x0, y0=y0,
            eps=self.superstep_eps)
        self._absorb_block(idx, blk, res)
        # randomized PH correction: consensus over ALL solved active
        # scenarios, dual update for the SAMPLED rows only
        self._recompute_consensus()
        self.W_host[idx] += self._rho_host * (
            self.x_na_host[idx] - self.xbar_host[None, :])
        self.conv = self._host_conv()
        self._install_state(res, blk, it=k)
        if self._ladder is not None:
            self._ladder_eps = min(
                self._ladder_eps,
                max(self._ladder["min"],
                    self._ladder["couple"] * self.conv))
        wall = time.time() - t0
        tel = self._tel
        if tel.enabled:
            r = tel.registry
            r.counter("ph.iterations").inc()
            r.counter("stream.supersteps").inc()
            r.histogram("ph.iteration_seconds").observe(wall)
            r.gauge("ph.conv").set(self.conv)
        self._ext("post_solve_loop")
        # certification runs INSIDE the superstep (before the
        # checkpoint in iterk_loop) so a crash-after-checkpoint resume
        # replays it with the same cursors
        if (self.module is not None and self.certified is None
                and k % self._check_every == 0):
            self._certify_step()
        return self.conv

    # -- certification (the BM/BPL stopping rule) -------------------------
    def _certify_step(self):
        nk = int(self.sampler.active_n)
        xhat = self.xbar_host.copy()
        try:
            est = ciutils.gap_estimators(
                xhat, self.module, num_scens=nk, seed=self._est_seed,
                cfg=self.options)
        except RuntimeError as e:
            global_toc(f"stream certify: candidate evaluation failed "
                       f"({e}); continuing")
            return False
        self._est_seed = int(est["seed"])
        # quarantined-corpus accounting: resampled (lost) scenario
        # mass widens the gap estimate BEFORE the stopping rule sees
        # it — a degraded corpus must work harder to certify, and the
        # reported CI carries the debit explicitly.  frac == 0 (no
        # store, or a healthy one) leaves the estimate bit-untouched.
        store = self._shard_store()
        q_frac = float(store.quarantined_frac) if store is not None \
            else 0.0
        debit = ciutils.debit_quarantined_mass(est, q_frac)
        G, s = float(est["G"]), float(est["std"])
        self._est_history.append([nk, G, s])
        self._last_zhats = float(est["zhats"])
        stop = self.sampler.observe(G, s)
        global_toc(f"stream certify: n={nk} G={G:.6g} s={s:.6g} "
                   f"stop={stop} active_n={self.sampler.active_n}"
                   + (f" quarantine_debit={debit:.6g}" if debit else ""))
        if self._tel.enabled:
            self._tel.event("stream.certify", n=nk, G=G, s=s,
                            stop=bool(stop), quarantine_debit=debit)
        if stop:
            self.certified = {
                "G": G, "s": s, "num_scens": nk,
                "CI": [0.0, self.rule.ci_upper(s) + debit],
                "zhats": self._last_zhats,
                "T": int(self.sampler.est_rounds),
                "criterion": self.rule.stopping_criterion,
                "quarantined_frac": q_frac,
                "gap_debit": debit,
            }
            return True
        return False

    # -- checkpointing (resilience/checkpoint.py stream format) -----------
    def _save_checkpoint(self, path):
        from ..resilience.checkpoint import save_stream_checkpoint
        save_stream_checkpoint(path, self)

    def restore_run_checkpoint(self, path):
        from ..resilience.checkpoint import load_stream_checkpoint
        load_stream_checkpoint(path, self)
        # blocks are pure functions of their index set: re-issuing the
        # pending prefetch rebuilds exactly the block the crashed run
        # had in flight (the storage cursor was restored first, so a
        # shard-backed source replays the same substitutions)
        self._prefetch(self._pending_indices)
        global_toc(f"StreamingPH resumed from {path} at superstep "
                   f"{int(self.state.it)} "
                   f"(active_n={self.sampler.active_n})")
        return self.trivial_bound

    # -- driver -----------------------------------------------------------
    def post_loops(self):
        """Sampled E[f(xhat)]: the last certification's zhats (the
        fixed-candidate evaluation on the estimator sample) when one
        ran, else the last block's sampled objective.  Denouement
        callbacks are skipped — the resident block's rows are a sample,
        not the universe."""
        if self.certified is not None:
            return float(self.certified["zhats"])
        if getattr(self, "_last_zhats", None) is not None:
            return float(self._last_zhats)
        return float(self.Eobjective(self.state.obj))

    def stream_main(self, finalize=True):
        """Iter0 sweep -> randomized supersteps -> certified stop.
        Mirrors PH.ph_main's resume contract: `resume_from=` a stream
        checkpoint replaces Iter0 and bit-replays the trajectory."""
        resume = self.options.get("resume_from")
        from ..resilience.checkpoint import checkpoint_exists
        if resume is not None and checkpoint_exists(resume):
            trivial = self.restore_run_checkpoint(resume)
        else:
            trivial = self.Iter0()
        self.iterk_loop()
        self.stream.close()
        closer = getattr(self.source, "close", None)
        if closer is not None:
            closer()          # stop a shard source's readahead worker
        if finalize:
            eobj = self.post_loops()
            ci = self.certified["CI"] if self.certified else None
            global_toc(f"StreamingPH done: conv={self.conv:.4e} "
                       f"E[obj]~{eobj:.6g} certified_CI={ci}")
            return self.conv, eobj, trivial
        return self.conv, None, trivial

    def stream_stats(self):
        """Streaming run facts for bench.py / callers."""
        st = self.stream.stats()
        steps = int(self.state.it) if self.state is not None else 0
        out = {
            "sampled_scenarios": int(self.sampler.active_n),
            "total_scens": int(self.total_scens),
            "block_width": int(self.block_width),
            "peak_block_scens": int(self.peak_block_scens),
            "supersteps": steps,
            "blocks_per_superstep": (
                st["blocks_loaded"] / max(steps, 1)),
            "sample_growth_events": int(self.sampler.growth_events),
            "ci_gap": (list(self.certified["CI"])
                       if self.certified else None),
            "certified": self.certified,
            "est_history": list(self._est_history),
            **st,
        }
        src_stats = getattr(self.source, "stats", None)
        if src_stats is not None:
            out["storage"] = src_stats()
        return out

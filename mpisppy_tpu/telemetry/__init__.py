"""Telemetry subsystem: structured tracing, metrics, trace export.

Three layers (doc/src/telemetry.md):

  * `tracer` — span() tracing over CLOCK_MONOTONIC into a lock-free
    ring buffer, exported as Chrome/Perfetto trace-event JSON with one
    process row per hub/spoke (export.chrome_events / merge_traces);
  * `metrics` — counters / gauges / time-value histograms + a bounded
    event log, snapshotted to JSONL and optionally Prometheus text;
  * this facade — ONE process-global `Telemetry` handle configured
    from `options["telemetry"]` or the MPISPPY_TPU_TELEMETRY env var
    (env wins, same layering as resilience.chaos), held by every
    instrumented object (`SPOpt._tel`, `SPCommunicator.telemetry`).

Zero-cost-when-off: a disabled handle exposes the shared NullTracer /
null-instrument registry, so hot paths hold real references and the
off-path cost is an attribute read and a false branch — no allocation,
no host sync, and (structurally: this package never imports jax) no
`block_until_ready` anywhere in the telemetry layer.

Config forms accepted (options value or env var):
    None / False / "0"|"off"|"false"      disabled (default)
    True / "1"|"on"|"true"                enabled, no files written
    "<dir>"                               enabled, artifacts under dir
    {"enabled": ..., "dir": ..., "phase_timing": ...,
     "capacity": ..., "prometheus": ..., "main_label": ...}   full form
"""

from __future__ import annotations

import json
import os

from . import export
from .metrics import MetricsRegistry
from .tracer import NULL_SPAN, NULL_TRACER, Tracer  # noqa: F401

ENV_VAR = "MPISPPY_TPU_TELEMETRY"

_DEFAULTS = {
    "enabled": False,
    "dir": None,
    # phase_timing: time the superstep's four phases individually (the
    # superstep runs UNFUSED when on — see phbase._superstep_phased)
    "phase_timing": True,
    "capacity": 65536,
    "prometheus": False,
    "main_label": "hub",
}

_FALSY = ("", "0", "off", "false", "no")
_TRUTHY = ("1", "on", "true", "yes")


def _norm(config):
    """Any accepted config form -> partial dict (or None for 'unset')."""
    if config is None:
        return None
    if isinstance(config, bool):
        return {"enabled": config}
    if isinstance(config, str):
        s = config.strip()
        if s.lower() in _FALSY:
            return {"enabled": False}
        if s.startswith("{"):
            try:
                d = json.loads(s)
            except ValueError:
                return {"enabled": True}
            return dict(d, enabled=d.get("enabled", True))
        if s.lower() in _TRUTHY:
            return {"enabled": True}
        return {"enabled": True, "dir": s}
    d = dict(config)
    d.setdefault("enabled", True)
    return d


def _effective(config):
    """defaults <- caller config <- env var (env wins — the same
    override layering as resilience.chaos.ChaosInjector)."""
    cfg = dict(_DEFAULTS)
    c = _norm(config)
    if c:
        cfg.update(c)
    env = _norm(os.environ.get(ENV_VAR))
    if env:
        cfg.update(env)
    return cfg


class Telemetry:
    """One configured telemetry instance: a tracer + a registry."""

    def __init__(self, config=None):
        self.config = _effective(config)
        self.enabled = bool(self.config["enabled"])
        self.phase_timing = self.enabled and bool(
            self.config["phase_timing"])
        self.out_dir = self.config.get("dir")
        if self.enabled:
            self.tracer = Tracer(
                capacity=self.config["capacity"],
                main_label=self.config.get("main_label", "hub"))
            self.registry = MetricsRegistry(enabled=True)
        else:
            self.tracer = NULL_TRACER
            self.registry = MetricsRegistry(enabled=False)

    # -- hot-path API -----------------------------------------------------
    def span(self, name, track=None, **args):
        if not self.enabled:
            return NULL_SPAN
        return self.tracer.span(name, track=track, args=args or None)

    def event(self, name, track=None, **args):
        """Instant trace event + entry in the registry event log."""
        if self.enabled:
            self.tracer.instant(name, track=track, args=args or None)
            self.registry.event(name, **args)

    def counter(self, name):
        return self.registry.counter(name)

    def gauge(self, name):
        return self.registry.gauge(name)

    def histogram(self, name):
        return self.registry.histogram(name)

    # -- export -----------------------------------------------------------
    def write_trace(self, path):
        return export.write_trace(path, export.chrome_events(self.tracer))

    def write_metrics(self, path):
        return self.registry.write_jsonl(path)

    def flush(self, out_dir=None, extra_trace_files=()):
        """Write trace.json (merged with any per-spoke-process files),
        metrics.jsonl, and (if configured) prometheus.txt under
        out_dir (default: the configured dir).  Returns the trace path
        or None when disabled / no dir."""
        d = out_dir or self.out_dir
        if not (self.enabled and d):
            return None
        os.makedirs(d, exist_ok=True)
        trace = export.merge_traces(
            os.path.join(d, "trace.json"),
            event_lists=[export.chrome_events(self.tracer)],
            trace_files=extra_trace_files)
        self.registry.write_jsonl(os.path.join(d, "metrics.jsonl"))
        if self.config.get("prometheus"):
            self.registry.write_prometheus(
                os.path.join(d, "prometheus.txt"))
        return trace


_active: Telemetry | None = None


def get() -> Telemetry:
    """The process-global handle (lazily built from the env var alone
    the first time; disabled unless MPISPPY_TPU_TELEMETRY enables it)."""
    global _active
    if _active is None:
        _active = Telemetry(None)
    return _active


def configure(config=None) -> Telemetry:
    """Install a fresh global Telemetry from `config` (+env overlay)."""
    global _active
    _active = Telemetry(config)
    return _active


def configure_from_options(config) -> Telemetry:
    """Install telemetry from an options-dict value.  None leaves the
    active instance untouched (the env var may still have enabled it);
    an IDENTICAL effective config is idempotent — the wheel builds
    several optimizers from copies of one options dict and they must
    share one registry/tracer, not reset each other."""
    if config is None:
        return get()
    cand = _effective(config)
    if _active is not None and _active.config == cand:
        return _active
    return configure(config)


def reset():
    """Drop the global instance (tests)."""
    global _active
    _active = None


def traffic_counters(registry=None):
    """Window-traffic counter dict for bench JSON (zeros when the run
    had telemetry off — keys are stable either way)."""
    reg = registry if registry is not None else get().registry
    names = ("window.writes", "window.reads", "window.stale_reads",
             "window.kill_signals", "window.bound_rejects")
    vals = ({k: c.value for k, c in reg._counters.items()}
            if reg.enabled else {})
    return {n.replace(".", "_"): int(vals.get(n, 0)) for n in names}


def pdhg_counters(registry=None):
    """Inner-solver adaptive-work counters for bench JSON (zeros when
    the run had telemetry off — keys are stable either way)."""
    reg = registry if registry is not None else get().registry
    names = ("pdhg.inner_iters_total", "pdhg.restarts_total",
             "pdhg.flops_saved", "pdhg.promotions",
             "pdhg.sparse_matvecs")
    vals = ({k: c.value for k, c in reg._counters.items()}
            if reg.enabled else {})
    out = {n.replace(".", "_"): int(vals.get(n, 0)) for n in names}
    g = (reg._gauges.get("pdhg.active_fraction")
         if reg.enabled else None)
    out["pdhg_active_fraction"] = float(g.value) if g is not None else 0.0
    return out


def stream_counters(registry=None):
    """Streaming-layer counter dict for bench JSON (zeros when the run
    had telemetry off — keys are stable either way): blocks loaded,
    scenarios streamed through the host->device pipe, sample growth
    events, the active-sample-size gauge, and the total seconds the
    consumer spent blocked on prefetch (the double-buffering
    effectiveness signal — near-zero means block i+1 loads fully
    overlap block i's solve)."""
    reg = registry if registry is not None else get().registry
    names = ("stream.blocks_loaded", "stream.scenarios_streamed",
             "stream.sample_growth_events", "stream.supersteps",
             "stream.source_retries", "stream.source_giveups")
    vals = ({k: c.value for k, c in reg._counters.items()}
            if reg.enabled else {})
    out = {n.replace(".", "_"): int(vals.get(n, 0)) for n in names}
    g = (reg._gauges.get("stream.active_sample_size")
         if reg.enabled else None)
    out["stream_active_sample_size"] = (
        int(g.value) if g is not None else 0)
    h = (reg._histograms.get("stream.prefetch_wait_seconds")
         if reg.enabled else None)
    out["stream_prefetch_wait_seconds"] = (
        float(h.total) if h is not None else 0.0)
    return out


def storage_counters(registry=None):
    """Shard-store counter dict for bench JSON (zeros when the run had
    telemetry off — keys are stable either way): shards read/
    quarantined, read retries, resampled indices, readahead hit/miss
    traffic, plus the quarantined-mass and hit-rate gauges and the
    total seconds the reader spent blocked on shard loads
    (store.read_wait_seconds — ~0 when the readahead fully overlaps
    gathers and solves)."""
    reg = registry if registry is not None else get().registry
    names = ("store.shards_read", "store.read_retries",
             "store.shards_quarantined", "store.resampled_indices",
             "store.readahead_hits", "store.readahead_misses")
    vals = ({k: c.value for k, c in reg._counters.items()}
            if reg.enabled else {})
    out = {n.replace(".", "_"): int(vals.get(n, 0)) for n in names}
    for gname in ("store.quarantined_frac", "store.readahead_hit_rate"):
        g = reg._gauges.get(gname) if reg.enabled else None
        out[gname.replace(".", "_")] = (
            float(g.value) if g is not None else 0.0)
    h = (reg._histograms.get("store.read_wait_seconds")
         if reg.enabled else None)
    out["store_read_wait_seconds"] = (
        float(h.total) if h is not None else 0.0)
    return out


def wheel_counters(registry=None):
    """MPMD-wheel exchange/supervision counters for bench JSON (zeros
    when the run had telemetry off — keys are stable either way).
    Distinct from resilience.wheel_counters (which reads a hub's
    supervisor attributes): this reads the wheel.* instruments — the
    device-exchange traffic (bytes/writes/latency), window-level stale
    reads, slice restart/prune counts, the slice-count gauge, and the
    per-slice bound-progression gauges keyed by trace track."""
    reg = registry if registry is not None else get().registry
    names = ("wheel.exchange_writes", "wheel.exchange_bytes",
             "wheel.collective_exchanges", "wheel.stale_reads",
             "wheel.slice_restarts", "wheel.slices_failed",
             "wheel.reslice_events", "wheel.corrupt_reads",
             "wheel.devices_reclaimed")
    vals = ({k: c.value for k, c in reg._counters.items()}
            if reg.enabled else {})
    out = {n.replace(".", "_"): int(vals.get(n, 0)) for n in names}
    g = reg._gauges.get("wheel.n_slices") if reg.enabled else None
    out["wheel_n_slices"] = int(g.value) if g is not None else 0
    h = (reg._histograms.get("wheel.exchange_seconds")
         if reg.enabled else None)
    out["wheel_exchange_latency_seconds"] = (
        float(h.total) if h is not None else 0.0)
    out["wheel_slice_bounds"] = (
        {k[len("wheel.slice_bound."):]: float(g.value)
         for k, g in reg._gauges.items()
         if k.startswith("wheel.slice_bound.")} if reg.enabled else {})
    return out


def serve_counters(registry=None):
    """Serve-layer counter dict for bench JSON (zeros when the run had
    telemetry off — keys are stable either way)."""
    reg = registry if registry is not None else get().registry
    names = ("serve.requests.submitted", "serve.requests.ok",
             "serve.requests.timeout", "serve.requests.rejected",
             "serve.requests.failed", "serve.compile_cache.hit",
             "serve.compile_cache.miss", "serve.worker_restarts")
    vals = ({k: c.value for k, c in reg._counters.items()}
            if reg.enabled else {})
    return {n.replace(".", "_"): int(vals.get(n, 0)) for n in names}


def router_counters(registry=None):
    """Router-layer (replica-set front door) counter dict for bench
    JSON — stable keys whether or not telemetry was on."""
    reg = registry if registry is not None else get().registry
    names = ("router.requests_submitted", "router.requests_ok",
             "router.requests_timeout", "router.requests_failed",
             "router.requests_rejected", "router.hedged_requests",
             "router.shed_hedges", "router.shed_requests",
             "router.over_quota", "router.breaker_opens",
             "router.replica_restarts", "router.replayed_requests",
             "router.quarantined", "router.duplicate_completions",
             "router.degraded_requests", "router.bucket_starvation")
    vals = ({k: c.value for k, c in reg._counters.items()}
            if reg.enabled else {})
    out = {n.replace(".", "_"): int(vals.get(n, 0)) for n in names}
    g = (reg._gauges.get("router.brownout_level")
         if reg.enabled else None)
    out["router_brownout_level"] = int(g.value) if g is not None else 0
    return out


def gateway_counters(registry=None):
    """Network-edge (serve/net gateway) + AOT-disk-cache counter dict
    for bench JSON — stable keys whether or not telemetry was on,
    mirroring router_counters().  `gateway_rejects_by_code` expands the
    `gateway.rejects.<code>` counter family (protocol.ERROR_CODES keys)
    into a dict, the same prefix-scan shape as wheel_slice_bounds."""
    reg = registry if registry is not None else get().registry
    names = ("gateway.requests", "gateway.bytes_in",
             "gateway.bytes_out", "gateway.rolls", "gateway.drains",
             "cache.aot_loads", "cache.aot_load_failures",
             "cache.aot_saves", "cache.aot_export_failures",
             "cache.aot_prewarm_hits", "cache.aot_evictions",
             "client.reconnects", "client.resends",
             "client.idle_reaped")
    vals = ({k: c.value for k, c in reg._counters.items()}
            if reg.enabled else {})
    out = {n.replace(".", "_"): int(vals.get(n, 0)) for n in names}
    g = (reg._gauges.get("gateway.active_connections")
         if reg.enabled else None)
    out["gateway_active_connections"] = (
        int(g.value) if g is not None else 0)
    out["gateway_rejects_by_code"] = (
        {k[len("gateway.rejects."):]: int(c.value)
         for k, c in reg._counters.items()
         if k.startswith("gateway.rejects.")} if reg.enabled else {})
    return out

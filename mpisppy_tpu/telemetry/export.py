"""Chrome/Perfetto trace-event serialization and multi-process merge.

Output is the Chrome trace-event JSON object format
({"traceEvents": [...]}) — open in https://ui.perfetto.dev or
chrome://tracing.  Each track (hub, every spoke) renders as its own
process row via "M" process_name metadata; cross-process merging works
because every recorder stamps CLOCK_MONOTONIC (system-wide on Linux),
so hub and spoke-process events share one time base.
"""

from __future__ import annotations

import json
import os

_CAT = "mpisppy_tpu"


def chrome_events(tracer):
    """Convert a Tracer's retained records to Chrome trace events,
    prefixed with per-row process_name metadata."""
    events = [{"ph": "M", "name": "process_name", "pid": tracer._pid,
               "tid": 0, "args": {"name": tracer.main_label}}]
    for label, pid in tracer._tracks.items():
        events.append({"ph": "M", "name": "process_name", "pid": pid,
                       "tid": 0, "args": {"name": label}})
    for rec in tracer.records():
        kind = rec[0]
        if kind == "X":
            _, name, pid, tid, ts, dur, args = rec
            e = {"ph": "X", "cat": _CAT, "name": name, "pid": pid,
                 "tid": tid, "ts": ts, "dur": dur}
        elif kind == "i":
            _, name, pid, tid, ts, args = rec
            e = {"ph": "i", "s": "p", "cat": _CAT, "name": name,
                 "pid": pid, "tid": tid, "ts": ts}
        else:  # "C"
            _, name, pid, ts, values = rec
            e = {"ph": "C", "cat": _CAT, "name": name, "pid": pid,
                 "tid": 0, "ts": ts, "args": values}
            events.append(e)
            continue
        if args:
            e["args"] = args
        events.append(e)
    return events


def write_trace(path, events):
    """Atomic write of one trace file."""
    payload = {"traceEvents": list(events), "displayTimeUnit": "ms"}
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(payload, f)
    os.replace(tmp, path)
    return path


def load_trace_events(path):
    """Events from a trace file; [] for missing/corrupt files (a spoke
    SIGKILLed mid-write must not take down the hub's merge)."""
    try:
        with open(path) as f:
            data = json.load(f)
    except (OSError, ValueError):
        return []
    if isinstance(data, dict):
        return data.get("traceEvents", [])
    return data if isinstance(data, list) else []


def merge_traces(out_path, event_lists=(), trace_files=()):
    """Merge in-memory event lists + per-spoke trace FILES into one
    timeline file.  Metadata events sort first so every row is named
    before its first real event; the rest sort by timestamp."""
    merged = []
    for evs in event_lists:
        merged.extend(evs)
    for p in trace_files:
        merged.extend(load_trace_events(p))
    meta = [e for e in merged if e.get("ph") == "M"]
    rest = sorted((e for e in merged if e.get("ph") != "M"),
                  key=lambda e: e.get("ts", 0))
    return write_trace(out_path, meta + rest)

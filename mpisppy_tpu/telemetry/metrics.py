"""Metrics registry: counters, gauges, histograms, and a bounded event
log, with JSONL snapshots and Prometheus text exposition.

Hot-path contract: instrument handles are looked up ONCE (get-or-create
by name) and then `inc`/`set`/`observe` are plain attribute updates.
On a disabled registry the same lookups return shared null singletons
whose methods are no-ops — callers hold one handle and never branch.
Concurrent updates from spoke threads are tolerated as approximate
(`+=` under the GIL can drop an increment under contention; telemetry
is diagnostics, not accounting).

Like the tracer, this module NEVER imports jax (guarded by
tests/test_telemetry.py), so no metric call can sync the device.
"""

from __future__ import annotations

import collections
import json
import math
import os
import re
import time

# exponential seconds-scale buckets: 10 µs .. 2 min (solve phases span
# ~100 µs CPU-test solves to minutes-long certified re-solves)
DEFAULT_BUCKETS = (1e-5, 1e-4, 1e-3, 1e-2, 0.1, 0.5, 1.0, 5.0, 30.0,
                   120.0)


class Counter:
    __slots__ = ("value",)

    def __init__(self):
        self.value = 0

    def inc(self, n=1):
        self.value += n


class Gauge:
    __slots__ = ("value",)

    def __init__(self):
        self.value = 0.0

    def set(self, v):
        self.value = float(v)


class Histogram:
    __slots__ = ("count", "total", "min", "max", "buckets",
                 "bucket_counts")

    def __init__(self, buckets=DEFAULT_BUCKETS):
        self.count = 0
        self.total = 0.0
        self.min = None
        self.max = None
        self.buckets = tuple(buckets)
        self.bucket_counts = [0] * (len(self.buckets) + 1)  # +Inf tail

    def observe(self, v):
        v = float(v)
        self.count += 1
        self.total += v
        if self.min is None or v < self.min:
            self.min = v
        if self.max is None or v > self.max:
            self.max = v
        for i, b in enumerate(self.buckets):
            if v <= b:
                self.bucket_counts[i] += 1
                return
        self.bucket_counts[-1] += 1

    @property
    def mean(self):
        return self.total / self.count if self.count else None

    def summary(self):
        return {"count": self.count, "sum": self.total,
                "min": self.min, "max": self.max, "mean": self.mean}


class _NullCounter:
    __slots__ = ()
    value = 0

    def inc(self, n=1):
        pass


class _NullGauge:
    __slots__ = ()
    value = 0.0

    def set(self, v):
        pass


class _NullHistogram:
    __slots__ = ()
    count = 0
    total = 0.0
    min = None
    max = None
    mean = None

    def observe(self, v):
        pass

    def summary(self):
        return {"count": 0, "sum": 0.0, "min": None, "max": None,
                "mean": None}


NULL_COUNTER = _NullCounter()
NULL_GAUGE = _NullGauge()
NULL_HISTOGRAM = _NullHistogram()


def _json_safe(obj):
    """Recursively replace non-finite floats (inf bounds, NaN poisons)
    with None so snapshot lines stay STRICT JSON (json.dumps would
    otherwise emit the non-standard Infinity/NaN literals)."""
    if isinstance(obj, float) and not math.isfinite(obj):
        return None
    if isinstance(obj, dict):
        return {k: _json_safe(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_json_safe(v) for v in obj]
    return obj


def _prom_name(name):
    """Prometheus metric names admit [a-zA-Z0-9_:] only."""
    return re.sub(r"[^a-zA-Z0-9_:]", "_", name)


class MetricsRegistry:
    def __init__(self, enabled=True, max_events=4096):
        self.enabled = bool(enabled)
        self._counters = {}
        self._gauges = {}
        self._histograms = {}
        # bounded: a misbehaving spoke (steady NaN stream -> one reject
        # event per read) must not grow host memory without bound
        self._events = collections.deque(maxlen=max_events)

    # -- instruments (get-or-create; setdefault keeps races benign) -------
    def counter(self, name):
        if not self.enabled:
            return NULL_COUNTER
        c = self._counters.get(name)
        return c if c is not None else self._counters.setdefault(
            name, Counter())

    def gauge(self, name):
        if not self.enabled:
            return NULL_GAUGE
        g = self._gauges.get(name)
        return g if g is not None else self._gauges.setdefault(
            name, Gauge())

    def histogram(self, name, buckets=DEFAULT_BUCKETS):
        if not self.enabled:
            return NULL_HISTOGRAM
        h = self._histograms.get(name)
        return h if h is not None else self._histograms.setdefault(
            name, Histogram(buckets))

    # -- event log --------------------------------------------------------
    def event(self, name, **args):
        """Append a timestamped record to the bounded event log (e.g.
        supervisor lifecycle: spawn/restart/prune)."""
        if self.enabled:
            self._events.append(
                dict({"ts": time.time(), "event": name}, **args))

    def events(self, name=None):
        evs = list(self._events)
        if name is not None:
            evs = [e for e in evs if e["event"] == name]
        return evs

    # -- export -----------------------------------------------------------
    def snapshot(self):
        """One JSON-safe snapshot of everything."""
        return _json_safe({
            "ts": time.time(),
            "counters": {k: c.value for k, c in self._counters.items()},
            "gauges": {k: g.value for k, g in self._gauges.items()},
            "histograms": {k: h.summary()
                           for k, h in self._histograms.items()},
            "events": list(self._events),
        })

    def write_jsonl(self, path):
        """Append one snapshot line (JSONL: a run's successive
        snapshots accumulate; readers take the last line for finals)."""
        line = json.dumps(self.snapshot())
        with open(path, "a") as f:
            f.write(line + "\n")
        return path

    def prometheus_text(self):
        """Text exposition format: counters/gauges directly, histograms
        as cumulative `le` buckets + _sum/_count."""
        out = []
        for k, c in sorted(self._counters.items()):
            n = _prom_name(k)
            out.append(f"# TYPE {n} counter")
            out.append(f"{n} {c.value}")
        for k, g in sorted(self._gauges.items()):
            n = _prom_name(k)
            out.append(f"# TYPE {n} gauge")
            v = g.value
            out.append(f"{n} {v if math.isfinite(v) else 'NaN'}")
        for k, h in sorted(self._histograms.items()):
            n = _prom_name(k)
            out.append(f"# TYPE {n} histogram")
            cum = 0
            for b, cnt in zip(h.buckets, h.bucket_counts):
                cum += cnt
                out.append(f'{n}_bucket{{le="{b}"}} {cum}')
            cum += h.bucket_counts[-1]
            out.append(f'{n}_bucket{{le="+Inf"}} {cum}')
            out.append(f"{n}_sum {h.total}")
            out.append(f"{n}_count {h.count}")
        return "\n".join(out) + "\n"

    def write_prometheus(self, path):
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            f.write(self.prometheus_text())
        os.replace(tmp, path)
        return path

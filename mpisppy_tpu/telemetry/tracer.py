"""Span tracing over monotonic clocks into a lock-free ring buffer.

Design constraints (doc/src/telemetry.md):

  * recording a span must be cheap enough for per-iteration hot paths:
    one `time.monotonic_ns()` call on enter and one slot assignment on
    exit — no locks, no allocation beyond the record tuple;
  * the buffer is bounded: a preallocated slot list indexed by an
    `itertools.count()` sequence (atomic under the GIL, so concurrent
    spoke threads never tear or lose the index), with old records
    overwritten once the capacity wraps;
  * timestamps are `CLOCK_MONOTONIC` nanoseconds — system-wide on
    Linux, so traces recorded by separate spoke PROCESSES merge onto
    one consistent timeline with the hub's (export.merge_traces);
  * NEVER imports jax: a tracer call can therefore never introduce a
    device sync into the jitted path (tests/test_telemetry.py guards
    this structurally).

Records are tuples (kind first):
    ("X", name, pid, tid, ts_us, dur_us, args)   complete span
    ("i", name, pid, tid, ts_us, args)           instant event
    ("C", name, pid, ts_us, values)              counter sample

`pid` here is the Chrome-trace ROW id: the real os.getpid() for the
main track, synthetic per-track ids for in-process spokes (each spoke
renders as its own process row even when it shares the hub's process).
"""

from __future__ import annotations

import itertools
import threading
import time
import os


class _Span:
    """Context manager recording one complete ("X") event on exit."""

    __slots__ = ("_tracer", "_name", "_pid", "_args", "_t0")

    def __init__(self, tracer, name, pid, args):
        self._tracer = tracer
        self._name = name
        self._pid = pid
        self._args = args

    def __enter__(self):
        self._t0 = time.monotonic_ns()
        return self

    def __exit__(self, exc_type, exc, tb):
        t1 = time.monotonic_ns()
        self._tracer._append(
            ("X", self._name, self._pid, threading.get_native_id(),
             self._t0 // 1000, (t1 - self._t0) // 1000, self._args))
        return False


class _NullSpan:
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        return False


NULL_SPAN = _NullSpan()


class _TrackScope:
    """Thread-local track push/pop (so spans recorded inside a spoke's
    step land on that spoke's row without threading a track argument
    through every call site)."""

    __slots__ = ("_tracer", "_label")

    def __init__(self, tracer, label):
        self._tracer = tracer
        self._label = label

    def __enter__(self):
        tls = self._tracer._tls
        stack = getattr(tls, "stack", None)
        if stack is None:
            stack = tls.stack = []
        stack.append(self._label)
        return self

    def __exit__(self, exc_type, exc, tb):
        self._tracer._tls.stack.pop()
        return False


class Tracer:
    """Lock-free bounded trace recorder (see module docstring)."""

    enabled = True

    def __init__(self, capacity=65536, main_label="hub"):
        self.capacity = max(int(capacity), 16)
        self._slots = [None] * self.capacity
        self._seq = itertools.count()    # C-level atomic increment
        self._pid = os.getpid()
        self.main_label = main_label
        # track label -> synthetic row pid; insertion-ordered so the
        # merged trace shows spokes in wiring order
        self._tracks = {}
        self._tracks_lock = threading.Lock()
        self._tls = threading.local()

    def set_main_label(self, label):
        self.main_label = label

    # -- track (row) management ------------------------------------------
    def track(self, label):
        """Scope: spans/events recorded inside land on `label`'s row.
        label=None is the main (hub) row."""
        return _TrackScope(self, label)

    def _track_pid(self, track):
        if track is None:
            stack = getattr(self._tls, "stack", None)
            track = stack[-1] if stack else None
        if track is None:
            return self._pid
        pid = self._tracks.get(track)
        if pid is None:
            with self._tracks_lock:
                pid = self._tracks.setdefault(
                    track, self._pid * 1000 + 1 + len(self._tracks))
        return pid

    # -- recording --------------------------------------------------------
    def _append(self, rec):
        i = next(self._seq)
        self._slots[i % self.capacity] = (i, rec)

    def span(self, name, track=None, args=None):
        return _Span(self, name, self._track_pid(track), args)

    def instant(self, name, track=None, args=None):
        self._append(("i", name, self._track_pid(track),
                      threading.get_native_id(),
                      time.monotonic_ns() // 1000, args))

    def counter(self, name, values, track=None):
        """Chrome counter sample ("C"): values is {series: number}."""
        self._append(("C", name, self._track_pid(track),
                      time.monotonic_ns() // 1000, dict(values)))

    def record_span(self, name, t0_ns, t1_ns, track=None, args=None):
        """Record an already-measured interval (callers that timed the
        work themselves, e.g. solve_loop's existing wall accounting)."""
        self._append(("X", name, self._track_pid(track),
                      threading.get_native_id(), t0_ns // 1000,
                      max(t1_ns - t0_ns, 0) // 1000, args))

    # -- drain ------------------------------------------------------------
    def records(self):
        """Snapshot of retained records in emission order."""
        live = [s for s in self._slots if s is not None]
        live.sort(key=lambda t: t[0])
        return [rec for _, rec in live]

    @property
    def emitted(self):
        live = [s[0] for s in self._slots if s is not None]
        return max(live) + 1 if live else 0

    @property
    def dropped(self):
        """Records overwritten after the ring wrapped."""
        return max(0, self.emitted - self.capacity)


class NullTracer:
    """Disabled-mode stand-in: every operation is a no-op and span()
    returns the shared NULL_SPAN (no allocation on the hot path)."""

    enabled = False
    capacity = 0
    main_label = "off"
    _tracks = {}
    _pid = 0

    def set_main_label(self, label):
        pass

    def track(self, label):
        return NULL_SPAN

    def span(self, name, track=None, args=None):
        return NULL_SPAN

    def instant(self, name, track=None, args=None):
        pass

    def counter(self, name, values, track=None):
        pass

    def record_span(self, name, t0_ns, t1_ns, track=None, args=None):
        pass

    def records(self):
        return []

    emitted = 0
    dropped = 0


NULL_TRACER = NullTracer()

"""Amalgamator — one-call driver from a model module + Config
(reference: mpisppy/utils/amalgamator.py, 451 LoC).

Module contract (reference amalgamator.py:123-135): the model module
must export
    scenario_names_creator(num_scens, start=0)
    scenario_creator(name, **kwargs)   OR   build_batch(num_scens, **kw)
    inparser_adder(cfg)
    kw_creator(cfg) -> kwargs for the creator / batch builder
`build_batch` is this framework's fast path (vectorized lowering); when
present it is preferred and `kw_creator`'s result is passed to it.

Dispatch (reference Amalgamator.run, :292+): cfg.EF mode solves the
extensive form in one consensus solve; otherwise a WheelSpinner is
built from cfg flags via the vanilla factories (the reference's
hubs/spokes compat tables, :52-67).
"""

from __future__ import annotations

import importlib

import numpy as np

from .. import global_toc
from ..opt.ef import ExtensiveForm
from ..spin_the_wheel import WheelSpinner
from . import vanilla
from .config import Config


def from_module(mname, cfg, extraargs_fct=None, use_command_line=True,
                args=None, progname=None):
    """Build an Amalgamator for model module `mname` (reference
    amalgamator.py:139).  Declares the module's flags on cfg and
    optionally parses the command line (argparse prog = `progname`,
    defaulting to the module name)."""
    m = mname if not isinstance(mname, str) else importlib.import_module(
        mname)
    for needed in ("scenario_names_creator", "inparser_adder",
                   "kw_creator"):
        if not hasattr(m, needed):
            raise RuntimeError(
                f"module {getattr(m, '__name__', m)} missing {needed} "
                "(amalgamator module contract)")
    if not (hasattr(m, "build_batch") or hasattr(m, "scenario_creator")):
        raise RuntimeError("module needs build_batch or scenario_creator")
    m.inparser_adder(cfg)
    if extraargs_fct is not None:
        extraargs_fct(cfg)
    if use_command_line:
        cfg.parse_command_line(
            progname or getattr(m, "__name__", "amalgamator"),
            args=args)
    return Amalgamator(cfg, m)


class Amalgamator:
    def __init__(self, cfg: Config, module):
        self.cfg = cfg
        self.module = module
        self.is_EF = bool(cfg.get("EF", False)) or bool(
            cfg.get("EF_2stage", False)) or bool(
            cfg.get("EF_mstage", False))
        self.best_inner_bound = None
        self.best_outer_bound = None
        self.EF_Obj = None
        self.first_stage_solution = None
        self.wheel = None

    def _make_batch_and_names(self):
        import inspect
        cfg, m = self.cfg, self.module
        kw = dict(m.kw_creator(cfg))
        kw.pop("num_scens", None)   # build_batch takes it positionally
        # forward --seed through whichever seed kwarg the builder takes
        # (same protocol as confidence_intervals.ciutils.sample_batch)
        if hasattr(m, "build_batch"):
            sig = inspect.signature(m.build_batch)
            seed = int(cfg.get("seed", 0) or 0)
            for s in ("seed", "seedoffset", "start_seed"):
                if s in sig.parameters and s not in kw:
                    kw[s] = seed
                    break
        if getattr(m, "MULTISTAGE", False):
            # multistage modules size themselves from branching factors
            batch = m.build_batch(**kw)
            names = m.scenario_names_creator(batch.num_scens)
            return batch, names, None, None
        num_scens = int(cfg.get("num_scens", 3))
        names = m.scenario_names_creator(num_scens)
        if hasattr(m, "build_batch"):
            batch = m.build_batch(num_scens, **kw)
            return batch, names, None, None
        return None, names, m.scenario_creator, kw

    def run(self):
        import time as _time
        t0 = _time.time()
        batch, names, creator, ckw = self._make_batch_and_names()
        # wall split for corpus timing (run_all.py): batch lowering vs
        # the solve (whose first iteration carries the jit compiles)
        self.wall_build = _time.time() - t0
        t0 = _time.time()
        try:
            return self._run_built(batch, names, creator, ckw)
        finally:
            self.wall_run = _time.time() - t0

    def _run_built(self, batch, names, creator, ckw):
        cfg = self.cfg
        opts = cfg.options_dict()
        if self.is_EF:
            opts["pdhg_eps"] = cfg.get("EF_solver_eps",
                                       opts.get("pdhg_eps", 1e-7))
            ef = ExtensiveForm(opts, names, batch=batch,
                               scenario_creator=creator,
                               scenario_creator_kwargs=ckw)
            ef.solve_extensive_form()
            self.EF_Obj = ef.get_objective_value()
            self.best_inner_bound = self.EF_Obj
            self.best_outer_bound = ef.get_dual_bound()
            self.first_stage_solution = np.asarray(ef.get_root_solution())
            global_toc(f"Amalgamator EF obj = {self.EF_Obj:.6g}")
            return self

        hub = vanilla.ph_hub(cfg, creator, None, names,
                             scenario_creator_kwargs=ckw, batch=batch)
        spokes = vanilla.build_spokes(cfg, creator, None, names,
                                      scenario_creator_kwargs=ckw,
                                      batch=batch)
        if cfg.get("fixer"):
            vanilla.add_fixer(hub, cfg)
        if cfg.get("use_norm_rho_updater"):
            vanilla.add_norm_rho(hub, cfg)
        if cfg.get("mult_rho"):
            vanilla.add_multi_rho(hub, cfg)
        if cfg.get("wtracker"):
            vanilla.add_wtracker(hub, cfg)
        if cfg.get("W_fname") or cfg.get("Xbar_fname"):
            from ..extensions.wxbarwriter import WXBarWriter
            hub["opt_kwargs"]["options"]["W_fname"] = (
                cfg.get("W_fname") or cfg.get("Xbar_fname"))
            vanilla.extension_adder(hub, WXBarWriter)
        if cfg.get("init_W_fname") or cfg.get("init_Xbar_fname"):
            from ..extensions.wxbarreader import WXBarReader
            hub["opt_kwargs"]["options"]["init_W_fname"] = (
                cfg.get("init_W_fname") or cfg.get("init_Xbar_fname"))
            vanilla.extension_adder(hub, WXBarReader)
        if cfg.get("primal_dual_converger"):
            from ..convergers.primal_dual_converger import \
                PrimalDualConverger
            hub["opt_kwargs"]["options"]["ph_converger"] = \
                PrimalDualConverger
            hub["opt_kwargs"]["options"][
                "primal_dual_converger_options"] = {
                "tol": cfg.get("primal_dual_converger_tol", 1e-2)}
        elif cfg.get("use_norm_rho_converger"):
            from ..convergers.norm_rho_converger import NormRhoConverger
            hub["opt_kwargs"]["options"]["ph_converger"] = \
                NormRhoConverger

        self.wheel = WheelSpinner(hub, spokes).spin()
        self.best_inner_bound = self.wheel.BestInnerBound
        self.best_outer_bound = self.wheel.BestOuterBound
        sol = self.wheel.best_nonant_solution()
        if sol is not None:
            self.first_stage_solution = np.asarray(sol)
        if cfg.get("solution_base_name") and \
                self.first_stage_solution is not None:
            self.wheel.write_first_stage_solution(
                cfg["solution_base_name"] + ".csv")
        return self

"""Bundling — block-diagonal stacking of scenarios into one batch
element (reference: spopt.py:805-836 subproblem_creation +
utils/pickle_bundle.py "proper bundles"; SURVEY.md §2.10).

A bundle of m scenarios becomes ONE subproblem: constraint blocks on
the diagonal, objectives weighted by within-bundle conditional
probability, and (m-1)*K explicit nonanticipativity equality rows
chaining the members' nonant columns — the same construction as the
reference's per-bundle EF (sputils._create_EF_from_scen_dict), done on
arrays.  The bundled batch is a plain ScenarioBatch, so every
algorithm (PH, L-shaped, FWPH, EF) runs on bundles unchanged; PH's
consensus then couples only across bundles.

Two-stage only (proper bundles make multistage 2-stage by construction
in the reference as well — pickle_bundle.py:14-30).
"""

from __future__ import annotations

import numpy as np

from ..ir import ScenarioBatch, TreeInfo

INF = float("inf")


def bundle_batch(batch: ScenarioBatch, scenarios_per_bundle: int):
    """Stack every `scenarios_per_bundle` consecutive scenarios into a
    bundle.  S must be divisible by the bundle size (the reference
    likewise requires equal bundles, spbase.py:219 _assign_bundles)."""
    m = int(scenarios_per_bundle)
    S = batch.num_scens
    if m <= 1:
        return batch
    if S % m:
        raise ValueError(f"num_scens {S} not divisible by bundle size {m}")
    if int(np.asarray(batch.tree.node_of).max()) > 0:
        raise ValueError("bundle_batch is two-stage only")
    B = S // m
    N, M, K = batch.num_vars, batch.num_rows, batch.num_nonants
    na = np.asarray(batch.nonant_idx)
    A = np.asarray(batch.A)
    prob = np.asarray(batch.prob)
    # a shared-A batch bundles to a shared-A batch: every bundle's
    # block-diagonal is the same matrix (A identical across members,
    # nonant-chain rows constant), so Ab stays (1, Mb, Nb) and the
    # bmatvec matmul fast path survives bundling
    shared = batch.shared_A

    Nb = m * N
    Mb = m * M + (m - 1) * K
    Ab = np.zeros((1 if shared else B, Mb, Nb))
    lob = np.full((B, Mb), -INF)
    hib = np.full((B, Mb), INF)
    cb = np.zeros((B, Nb))
    qb = np.zeros((B, Nb))
    lbb = np.zeros((B, Nb))
    ubb = np.zeros((B, Nb))
    constb = np.zeros((B,))
    intb = np.zeros((B, Nb), bool)
    pb = np.zeros((B,))

    c = np.asarray(batch.c)
    q = np.asarray(batch.qdiag)
    lo = np.asarray(batch.row_lo)
    hi = np.asarray(batch.row_hi)
    lb = np.asarray(batch.lb)
    ub = np.asarray(batch.ub)
    oc = np.asarray(batch.obj_const)
    im = np.asarray(batch.integer_mask)

    for b in range(B):
        mem = range(b * m, (b + 1) * m)
        pB = prob[list(mem)].sum()
        pb[b] = pB
        for j, s in enumerate(mem):
            w = prob[s] / pB if pB > 0 else 1.0 / m
            sl = slice(j * N, (j + 1) * N)
            rw = slice(j * M, (j + 1) * M)
            if not shared:
                Ab[b, rw, sl] = A[s]
            lob[b, rw] = lo[s]
            hib[b, rw] = hi[s]
            cb[b, sl] = w * c[s]
            qb[b, sl] = w * q[s]
            lbb[b, sl] = lb[s]
            ubb[b, sl] = ub[s]
            intb[b, sl] = im[s]
            constb[b] += w * oc[s]
        # nonant chains: member j's nonants == member 0's (equality
        # row bounds per bundle; the matrix entries per A block below)
        lob[b, m * M:] = 0.0
        hib[b, m * M:] = 0.0
        if not shared:
            for j in range(1, m):
                for k in range(K):
                    r = m * M + (j - 1) * K + k
                    Ab[b, r, na[k]] = 1.0
                    Ab[b, r, j * N + na[k]] = -1.0
    if shared:
        # ONE block-diagonal serves every bundle (members share A and
        # the chain rows are constant)
        for j in range(m):
            Ab[0, j * M:(j + 1) * M, j * N:(j + 1) * N] = A[0]
        for j in range(1, m):
            for k in range(K):
                r = m * M + (j - 1) * K + k
                Ab[0, r, na[k]] = 1.0
                Ab[0, r, j * N + na[k]] = -1.0

    # remap sparse matrix-uncertainty coordinates (ir.SplitA contract)
    # to the bundled block-diagonal layout: member j's delta entry
    # (r, c) lands at (j*M + r, j*N + c).  The shared part stays
    # bundle-independent (identical member blocks + constant chain
    # rows), so the split fast path survives bundling.
    from ..ir import delta_idx
    meta = dict(batch.model_meta) if isinstance(batch.model_meta, dict) \
        else None
    if meta and delta_idx(batch) is not None:
        if shared:
            del meta["A_delta_idx"]   # already on the shared-A path
        else:
            r0, c0 = (np.asarray(v) for v in delta_idx(batch))
            meta["A_delta_idx"] = (
                np.concatenate([j * M + r0 for j in range(m)]).astype(
                    np.int32),
                np.concatenate([j * N + c0 for j in range(m)]).astype(
                    np.int32))
    names = batch.tree.scen_names or tuple(str(i) for i in range(S))
    tree = TreeInfo(
        node_of=np.zeros((B, K), np.int32),
        prob=pb / pb.sum(),
        num_nodes=1,
        stage_of=batch.tree.stage_of,
        nonant_names=batch.tree.nonant_names,
        scen_names=tuple(f"bundle{b}({names[b*m]}..{names[(b+1)*m-1]})"
                         for b in range(B)),
    )
    return ScenarioBatch(
        c=cb, qdiag=qb, A=Ab, row_lo=lob, row_hi=hib, lb=lbb, ub=ubb,
        obj_const=constb, nonant_idx=batch.nonant_idx,
        integer_mask=intb, tree=tree,
        stage_cost_c=None,
        model_meta=meta if meta is not None else batch.model_meta,
        var_names=tuple(f"m{j}.{v}" for j in range(m)
                        for v in (batch.var_names
                                  or tuple(str(i) for i in range(N)))))

"""Bundling — block-diagonal stacking of scenarios into one batch
element (reference: spopt.py:805-836 subproblem_creation +
utils/pickle_bundle.py "proper bundles"; SURVEY.md §2.10).

A bundle of m scenarios becomes ONE subproblem: constraint blocks on
the diagonal, objectives weighted by within-bundle conditional
probability, and explicit nonanticipativity equality rows chaining the
members' nonant columns — the same construction as the reference's
per-bundle EF (sputils._create_EF_from_scen_dict), done on arrays.
The bundled batch is a plain ScenarioBatch, so every algorithm (PH,
L-shaped, FWPH, EF) runs on bundles unchanged; PH's consensus then
couples only across bundles.

Multistage: a "proper bundle" consumes ENTIRE subtrees (the
reference's constraint — pickle_bundle.py:14-30, aircondB.py:158-161:
"bundles consume entire second stage nodes"), so every stage>=2 tree
node is interior to one bundle.  The in-bundle nonanticipativity of
those nodes becomes explicit chain rows, only the ROOT slots remain
nonanticipative ACROSS bundles, and the bundled problem is TWO-STAGE
by construction — exactly how the reference turns multistage aircond
into two-stage pickled bundles.
"""

from __future__ import annotations

import numpy as np

from ..ir import ScenarioBatch, TreeInfo

INF = float("inf")


def _chain_pairs(node_of, stage_of, members):
    """Chain specification for one bundle: list of (j_a, k, j_b) — tie
    member j_a's nonant slot k to member j_b's — covering (a) every
    stage-1 slot of every member j>0 chained to member 0, and (b) every
    stage>=2 (node, slot) group chained within its members.  Raises if
    a stage>=2 node's scenario set extends outside the bundle (the
    bundle does not consume entire subtrees)."""
    m = len(members)
    K = node_of.shape[1]
    pairs = []
    for k in range(K):
        if stage_of is not None and stage_of[k] == 1:
            for j in range(1, m):
                pairs.append((j, k, 0))
            continue
        # group members by the node owning slot k
        groups = {}
        for j, s in enumerate(members):
            groups.setdefault(int(node_of[s, k]), []).append(j)
        for js in groups.values():
            for j in js[1:]:
                pairs.append((j, k, js[0]))
    return pairs


def bundle_batch(batch: ScenarioBatch, scenarios_per_bundle: int):
    """Stack every `scenarios_per_bundle` consecutive scenarios into a
    bundle.  S must be divisible by the bundle size (the reference
    likewise requires equal bundles, spbase.py:219 _assign_bundles).
    Multistage batches additionally require each bundle to consume
    entire stage>=2 subtrees (proper bundles)."""
    m = int(scenarios_per_bundle)
    S = batch.num_scens
    if m <= 1:
        return batch
    if S % m:
        raise ValueError(f"num_scens {S} not divisible by bundle size {m}")
    if batch.var_prob is not None:
        raise ValueError("bundle_batch does not support "
                         "variable_probability")
    B = S // m
    N, M, K = batch.num_vars, batch.num_rows, batch.num_nonants
    na = np.asarray(batch.nonant_idx)
    node_of = np.asarray(batch.tree.node_of)
    stage_of = (np.asarray(batch.tree.stage_of)
                if batch.tree.stage_of is not None else None)
    multistage = int(node_of.max()) > 0
    if multistage and stage_of is None:
        raise ValueError("multistage bundling needs tree.stage_of")
    A = np.asarray(batch.A)
    prob = np.asarray(batch.prob)

    # proper-bundle check in ONE pass: every stage>=2 node must be
    # touched by exactly one bundle (scenario s belongs to bundle
    # s // m, so a node's scenario set maps to one bundle id)
    if multistage:
        deep = np.flatnonzero(stage_of > 1)
        node_ids = node_of[:, deep]                       # (S, Kd)
        bundle_of = (np.arange(S) // m)[:, None]
        owner = {}
        for n, b in zip(node_ids.ravel().tolist(),
                        np.broadcast_to(bundle_of,
                                        node_ids.shape).ravel().tolist()):
            if owner.setdefault(n, b) != b:
                raise ValueError(
                    "proper bundles must consume entire subtrees: a "
                    "stage>=2 tree node is shared across bundles "
                    "(choose scenarios_per_bundle as a multiple of "
                    "the leaves per stage-2 subtree)")
    all_pairs = [
        _chain_pairs(node_of, stage_of,
                     list(range(b * m, (b + 1) * m)))
        for b in range(B)]
    n_chain = max(len(p) for p in all_pairs)
    # identical chain patterns across bundles keep the shared-A fast
    # path available (regular trees — aircond — always qualify)
    uniform_chains = all(p == all_pairs[0] for p in all_pairs)
    # a shared-A batch bundles to a shared-A batch: every bundle's
    # block-diagonal is the same matrix (A identical across members,
    # nonant-chain rows constant), so Ab stays (1, Mb, Nb) and the
    # bmatvec matmul fast path survives bundling
    shared = batch.shared_A and uniform_chains

    Nb = m * N
    Mb = m * M + n_chain
    Ab = np.zeros((1 if shared else B, Mb, Nb))
    lob = np.full((B, Mb), -INF)
    hib = np.full((B, Mb), INF)
    cb = np.zeros((B, Nb))
    qb = np.zeros((B, Nb))
    lbb = np.zeros((B, Nb))
    ubb = np.zeros((B, Nb))
    constb = np.zeros((B,))
    intb = np.zeros((B, Nb), bool)
    pb = np.zeros((B,))

    c = np.asarray(batch.c)
    q = np.asarray(batch.qdiag)
    lo = np.asarray(batch.row_lo)
    hi = np.asarray(batch.row_hi)
    lb = np.asarray(batch.lb)
    ub = np.asarray(batch.ub)
    oc = np.asarray(batch.obj_const)
    im = np.asarray(batch.integer_mask)

    for b in range(B):
        mem = range(b * m, (b + 1) * m)
        pB = prob[list(mem)].sum()
        pb[b] = pB
        for j, s in enumerate(mem):
            w = prob[s] / pB if pB > 0 else 1.0 / m
            sl = slice(j * N, (j + 1) * N)
            rw = slice(j * M, (j + 1) * M)
            if not shared:
                Ab[b, rw, sl] = A[s] if A.shape[0] > 1 else A[0]
            lob[b, rw] = lo[s]
            hib[b, rw] = hi[s]
            cb[b, sl] = w * c[s]
            qb[b, sl] = w * q[s]
            lbb[b, sl] = lb[s]
            ubb[b, sl] = ub[s]
            intb[b, sl] = im[s]
            constb[b] += w * oc[s]
        # nonant chains (equality row bounds; matrix entries below)
        pairs = all_pairs[b]
        lob[b, m * M:m * M + len(pairs)] = 0.0
        hib[b, m * M:m * M + len(pairs)] = 0.0
        if not shared:
            for r0, (ja, k, jb) in enumerate(pairs):
                r = m * M + r0
                Ab[b, r, ja * N + na[k]] = 1.0
                Ab[b, r, jb * N + na[k]] = -1.0
    if shared:
        # ONE block-diagonal serves every bundle (members share A and
        # the chain rows are constant)
        for j in range(m):
            Ab[0, j * M:(j + 1) * M, j * N:(j + 1) * N] = A[0]
        for r0, (ja, k, jb) in enumerate(all_pairs[0]):
            r = m * M + r0
            Ab[0, r, ja * N + na[k]] = 1.0
            Ab[0, r, jb * N + na[k]] = -1.0

    # remap sparse matrix-uncertainty coordinates (ir.SplitA contract)
    # to the bundled block-diagonal layout: member j's delta entry
    # (r, c) lands at (j*M + r, j*N + c).  The shared part stays
    # bundle-independent (identical member blocks + constant chain
    # rows), so the split fast path survives bundling.
    from ..ir import delta_idx
    meta = dict(batch.model_meta) if isinstance(batch.model_meta, dict) \
        else None
    if meta and delta_idx(batch) is not None:
        if shared:
            del meta["A_delta_idx"]   # already on the shared-A path
        else:
            r0, c0 = (np.asarray(v) for v in delta_idx(batch))
            meta["A_delta_idx"] = (
                np.concatenate([j * M + r0 for j in range(m)]).astype(
                    np.int32),
                np.concatenate([j * N + c0 for j in range(m)]).astype(
                    np.int32))
    names = batch.tree.scen_names or tuple(str(i) for i in range(S))
    # the bundled problem is TWO-STAGE: only member 0's ROOT slots stay
    # nonanticipative across bundles (multistage slots are chained
    # inside each bundle above)
    if multistage:
        keep = np.flatnonzero(stage_of == 1)
    else:
        keep = np.arange(K)
    nonant_idx_b = na[keep].astype(np.int32)
    Kb = keep.size
    tree = TreeInfo(
        node_of=np.zeros((B, Kb), np.int32),
        prob=pb / pb.sum(),
        num_nodes=1,
        stage_of=(1,) * Kb,
        nonant_names=tuple(np.asarray(
            batch.tree.nonant_names or tuple(str(k) for k in range(K))
        )[keep]),
        scen_names=tuple(f"bundle{b}({names[b*m]}..{names[(b+1)*m-1]})"
                         for b in range(B)),
    )
    return ScenarioBatch(
        c=cb, qdiag=qb, A=Ab, row_lo=lob, row_hi=hib, lb=lbb, ub=ubb,
        obj_const=constb, nonant_idx=nonant_idx_b,
        integer_mask=intb, tree=tree,
        stage_cost_c=None,
        model_meta=meta if meta is not None else batch.model_meta,
        var_names=tuple(f"m{j}.{v}" for j in range(m)
                        for v in (batch.var_names
                                  or tuple(str(i) for i in range(N)))))

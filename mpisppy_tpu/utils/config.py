"""Config — typed flag registry auto-exposed as argparse args
(reference: mpisppy/utils/config.py, 778 LoC, a Pyomo ConfigDict
subclass).

A `Config` declares typed options with `add_to_config`; every declared
option becomes a `--dashed-name` CLI flag via `create_parser` /
`parse_command_line`.  The reference's ~25 named groups
(config.py:151-778) are mirrored as methods below, with solver flags
translated to their TPU-kernel analogs (e.g. mipgap -> pdhg eps).

Usage (mirrors the reference's driver pattern):
    cfg = config.Config()
    cfg.popular_args(); cfg.ph_args(); cfg.two_sided_args()
    farmer.inparser_adder(cfg)
    cfg.parse_command_line("farmer_cylinders")
"""

from __future__ import annotations

import argparse


def _boolify(v):
    if isinstance(v, bool):
        return v
    if isinstance(v, str):
        return v.lower() in ("1", "true", "yes", "on")
    return bool(v)


class Config(dict):
    """dict of option-name -> value with typed declarations.
    Attribute access mirrors the reference (cfg.num_scens)."""

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self.__dict__["_defs"] = {}

    # -- declaration (reference config.py:47-78) --------------------------
    def add_to_config(self, name, description="", domain=str,
                      default=None, argparse=True, complain=False):
        if name in self._defs:
            if complain:
                raise RuntimeError(f"option {name} re-declared")
            return
        self._defs[name] = dict(description=description, domain=domain,
                                default=default, argparse=argparse)
        self.setdefault(name, default)

    def quick_assign(self, name, domain=str, value=None):
        self.add_to_config(name, domain=domain, default=value,
                           argparse=False)
        self[name] = value

    # -- attribute sugar --------------------------------------------------
    def __getattr__(self, name):
        try:
            return self[name]
        except KeyError:
            raise AttributeError(name)

    def __setattr__(self, name, value):
        self[name] = value

    # -- argparse bridge (reference config.py:47-78 auto-args) ------------
    def create_parser(self, progname=None):
        parser = argparse.ArgumentParser(prog=progname)
        for name, d in self._defs.items():
            if not d["argparse"]:
                continue
            flag = "--" + name.replace("_", "-")
            dom = d["domain"]
            # the CURRENT value is the parser default, so programmatic
            # assignments between declaration and parse survive an
            # absent flag
            cur = self.get(name, d["default"])
            if dom is bool:
                # bare --flag means True; --flag false/0 also accepted;
                # one arity regardless of the current value
                parser.add_argument(
                    flag, dest=name, nargs="?", const=True,
                    type=_boolify, default=bool(cur),
                    help=d["description"])
            else:
                parser.add_argument(flag, dest=name, type=dom,
                                    default=cur,
                                    help=d["description"])
        return parser

    def parse_command_line(self, progname=None, args=None):
        parser = self.create_parser(progname)
        ns = parser.parse_args(args=args)
        for name in self._defs:
            if self._defs[name]["argparse"]:
                self[name] = getattr(ns, name)
        return self

    # ======= named groups (reference config.py:151-778) =================
    def popular_args(self):
        self.add_to_config("max_iterations", "hub iteration limit",
                           int, 100)
        self.add_to_config("time_limit", "wall-clock limit (s)",
                           float, None, argparse=False)
        self.add_to_config("default_rho", "PH rho", float, 1.0)
        self.add_to_config("seed", "base RNG seed", int, 0)
        self.add_to_config("solver_eps", "kernel KKT tolerance "
                           "(the solver-options analog)", float, 1e-6)
        self.add_to_config("solver_max_iters", "kernel iteration cap",
                           int, 20000)
        self.add_to_config("display_timing", "print solve timing",
                           bool, False)
        self.add_to_config("verbose", "chatty output", bool, False)
        self.add_to_config("solution_base_name",
                           "write solution files with this prefix",
                           str, None)

    def num_scens_required(self):
        self.add_to_config("num_scens", "number of scenarios", int, 3)

    def add_branching_factors(self):
        self.add_to_config("branching_factors",
                           "comma-separated branching factors",
                           str, "3,3")

    def ph_args(self):
        self.add_to_config("convthresh", "PH convergence threshold",
                           float, 1e-4)
        self.add_to_config("linearize_proximal_terms",
                           "kept for API parity (prox is exact here)",
                           bool, False)

    def two_sided_args(self):
        self.add_to_config("rel_gap", "relative gap termination",
                           float, 0.01)
        self.add_to_config("abs_gap", "absolute gap termination",
                           float, None, argparse=False)
        self.add_to_config("max_stalled_iters", "stall termination",
                           int, 100)

    def aph_args(self):
        self.add_to_config("aph_gamma", "APH gamma", float, 1.0)
        self.add_to_config("aph_nu", "APH nu (relaxation)", float, 1.0)
        self.add_to_config("dispatch_frac",
                           "fraction of scenarios dispatched per pass",
                           float, 1.0)

    def fwph_args(self):
        self.add_to_config("fwph_iter_limit", "SDM rounds per pass",
                           int, 2)
        self.add_to_config("fwph_column_bank", "column capacity",
                           int, 16)
        self.add_to_config("fwph", "add an FWPH outer-bound spoke",
                           bool, False)

    def lagrangian_args(self):
        self.add_to_config("lagrangian",
                           "add a Lagrangian outer-bound spoke",
                           bool, False)

    def lagranger_args(self):
        self.add_to_config("lagranger",
                           "add a Lagranger outer-bound spoke",
                           bool, False)
        self.add_to_config("lagranger_rho_rescale_factors_json",
                           "per-iteration rho rescale factors",
                           str, None)

    def xhatlooper_args(self):
        self.add_to_config("xhatlooper", "add an xhat looper spoke",
                           bool, False)
        self.add_to_config("xhat_scen_limit", "looper scenario limit",
                           int, 3)

    def xhatshuffle_args(self):
        self.add_to_config("xhatshuffle",
                           "add an xhat shuffle-looper spoke",
                           bool, False)
        self.add_to_config("add_reversed_shuffle",
                           "also walk reversed epochs", bool, False)

    def xhatspecific_args(self):
        self.add_to_config("xhatspecific",
                           "add an xhat specific-scenario spoke",
                           bool, False)

    def xhatxbar_args(self):
        self.add_to_config("xhatxbar", "add an xhat-xbar spoke",
                           bool, False)

    def xhatlshaped_args(self):
        self.add_to_config("xhatlshaped",
                           "add an L-shaped xhat spoke", bool, False)

    def slammax_args(self):
        self.add_to_config("slammax", "add a slam-max spoke",
                           bool, False)

    def slammin_args(self):
        self.add_to_config("slammin", "add a slam-min spoke",
                           bool, False)

    def fixer_args(self):
        self.add_to_config("fixer", "attach the Fixer extension",
                           bool, False)
        self.add_to_config("fixer_tol", "Fixer ripeness tolerance",
                           float, 1e-2)
        self.add_to_config("fixer_nb", "consecutive-ripe count",
                           int, 3)

    def gapper_args(self):
        self.add_to_config("mipgaps_json",
                           "JSON file of {iter: eps} schedule",
                           str, None)

    def converger_args(self):
        self.add_to_config("use_norm_rho_converger",
                           "use NormRhoConverger", bool, False)
        self.add_to_config("primal_dual_converger",
                           "use PrimalDualConverger", bool, False)
        self.add_to_config("primal_dual_converger_tol",
                           "its tolerance", float, 1e-2)

    def mult_rho_args(self):
        self.add_to_config("mult_rho", "attach MultRhoUpdater",
                           bool, False)
        self.add_to_config("mult_rho_convergence_tolerance",
                           "stop updating below this conv", float, 1e-4)
        self.add_to_config("mult_rho_update_stop_iteration",
                           "stop updating after this iter", int, None,
                           argparse=False)
        self.add_to_config("mult_rho_update_start_iteration",
                           "start updating at this iter", int, 2)

    def norm_rho_args(self):
        self.add_to_config("use_norm_rho_updater",
                           "attach NormRhoUpdater", bool, False)

    def gradient_args(self):
        self.add_to_config("grad_rho_setter",
                           "use gradient-based rho", bool, False)
        self.add_to_config("grad_order_stat",
                           "order statistic in [0,1] for grad rho",
                           float, 0.5)
        self.add_to_config("grad_rho_relative_bound",
                           "cap rho at this multiple of cost", float,
                           1e3)

    def wtracker_args(self):
        self.add_to_config("wtracker", "attach Wtracker", bool, False)
        self.add_to_config("wtracker_wlen", "window length", int, 10)

    def tracking_args(self):
        self.add_to_config("tracking_folder",
                           "PHTracker output folder", str, None)

    def wxbar_read_write_args(self):
        self.add_to_config("init_W_fname",
                           "warm-start W from this file", str, None)
        self.add_to_config("init_Xbar_fname",
                           "warm-start xbar from this file", str, None)
        self.add_to_config("W_fname", "write W to this file", str, None)
        self.add_to_config("Xbar_fname", "write xbar to this file",
                           str, None)

    def ef_args(self):
        self.add_to_config("EF", "solve the extensive form directly "
                           "(one consensus solve) instead of cylinders",
                           bool, False)
        self.add_to_config("EF_solver_eps", "EF kernel tolerance",
                           float, 1e-7)

    def dynamic_rho_args(self):
        self.gradient_args()

    # -- translation to runtime options -----------------------------------
    def options_dict(self):
        """Map declared flags to the option names the optimizers take
        (the role of the reference's shared_options block in
        cfg_vanilla.py:77-100)."""
        o = {
            "PHIterLimit": self.get("max_iterations", 100),
            "defaultPHrho": self.get("default_rho", 1.0),
            "convthresh": self.get("convthresh", 1e-4),
            "pdhg_eps": self.get("solver_eps", 1e-6),
            "pdhg_max_iters": self.get("solver_max_iters", 20000),
            "display_timing": self.get("display_timing", False),
            "verbose": self.get("verbose", False),
        }
        if self.get("aph_gamma") is not None:
            o["APHgamma"] = self.get("aph_gamma", 1.0)
        if self.get("aph_nu") is not None:
            o["APHnu"] = self.get("aph_nu", 1.0)
        if self.get("dispatch_frac") is not None:
            o["dispatch_frac"] = self.get("dispatch_frac", 1.0)
        if self.get("fwph_iter_limit") is not None:
            o["FW_iter_limit"] = self.get("fwph_iter_limit", 2)
        if self.get("fwph_column_bank") is not None:
            o["column_bank"] = self.get("fwph_column_bank", 16)
        return o


def parse_branching_factors(bf):
    """'3,3' or [3, 3] -> [3, 3] (shared by multistage kw_creators)."""
    if isinstance(bf, str):
        return [int(x) for x in bf.replace(" ", "").split(",") if x]
    return [int(x) for x in bf]


def global_config():
    """Reference exposes a module-level global_config; some drivers use
    it instead of passing cfg around."""
    global _GLOBAL
    try:
        return _GLOBAL
    except NameError:
        _GLOBAL = Config()
        return _GLOBAL

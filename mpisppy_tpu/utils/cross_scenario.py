"""Batch augmentation for cross-scenario cuts (TPU-side counterpart of
the reference's per-scenario eta variables + cut constraints,
reference: mpisppy/extensions/cross_scen_extension.py:16-283 and
opt/lshaped eta machinery).

`add_cross_scenario_capacity(batch, max_cuts, eta_weight)` appends

  * one variable `_eta_cross` (an epigraph of the EXPECTED value
    function E[f](x)), and
  * `max_cuts` initially-free constraint rows that the hub-side
    extension fills with aggregate optimality cuts,

and blends every scenario's objective to (1-w) f_s + w eta.  With
tight cuts, eta = E[f](x) at consensus, so the blended expected
objective equals E[f]; in between, each subproblem "sees" the other
scenarios' costs through eta — the cross-scenario information the
reference shares via its cut matrix.
"""

from __future__ import annotations

import dataclasses

import numpy as np

import jax.numpy as jnp

from ..ir import ScenarioBatch

BIG = 1e9


def add_cross_scenario_capacity(batch: ScenarioBatch, max_cuts=20,
                                eta_weight=0.1) -> ScenarioBatch:
    S, N, M = batch.num_scens, batch.num_vars, batch.num_rows
    w = float(eta_weight)

    def pad_col(v, fill):
        return np.concatenate(
            [np.asarray(v), np.full((S, 1), fill, np.asarray(v).dtype)],
            axis=1)

    A = np.zeros((S, M + max_cuts, N + 1))
    A[:, :M, :N] = np.asarray(batch.A)
    row_lo = np.concatenate(
        [np.asarray(batch.row_lo), np.full((S, max_cuts), -np.inf)],
        axis=1)
    row_hi = np.concatenate(
        [np.asarray(batch.row_hi), np.full((S, max_cuts), np.inf)],
        axis=1)

    newb = ScenarioBatch(
        c=pad_col((1.0 - w) * np.asarray(batch.c), w),
        qdiag=pad_col((1.0 - w) * np.asarray(batch.qdiag), 0.0),
        A=jnp.asarray(A),
        row_lo=jnp.asarray(row_lo),
        row_hi=jnp.asarray(row_hi),
        lb=pad_col(batch.lb, -BIG),
        ub=pad_col(batch.ub, BIG),
        obj_const=(1.0 - w) * np.asarray(batch.obj_const),
        nonant_idx=batch.nonant_idx,
        integer_mask=pad_col(batch.integer_mask, False),
        tree=batch.tree,
        stage_cost_c=None,
        var_names=tuple(batch.var_names or ()) + ("_eta_cross",),
    )
    return newb


def cross_meta(batch: ScenarioBatch):
    """Derive the cut-buffer layout structurally (survives the pytree
    rebuild in mesh.shard_batch): the eta column is the last variable
    (named _eta_cross); the cut buffer is the trailing block of rows
    that are either still free (all-zero, unbounded) or already-
    installed cuts (coefficient 1.0 on eta)."""
    if not batch.var_names or batch.var_names[-1] != "_eta_cross":
        return None
    A0 = np.asarray(batch.A[0])
    lo0 = np.asarray(batch.row_lo[0])
    hi0 = np.asarray(batch.row_hi[0])
    M, N = A0.shape
    eta = N - 1
    first = M
    n_cuts = 0
    for r in range(M - 1, -1, -1):
        is_free = (not A0[r].any()) and np.isinf(lo0[r]) and \
            np.isinf(hi0[r])
        is_cut = A0[r, eta] == 1.0
        if is_free or is_cut:
            first = r
            if is_cut:
                n_cuts += 1
        else:
            break
    return {"first_cut_row": first, "max_cuts": M - first,
            "n_cuts": n_cuts, "eta_col": eta}

"""Gradient-based rho utilities (reference: mpisppy/utils/gradient.py
:44-253 + utils/find_rho.py:45-331 + utils/rho_utils.py).

The reference computes per-variable objective-cost gradients per
scenario, writes them to CSV, and derives rho as an order statistic of
|gradient| over scenarios scaled by the nonant spread.  Vectorized
here: one (S, K) gradient tensor, one quantile call.
"""

from __future__ import annotations

import csv

import numpy as np


def grad_cost(opt, x=None):
    """Per-scenario objective gradient at the nonant slots: (S, K)
    g = c + qdiag * x restricted to nonant columns (reference
    gradient.py:44 grad_cost — Pyomo expression differentiation
    replaced by the closed form of the array IR)."""
    b = opt.batch
    if x is None:
        x = opt.state.x if getattr(opt, "state", None) is not None \
            else b.lb
    na = np.asarray(b.nonant_idx)
    g = np.asarray(b.c)[:, na] + np.asarray(
        b.qdiag)[:, na] * np.asarray(x)[:, na]
    return g


def find_rho(opt, order_stat=0.5, rel_bound=1e3, x=None):
    """(K,) rho from gradient order statistics (reference
    find_rho.py:45 Find_Rho.compute_rho): per slot, the order_stat
    quantile over scenarios of |g|, divided by the scenario spread of
    the nonant values (floored at 1), capped at rel_bound * median."""
    g = np.abs(grad_cost(opt, x=x))
    S = opt.n_real_scens
    g = g[:S]
    quant = np.quantile(g, order_stat, axis=0)
    if getattr(opt, "state", None) is not None:
        x_na = np.asarray(opt.batch.nonants(opt.state.x))[:S]
        spread = np.maximum(x_na.max(axis=0) - x_na.min(axis=0), 1.0)
    else:
        spread = np.ones_like(quant)
    rho = quant / spread
    med = np.median(rho[rho > 0]) if (rho > 0).any() else 1.0
    rho = np.clip(rho, med / rel_bound, med * rel_bound)
    return np.maximum(rho, 1e-6)


def write_grad_cost(path, opt, x=None):
    """CSV: scenario, varname, gradient (reference gradient.py CSV)."""
    g = grad_cost(opt, x=x)
    names = opt.batch.tree.nonant_names or tuple(
        str(k) for k in range(g.shape[1]))
    scen = opt.batch.tree.scen_names or tuple(
        str(s) for s in range(g.shape[0]))
    with open(path, "w", newline="") as f:
        w = csv.writer(f)
        for s in range(opt.n_real_scens):
            for k in range(g.shape[1]):
                w.writerow([scen[s], names[k], g[s, k]])


def read_grad_cost(path, opt):
    g = np.zeros((opt.batch.num_scens, opt.batch.num_nonants))
    names = {n: k for k, n in enumerate(
        opt.batch.tree.nonant_names
        or tuple(str(k) for k in range(g.shape[1])))}
    scen = {n: s for s, n in enumerate(
        opt.batch.tree.scen_names
        or tuple(str(s) for s in range(g.shape[0])))}
    with open(path, newline="") as f:
        for row in csv.reader(f):
            if len(row) == 3 and row[0] in scen and row[1] in names:
                g[scen[row[0]], names[row[1]]] = float(row[2])
    return g

"""Gradient-based rho utilities (reference: mpisppy/utils/gradient.py
:44-253 + utils/find_rho.py:45-331 + utils/rho_utils.py).

The reference computes per-variable objective-cost gradients per
scenario, writes them to CSV, and derives rho as an order statistic of
|gradient| over scenarios scaled by the nonant spread.  Vectorized
here: one (S, K) gradient tensor, one quantile call.
"""

from __future__ import annotations

import csv

import numpy as np


def grad_cost(opt, x=None):
    """Per-scenario objective gradient at the nonant slots: (S, K)
    g = c + qdiag * x restricted to nonant columns (reference
    gradient.py:44 grad_cost — Pyomo expression differentiation
    replaced by the closed form of the array IR)."""
    b = opt.batch
    if x is None:
        x = opt.state.x if getattr(opt, "state", None) is not None \
            else b.lb
    na = np.asarray(b.nonant_idx)
    g = np.asarray(b.c)[:, na] + np.asarray(
        b.qdiag)[:, na] * np.asarray(x)[:, na]
    return g


def find_rho(opt, order_stat=0.5, rel_bound=1e3, x=None):
    """(K,) rho from gradient order statistics (reference
    find_rho.py:45 Find_Rho.compute_rho): per slot, the order_stat
    quantile over scenarios of |g|, divided by the scenario spread of
    the nonant values (floored at 1), capped at rel_bound * median."""
    g = np.abs(grad_cost(opt, x=x))
    S = opt.n_real_scens
    g = g[:S]
    quant = np.quantile(g, order_stat, axis=0)
    if getattr(opt, "state", None) is not None:
        x_na = np.asarray(opt.batch.nonants(opt.state.x))[:S]
        spread = np.maximum(x_na.max(axis=0) - x_na.min(axis=0), 1.0)
    else:
        spread = np.ones_like(quant)
    rho = quant / spread
    med = np.median(rho[rho > 0]) if (rho > 0).any() else 1.0
    rho = np.clip(rho, med / rel_bound, med * rel_bound)
    return np.maximum(rho, 1e-6)


def _nonant_names(opt, count):
    return opt.batch.tree.nonant_names or tuple(
        str(k) for k in range(count))


def write_grad_cost(path, opt, x=None):
    """CSV: scenario, varname, gradient (reference gradient.py CSV)."""
    g = grad_cost(opt, x=x)
    names = _nonant_names(opt, g.shape[1])
    scen = opt.batch.tree.scen_names or tuple(
        str(s) for s in range(g.shape[0]))
    with open(path, "w", newline="") as f:
        w = csv.writer(f)
        for s in range(opt.n_real_scens):
            for k in range(g.shape[1]):
                w.writerow([scen[s], names[k], g[s, k]])


def read_grad_cost(path, opt):
    g = np.zeros((opt.batch.num_scens, opt.batch.num_nonants))
    names = {n: k for k, n in enumerate(_nonant_names(opt, g.shape[1]))}
    scen = {n: s for s, n in enumerate(
        opt.batch.tree.scen_names
        or tuple(str(s) for s in range(g.shape[0])))}
    with open(path, newline="") as f:
        for row in csv.reader(f):
            if len(row) == 3 and row[0] in scen and row[1] in names:
                g[scen[row[0]], names[row[1]]] = float(row[2])
    return g


# -- rho CSV round-trip (reference utils/rho_utils.py rhos_to_csv /
#    rho_list_from_csv: persist per-variable rhos so a later run can
#    start from them — the file format the CLI below emits) -------------

def write_rho(path, opt, rho):
    """CSV: varname, rho (one row per nonant slot; (K,) or (S, K)
    input — per-scenario rhos are written as their scenario-0 row,
    matching the reference's per-variable file format)."""
    rho = np.asarray(rho)
    if rho.ndim == 2:
        rho = rho[0]
    names = _nonant_names(opt, rho.size)
    with open(path, "w", newline="") as f:
        w = csv.writer(f)
        w.writerow(["varname", "rho"])
        for k in range(rho.size):
            w.writerow([names[k], rho[k]])


def read_rho(path, opt):
    """(K,) rho vector from a write_rho CSV."""
    names = {n: k for k, n in enumerate(
        _nonant_names(opt, opt.batch.num_nonants))}
    rho = np.ones(opt.batch.num_nonants)
    with open(path, newline="") as f:
        for row in csv.reader(f):
            if len(row) == 2 and row[0] in names:
                rho[names[row[0]]] = float(row[1])
    return rho


# -- standalone CLI (reference utils/gradient.py / find_rho.py __main__
#    surfaces: compute grad costs + rhos for a model module and write
#    the CSVs that Gradient_extension and WXBar warm starts consume) ----

def main(args=None):
    """python -m mpisppy_tpu.utils.gradient --module <model module>
    --num-scens N [--grad-order-stat q] [--grad-cost-file F]
    [--rho-file F]
    """
    from ..opt.ph import PH
    from .amalgamator import from_module
    from .config import Config

    cfg = Config()
    cfg.popular_args()
    cfg.ph_args()
    cfg.gradient_args()
    cfg.add_to_config("module", "model module name (e.g. "
                      "mpisppy_tpu.models.farmer)", str, None)
    cfg.add_to_config("grad_cost_file", "gradient CSV output", str,
                      "grad_cost.csv")
    cfg.add_to_config("rho_file", "rho CSV output", str, "rhos.csv")
    import importlib
    known, _ = cfg.create_parser("gradient").parse_known_args(args)
    if not known.module:
        cfg.create_parser("gradient").error(
            "--module is required (e.g. mpisppy_tpu.models.farmer)")
    m = importlib.import_module(known.module)
    ama = from_module(m, cfg, use_command_line=True, args=args,
                      progname="gradient")
    batch, names, creator, ckw = ama._make_batch_and_names()
    ph = PH(cfg.options_dict(), names, batch=batch,
            scenario_creator=creator, scenario_creator_kwargs=ckw)
    ph.Iter0()
    write_grad_cost(cfg["grad_cost_file"], ph)
    rho = find_rho(ph, order_stat=cfg.get("grad_order_stat", 0.5),
                   rel_bound=cfg.get("grad_rho_relative_bound", 1e3))
    write_rho(cfg["rho_file"], ph, rho)
    print(f"wrote {cfg['grad_cost_file']} and {cfg['rho_file']} "
          f"({rho.size} nonant slots)")


if __name__ == "__main__":      # pragma: no cover — CLI surface
    from .platform import ensure_cpu_backend
    ensure_cpu_backend()
    main()

"""FLOP accounting / MFU estimation for the batched solver kernel.

The reference measures nothing hardware-level (its solves cross a
process boundary into Gurobi); this build's stated bar is knowing how
far the superstep runs from chip peak, so the solve engine
(spopt.SPOpt.solve_loop) accumulates matvec FLOPs here and bench.py
reports `mfu` and `iters_per_sec`.

Peak numbers are dtype-aware:

- TPU: per-chip dense matmul peaks from public specs
  (jax-ml.github.io/scaling-book hardware table).  MXU f32 runs at
  half the bf16 rate on most generations; f64 is emulated an order of
  magnitude below f32 (no native f64 datapath), modeled here as
  f32_peak / 10 — a rough but non-null denominator.
- CPU: estimated from the host core count x a nominal frequency x
  SIMD FLOPs/cycle per dtype (AVX2-class FMA defaults: 32 f32, 16
  f64 FLOPs per core-cycle; bf16 has no wide CPU datapath and falls
  back to the f32 rate).  Override with env CPU_PEAK_FLOPS.  The
  estimate is coarse — its job is making the MFU gauge populate on
  the CPU-fallback bench rounds instead of reporting null — so treat
  CPU MFU as a relative signal, not a calibrated one.
"""

from __future__ import annotations

import os

# (bf16_peak, f32_peak) FLOP/s per chip
_PEAKS = {
    "v2": (45e12, 22.5e12),
    "v3": (123e12, 61.5e12),
    "v4": (275e12, 137.5e12),
    "v5e": (197e12, 98.5e12),
    "v5p": (459e12, 229.5e12),
    "v6e": (918e12, 459e12),
}

# TPUs emulate f64 in software well below the f32 rate; /10 keeps the
# denominator honest enough to compare runs without overstating peak
_F64_SLOWDOWN = 10.0

# SIMD FLOPs per core-cycle for the CPU estimate (AVX2 + 2xFMA class:
# 2 ports x 8 lanes x 2 flops for f32, half the lanes for f64)
_CPU_FLOPS_PER_CYCLE = {"float32": 32.0, "float64": 16.0,
                        "bfloat16": 32.0}
_CPU_NOMINAL_HZ = 2.5e9


def _dtype_name(dtype):
    s = str(dtype)
    if "bf16" in s or "bfloat16" in s:
        return "bfloat16"
    if "64" in s:
        return "float64"
    return "float32"


def cpu_peak_flops(dtype="float32"):
    """Estimated aggregate peak FLOP/s of this host for `dtype`.
    Override with env CPU_PEAK_FLOPS (total, not per-core)."""
    env = os.environ.get("CPU_PEAK_FLOPS")
    if env:
        return float(env)
    cores = os.cpu_count() or 1
    per_cycle = _CPU_FLOPS_PER_CYCLE[_dtype_name(dtype)]
    return cores * _CPU_NOMINAL_HZ * per_cycle


def device_peak_flops(device=None, dtype="float32"):
    """Best-effort peak FLOP/s for `device` (default: jax.devices()[0])
    at `dtype`.  Override with env TPU_PEAK_FLOPS (wins on every
    backend) or CPU_PEAK_FLOPS (hosts).  Never returns None: the CPU
    path uses the
    core-count x frequency x SIMD-width estimate above so the MFU
    gauge populates on every backend."""
    env = os.environ.get("TPU_PEAK_FLOPS")
    if env:
        return float(env)
    if device is None:
        import jax
        device = jax.devices()[0]
    if device.platform == "cpu":
        return cpu_peak_flops(dtype)
    kind = (getattr(device, "device_kind", "") or "").lower()
    name = _dtype_name(dtype)
    for key, peaks in _PEAKS.items():
        if key in kind:
            break
    else:
        # unknown TPU kind: assume v5e-class
        peaks = _PEAKS["v5e"]
    if name == "bfloat16":
        return peaks[0]
    if name == "float64":
        return peaks[1] / _F64_SLOWDOWN
    return peaks[1]


def pdhg_flops(iters, S, M, N, check_every=40, density=1.0):
    """FLOPs of `iters` PDHG iterations over an (S, M, N) batch.

    Per inner iteration: two batched matvecs (A^T y and A x~), 2*S*M*N
    mult-adds each -> 4*S*M*N FLOP counting mul+add separately is
    2*(2*S*M*N)*2... we count 1 FLOP per multiply and per add:
    each matvec = 2*M*N*S FLOP, so 4*S*M*N per iteration, plus the KKT
    check (2 more matvecs) every `check_every` iterations.

    density: nnz fraction of the constraint block when the matvecs run
    through the BCOO sparse path (ir.SparseSplitA) — sparse products
    only touch stored entries, so the dense model is debited by it.
    Dense matvecs pass the default 1.0.
    """
    per_iter = 4.0 * S * M * N * density
    checks = 4.0 * S * M * N * density / max(check_every, 1)
    return float(iters) * (per_iter + checks)


def mfu(flops, wall_seconds, device=None, dtype="float32"):
    """Model FLOP utilization in [0, 1], or None when wall time is
    degenerate.  The peak denominator is dtype-aware (see
    device_peak_flops) and defined on every backend, CPU included."""
    peak = device_peak_flops(device, dtype)
    if peak is None or wall_seconds <= 0:
        return None
    return flops / wall_seconds / peak


def record_to_registry(registry, flops, wall_seconds, kernel_iters=None,
                       device=None, dtype="float32"):
    """Mirror the accumulated FLOP/wall/MFU numbers into the telemetry
    registry as gauges, so hardware utilization shows up in metrics
    snapshots (telemetry/metrics.py write_jsonl) and not only in
    bench.py's final JSON.  No-op on a disabled registry — callers may
    invoke it unconditionally from hot paths."""
    if not getattr(registry, "enabled", False):
        return
    registry.gauge("mfu.kernel_flops").set(flops)
    registry.gauge("mfu.solve_wall_seconds").set(wall_seconds)
    if kernel_iters is not None:
        registry.gauge("mfu.kernel_iters").set(kernel_iters)
        if wall_seconds > 0:
            registry.gauge("mfu.iters_per_sec").set(
                kernel_iters / wall_seconds)
    u = mfu(flops, wall_seconds, device, dtype)
    if u is not None:
        registry.gauge("mfu.mfu").set(u)

"""FLOP accounting / MFU estimation for the batched solver kernel.

The reference measures nothing hardware-level (its solves cross a
process boundary into Gurobi); this build's stated bar is knowing how
far the superstep runs from chip peak, so the solve engine
(spopt.SPOpt.solve_loop) accumulates matvec FLOPs here and bench.py
reports `mfu` and `iters_per_sec`.

Peak numbers are per-chip dense matmul peaks from public TPU specs
(jax-ml.github.io/scaling-book hardware table).  MXU f32 runs at half
the bf16 rate on most generations; the kernel iterates in f32, so the
f32 peak is the honest denominator.
"""

from __future__ import annotations

import os

# (bf16_peak, f32_peak) FLOP/s per chip
_PEAKS = {
    "v2": (45e12, 22.5e12),
    "v3": (123e12, 61.5e12),
    "v4": (275e12, 137.5e12),
    "v5e": (197e12, 98.5e12),
    "v5p": (459e12, 229.5e12),
    "v6e": (918e12, 459e12),
}


def device_peak_flops(device=None, dtype="float32"):
    """Best-effort peak FLOP/s for `device` (default: jax.devices()[0]).
    Override with env TPU_PEAK_FLOPS.  Returns None on CPU (MFU
    denominator undefined there)."""
    env = os.environ.get("TPU_PEAK_FLOPS")
    if env:
        return float(env)
    if device is None:
        import jax
        device = jax.devices()[0]
    if device.platform == "cpu":
        return None
    kind = (getattr(device, "device_kind", "") or "").lower()
    col = 0 if "bf16" in dtype else 1
    for key, peaks in _PEAKS.items():
        if key in kind:
            return peaks[col]
    # unknown TPU kind: assume v5e-class
    return _PEAKS["v5e"][col]


def pdhg_flops(iters, S, M, N, check_every=40):
    """FLOPs of `iters` PDHG iterations over an (S, M, N) batch.

    Per inner iteration: two batched matvecs (A^T y and A x~), 2*S*M*N
    mult-adds each -> 4*S*M*N FLOP counting mul+add separately is
    2*(2*S*M*N)*2... we count 1 FLOP per multiply and per add:
    each matvec = 2*M*N*S FLOP, so 4*S*M*N per iteration, plus the KKT
    check (2 more matvecs) every `check_every` iterations.
    """
    per_iter = 4.0 * S * M * N
    checks = 4.0 * S * M * N / max(check_every, 1)
    return float(iters) * (per_iter + checks)


def mfu(flops, wall_seconds, device=None, dtype="float32"):
    """Model FLOP utilization in [0, 1], or None when no peak is known
    (CPU)."""
    peak = device_peak_flops(device, dtype)
    if peak is None or wall_seconds <= 0:
        return None
    return flops / wall_seconds / peak


def record_to_registry(registry, flops, wall_seconds, kernel_iters=None,
                       device=None, dtype="float32"):
    """Mirror the accumulated FLOP/wall/MFU numbers into the telemetry
    registry as gauges, so hardware utilization shows up in metrics
    snapshots (telemetry/metrics.py write_jsonl) and not only in
    bench.py's final JSON.  No-op on a disabled registry — callers may
    invoke it unconditionally from hot paths."""
    if not getattr(registry, "enabled", False):
        return
    registry.gauge("mfu.kernel_flops").set(flops)
    registry.gauge("mfu.solve_wall_seconds").set(wall_seconds)
    if kernel_iters is not None:
        registry.gauge("mfu.kernel_iters").set(kernel_iters)
        if wall_seconds > 0:
            registry.gauge("mfu.iters_per_sec").set(
                kernel_iters / wall_seconds)
    u = mfu(flops, wall_seconds, device, dtype)
    if u is not None:
        registry.gauge("mfu.mfu").set(u)

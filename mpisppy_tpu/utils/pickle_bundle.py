"""Array-native batch serialization (reference:
mpisppy/utils/pickle_bundle.py — dill-serialized "proper bundles" to
skip model build time; SURVEY.md §2.9: "array-native checkpoint of
lowered tensors").

A ScenarioBatch is a pytree of arrays + static metadata: np.savez holds
the arrays, a tiny JSON sidecar string holds the metadata.  Round-trips
through `dill_pickle` / `dill_unpickle` names kept for API parity.
"""

from __future__ import annotations

import json

import numpy as np

from ..ir import ScenarioBatch, TreeInfo


def dill_pickle(batch: ScenarioBatch, path):
    """Write a batch to `path` (.npz)."""
    meta = dict(
        num_nodes=int(batch.tree.num_nodes),
        stage_of=list(batch.tree.stage_of or ()),
        nonant_names=list(batch.tree.nonant_names or ()),
        scen_names=list(batch.tree.scen_names or ()),
        var_names=list(batch.var_names or ()),
        has_stage_cost=batch.stage_cost_c is not None,
    )
    arrays = dict(
        c=np.asarray(batch.c), qdiag=np.asarray(batch.qdiag),
        A=np.asarray(batch.A), row_lo=np.asarray(batch.row_lo),
        row_hi=np.asarray(batch.row_hi), lb=np.asarray(batch.lb),
        ub=np.asarray(batch.ub), obj_const=np.asarray(batch.obj_const),
        nonant_idx=np.asarray(batch.nonant_idx),
        integer_mask=np.asarray(batch.integer_mask),
        node_of=np.asarray(batch.tree.node_of),
        prob=np.asarray(batch.tree.prob),
        meta=np.frombuffer(json.dumps(meta).encode(), dtype=np.uint8),
    )
    if batch.stage_cost_c is not None:
        arrays["stage_cost_c"] = np.asarray(batch.stage_cost_c)
    np.savez_compressed(_norm_npz(path), **arrays)


def _norm_npz(path):
    """np.savez appends '.npz' to suffix-less names; keep reader and
    writer agreeing (same rule as wxbarutils)."""
    path = str(path)
    return path if path.endswith(".npz") else path + ".npz"


def dill_unpickle(path) -> ScenarioBatch:
    """Read a batch written by dill_pickle."""
    z = np.load(_norm_npz(path))
    meta = json.loads(bytes(z["meta"]).decode())
    tree = TreeInfo(
        node_of=z["node_of"], prob=z["prob"],
        num_nodes=meta["num_nodes"],
        stage_of=tuple(meta["stage_of"]) or None,
        nonant_names=tuple(meta["nonant_names"]),
        scen_names=tuple(meta["scen_names"]),
    )
    return ScenarioBatch(
        c=z["c"], qdiag=z["qdiag"], A=z["A"], row_lo=z["row_lo"],
        row_hi=z["row_hi"], lb=z["lb"], ub=z["ub"],
        obj_const=z["obj_const"], nonant_idx=z["nonant_idx"],
        integer_mask=z["integer_mask"], tree=tree,
        stage_cost_c=z["stage_cost_c"] if meta["has_stage_cost"] else None,
        var_names=tuple(meta["var_names"]),
    )


def pickle_bundle_parser(cfg):
    """Config flags for the pickled-bundle workflow (reference
    pickle_bundle.py:37-55 pickle_bundle_parser)."""
    cfg.add_to_config("pickle_bundles_dir",
                      description="write per-bundle npz files here",
                      domain=str, default=None)
    cfg.add_to_config("unpickle_bundles_dir",
                      description="read per-bundle npz files from here "
                      "instead of building the model",
                      domain=str, default=None)
    cfg.add_to_config("scenarios_per_bundle",
                      description="scenarios per proper bundle",
                      domain=int, default=None)


def have_proper_bundles(cfg):
    """Reference pickle_bundle.py:58-64: is a bundle workflow active?"""
    return (cfg.get("pickle_bundles_dir") is not None
            or cfg.get("unpickle_bundles_dir") is not None)

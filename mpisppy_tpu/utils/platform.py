"""Host-platform helpers.

The TPU plugin ("axon") may be pre-registered by the environment's
sitecustomize; once registered, even JAX_PLATFORMS=cpu initializes its
device tunnel, which hangs when the tunnel is down.  Every CPU-only
entry point (tests, dryrun, bench smoke) must call
`ensure_cpu_backend()` BEFORE the first jax backend initialization.
"""

from __future__ import annotations

import os


def ensure_cpu_backend(force=False):
    """Deregister the TPU plugin and pin jax to CPU.  No-op unless
    JAX_PLATFORMS requests cpu (or force=True)."""
    if not force and "cpu" not in os.environ.get("JAX_PLATFORMS", ""):
        return
    import jax._src.xla_bridge as xb
    xb._backend_factories.pop("axon", None)
    import jax
    jax.config.update("jax_platforms", "cpu")


def enable_compile_cache():
    """Point jax at a persistent compilation cache so warm restarts
    skip XLA (measured on CPU: repeat sizes-3 MIP runs drop 80.8 s ->
    49.3 s — ~30 s of the wall is compiles).

    Policy, most-specific wins:
      * an explicit JAX_COMPILATION_CACHE_DIR is jax's own knob and is
        never overridden;
      * MPISPPY_TPU_COMPILE_CACHE_DIR enables the cache at that path on
        EVERY backend — the serve layer's warm-restart contract
        (doc/src/serve.md);
      * otherwise the historical conservative default: CPU only
        (accelerator compile paths may be remote/plugin-managed), under
        MPISPPY_TPU_JAX_CACHE or ~/.cache/mpisppy_tpu_jax.

    Returns the cache dir in effect, or None when left disabled."""
    import jax

    if os.environ.get("JAX_COMPILATION_CACHE_DIR"):
        return os.environ["JAX_COMPILATION_CACHE_DIR"]
    path = os.environ.get("MPISPPY_TPU_COMPILE_CACHE_DIR")
    if not path:
        if jax.devices()[0].platform != "cpu":
            return None
        path = os.environ.get(
            "MPISPPY_TPU_JAX_CACHE",
            os.path.join(os.path.expanduser("~"), ".cache",
                         "mpisppy_tpu_jax"))
    try:
        os.makedirs(path, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", path)
        return path
    except (OSError, AttributeError):   # read-only home / old jax
        return None


# historical name (examples/_driver.py and external callers)
enable_compile_cache_if_cpu = enable_compile_cache


def enable_x64_scope():
    """Version-tolerant `with ... :` scope forcing x64 semantics: jax
    exports the context manager as `jax.enable_x64` in newer releases
    and as `jax.experimental.enable_x64` in older ones; the f64
    certification paths (spopt certify, ef dual bound) must work on
    both."""
    import jax

    ctx = getattr(jax, "enable_x64", None)
    if ctx is None:
        from jax.experimental import enable_x64 as ctx
    return ctx()


def enable_f64_if_cpu():
    """The project-wide precision protocol: device=cpu always means
    f64 (certified-eps paths — MIP diving at 1e-6, golden drives — are
    not reliable in f32; f32 is the accelerator's trade, not the
    host's).  Gates on the ACTUAL backend, so it initializes jax.
    Returns True when the backend is CPU (callers branch on it for
    CPU-vs-accelerator run protocol)."""
    import jax

    on_cpu = jax.devices()[0].platform == "cpu"
    if on_cpu:
        jax.config.update("jax_enable_x64", True)
    return on_cpu

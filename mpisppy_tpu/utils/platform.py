"""Host-platform helpers.

The TPU plugin ("axon") may be pre-registered by the environment's
sitecustomize; once registered, even JAX_PLATFORMS=cpu initializes its
device tunnel, which hangs when the tunnel is down.  Every CPU-only
entry point (tests, dryrun, bench smoke) must call
`ensure_cpu_backend()` BEFORE the first jax backend initialization.
"""

from __future__ import annotations

import os


def ensure_cpu_backend(force=False):
    """Deregister the TPU plugin and pin jax to CPU.  No-op unless
    JAX_PLATFORMS requests cpu (or force=True)."""
    if not force and "cpu" not in os.environ.get("JAX_PLATFORMS", ""):
        return
    import jax._src.xla_bridge as xb
    xb._backend_factories.pop("axon", None)
    import jax
    jax.config.update("jax_platforms", "cpu")

"""solver_spec — hierarchical solver-option resolution (reference:
mpisppy/utils/solver_spec.py:34 solver_specification, which cascades
`{root}_solver_name` / `{root}_solver_options` prefixes so each
cylinder can carry its own solver configuration).

There are no external solver NAMES here (the kernel is in-process),
so the cascade resolves KERNEL knobs instead: for an ordered list of
roots (e.g. ["lagrangian", ""]) the first root with any
`{root}_solver_*` setting wins and its knobs are returned as the
optimizer-option dict (pdhg_eps / pdhg_max_iters / pdhg_check_every /
pdhg_restart_every), falling back to the unprefixed values.  Options
may also be given as ONE string of space-separated key=value pairs
(`{root}_solver_options`, the reference's convention, parsed by
`option_string_to_dict`).
"""

from __future__ import annotations

KNOBS = ("eps", "max_iters", "check_every", "restart_every",
         "restart_mode", "restart_beta_sufficient",
         "restart_beta_necessary", "compact_threshold",
         "hot_dtype", "sparse_threshold")


def option_string_to_dict(ostr):
    """'eps=1e-6 max_iters=30000' -> {'eps': 1e-6, 'max_iters': 30000}
    (reference sputils.py:551 option_string_to_dict; values parsed as
    int, then float, then left as strings)."""
    if ostr is None or ostr == "":
        return None
    out = {}
    for tok in str(ostr).split():
        if "=" in tok:
            k, v = tok.split("=", 1)
            for cast in (int, float):
                try:
                    v = cast(v)
                    break
                except ValueError:
                    continue
        else:
            k, v = tok, True
        out[k] = v
    return out


def solver_specification(cfg, prefix="", name_required=False):
    """Resolve kernel options through a prefix cascade.

    Args:
        cfg: a Config or plain dict of options.
        prefix: one root string or an ordered list (first root with
            any `{root}_solver_*` key wins; "" = the unprefixed
            options).
        name_required: kept for reference-signature parity; raises if
            no root matched and this is True.

    Returns:
        (sroot, options) — the winning root (None if none matched)
        and a dict of optimizer options ({"pdhg_eps": ..., ...}).
    """
    roots = list(prefix) if isinstance(prefix, (list, tuple)) else [prefix]

    def get(k):
        """One safe accessor: .get when available, else item lookup;
        a missing knob is None either way (never KeyError)."""
        getter = getattr(cfg, "get", None)
        try:
            return getter(k) if getter is not None else cfg[k]
        except KeyError:
            return None

    def keyed(root, knob):
        return (f"solver_{knob}" if root == ""
                else f"{root}_solver_{knob}")

    checked = []
    for sroot in roots:
        hits = {}
        for knob in KNOBS:
            k = keyed(sroot, knob)
            checked.append(k)
            v = get(k)
            if v is not None:
                hits[f"pdhg_{knob}"] = v
        ostr = get(keyed(sroot, "options"))
        if ostr:
            for k, v in (option_string_to_dict(ostr) or {}).items():
                hits[k if k.startswith("pdhg_") else f"pdhg_{k}"] = v
        if hits:
            return sroot, hits
    if name_required:
        raise RuntimeError(
            f"no solver specification found; checked {checked}")
    return None, {}

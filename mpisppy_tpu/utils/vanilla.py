"""vanilla — hub/spoke dict factories from a Config (reference:
mpisppy/utils/cfg_vanilla.py, 637 LoC).

Each factory returns the dict schema WheelSpinner consumes.  All
factories share the signature (cfg, scenario_creator,
scenario_denouement, all_scenario_names, ...) of the reference, plus
the fast-path `batch=` keyword (a prebuilt ScenarioBatch) that skips
the per-scenario creator loop.
"""

from __future__ import annotations

from ..cylinders.fwph_spoke import FrankWolfeOuterBound
from ..cylinders.hub import APHHub, LShapedHub, PHHub
from ..cylinders.lagranger_bounder import LagrangerOuterBound
from ..cylinders.lagrangian_bounder import LagrangianOuterBound
from ..cylinders.lshaped_bounder import XhatLShapedInnerBound
from ..cylinders.slam_heuristic import SlamMaxHeuristic, SlamMinHeuristic
from ..cylinders.xhatlooper_bounder import XhatLooperInnerBound
from ..cylinders.xhatshufflelooper_bounder import XhatShuffleInnerBound
from ..cylinders.xhatspecific_bounder import XhatSpecificInnerBound
from ..cylinders.xhatxbar_bounder import XhatXbarInnerBound
from ..fwph.fwph import FWPH
from ..opt.aph import APH
from ..opt.lshaped import LShapedMethod
from ..opt.ph import PH
from ..utils.xhat_eval import Xhat_Eval


def shared_options(cfg):
    return cfg.options_dict()


def _opt_kwargs(cfg, scenario_creator, scenario_denouement,
                all_scenario_names, scenario_creator_kwargs=None,
                batch=None, rho_setter=None, all_nodenames=None,
                extensions=None, extension_kwargs=None, extra=None,
                solver_root=None):
    opts = shared_options(cfg)
    if solver_root:
        # per-cylinder kernel-knob cascade (reference
        # utils/solver_spec.py: each spoke may carry its own
        # {root}_solver_* configuration)
        from .solver_spec import solver_specification
        _, sopts = solver_specification(cfg, [solver_root, ""],
                                        name_required=False)
        opts.update(sopts)
    if extra:
        opts.update(extra)
    kw = dict(options=opts,
              all_scenario_names=all_scenario_names,
              scenario_creator=scenario_creator,
              scenario_denouement=scenario_denouement,
              scenario_creator_kwargs=scenario_creator_kwargs,
              batch=batch)
    if rho_setter is not None:
        kw["rho_setter"] = rho_setter
    if all_nodenames is not None:
        kw["all_nodenames"] = all_nodenames
    if extensions is not None:
        kw["extensions"] = extensions
        kw["extension_kwargs"] = extension_kwargs
    return kw


def _hub_options(cfg):
    o = {}
    for k in ("rel_gap", "abs_gap", "max_stalled_iters"):
        if cfg.get(k) is not None:
            o[k] = cfg[k]
    o["convthresh"] = cfg.get("convthresh", 1e-4)
    return o


def ph_hub(cfg, scenario_creator, scenario_denouement,
           all_scenario_names, scenario_creator_kwargs=None,
           ph_extensions=None, extension_kwargs=None, rho_setter=None,
           all_nodenames=None, batch=None):
    """Reference cfg_vanilla.py:77 ph_hub."""
    return {
        "hub_class": PHHub,
        "hub_kwargs": {"options": _hub_options(cfg)},
        "opt_class": PH,
        "opt_kwargs": _opt_kwargs(
            cfg, scenario_creator, scenario_denouement,
            all_scenario_names, scenario_creator_kwargs, batch,
            rho_setter, all_nodenames, ph_extensions, extension_kwargs),
    }


def aph_hub(cfg, scenario_creator, scenario_denouement,
            all_scenario_names, scenario_creator_kwargs=None,
            ph_extensions=None, extension_kwargs=None, rho_setter=None,
            all_nodenames=None, batch=None):
    """Reference cfg_vanilla.py:128 aph_hub."""
    d = ph_hub(cfg, scenario_creator, scenario_denouement,
               all_scenario_names, scenario_creator_kwargs,
               ph_extensions, extension_kwargs, rho_setter,
               all_nodenames, batch)
    d["hub_class"] = APHHub
    d["opt_class"] = APH
    return d


def lshaped_hub(cfg, scenario_creator, scenario_denouement,
                all_scenario_names, scenario_creator_kwargs=None,
                batch=None):
    opts = shared_options(cfg)
    opts.update({"max_iter": cfg.get("max_iterations", 50),
                 "tol": cfg.get("convthresh", 1e-6)})
    return {
        "hub_class": LShapedHub,
        "hub_kwargs": {"options": _hub_options(cfg)},
        "opt_class": LShapedMethod,
        "opt_kwargs": dict(options=opts,
                           all_scenario_names=all_scenario_names,
                           scenario_creator=scenario_creator,
                           scenario_creator_kwargs=scenario_creator_kwargs,
                           batch=batch),
    }


def _spoke(spoke_class, opt_class, cfg, scenario_creator,
           scenario_denouement, all_scenario_names,
           scenario_creator_kwargs=None, batch=None, extra=None,
           spoke_options=None, all_nodenames=None, solver_root=None):
    if solver_root is None:
        # "LagrangianOuterBound" -> "lagrangian", etc.
        solver_root = spoke_class.__name__.replace(
            "OuterBound", "").replace("InnerBound", "").replace(
            "Heuristic", "").lower()
    return {
        "spoke_class": spoke_class,
        "spoke_kwargs": {"options": spoke_options or {}},
        "opt_class": opt_class,
        "opt_kwargs": _opt_kwargs(
            cfg, scenario_creator, scenario_denouement,
            all_scenario_names, scenario_creator_kwargs, batch,
            all_nodenames=all_nodenames, extra=extra,
            solver_root=solver_root),
    }


def fwph_spoke(cfg, scenario_creator, scenario_denouement,
               all_scenario_names, scenario_creator_kwargs=None,
               batch=None):
    """Reference cfg_vanilla.py:277."""
    # explicit root: the derived name would be 'frankwolfe', but the
    # flag convention (fwph_args, fwph_solver_*) uses 'fwph'
    return _spoke(FrankWolfeOuterBound, FWPH, cfg, scenario_creator,
                  scenario_denouement, all_scenario_names,
                  scenario_creator_kwargs, batch, solver_root="fwph")


def lagrangian_spoke(cfg, scenario_creator, scenario_denouement,
                     all_scenario_names, scenario_creator_kwargs=None,
                     rho_setter=None, batch=None):
    """Reference cfg_vanilla.py:320."""
    return _spoke(LagrangianOuterBound, PH, cfg, scenario_creator,
                  scenario_denouement, all_scenario_names,
                  scenario_creator_kwargs, batch)


def lagranger_spoke(cfg, scenario_creator, scenario_denouement,
                    all_scenario_names, scenario_creator_kwargs=None,
                    rho_setter=None, batch=None):
    """Reference cfg_vanilla.py:356."""
    extra = {}
    if cfg.get("lagranger_rho_rescale_factors_json"):
        import json
        with open(cfg["lagranger_rho_rescale_factors_json"]) as f:
            extra["lagranger_rho_rescale_factors"] = {
                int(k): v for k, v in json.load(f).items()}
    return _spoke(LagrangerOuterBound, PH, cfg, scenario_creator,
                  scenario_denouement, all_scenario_names,
                  scenario_creator_kwargs, batch, extra=extra)


def xhatlooper_spoke(cfg, scenario_creator, scenario_denouement,
                     all_scenario_names, scenario_creator_kwargs=None,
                     batch=None):
    """Reference cfg_vanilla.py:393."""
    return _spoke(XhatLooperInnerBound, Xhat_Eval, cfg,
                  scenario_creator, scenario_denouement,
                  all_scenario_names, scenario_creator_kwargs, batch,
                  spoke_options={"scen_limit":
                                 cfg.get("xhat_scen_limit", 3)})


def xhatshuffle_spoke(cfg, scenario_creator, scenario_denouement,
                      all_scenario_names, scenario_creator_kwargs=None,
                      all_nodenames=None, batch=None):
    return _spoke(XhatShuffleInnerBound, Xhat_Eval, cfg,
                  scenario_creator, scenario_denouement,
                  all_scenario_names, scenario_creator_kwargs, batch,
                  all_nodenames=all_nodenames,
                  spoke_options={"reverse":
                                 cfg.get("add_reversed_shuffle", False)})


def xhatspecific_spoke(cfg, scenario_creator, scenario_denouement,
                       all_scenario_names, scenario_dict=None,
                       scenario_creator_kwargs=None, all_nodenames=None,
                       batch=None):
    return _spoke(XhatSpecificInnerBound, Xhat_Eval, cfg,
                  scenario_creator, scenario_denouement,
                  all_scenario_names, scenario_creator_kwargs, batch,
                  all_nodenames=all_nodenames,
                  spoke_options={"xhat_scenario_dict":
                                 scenario_dict or {}})


def xhatxbar_spoke(cfg, scenario_creator, scenario_denouement,
                   all_scenario_names, scenario_creator_kwargs=None,
                   batch=None):
    """Reference cfg_vanilla.py:424."""
    return _spoke(XhatXbarInnerBound, Xhat_Eval, cfg, scenario_creator,
                  scenario_denouement, all_scenario_names,
                  scenario_creator_kwargs, batch)


def xhatlshaped_spoke(cfg, scenario_creator, scenario_denouement,
                      all_scenario_names, scenario_creator_kwargs=None,
                      batch=None):
    return _spoke(XhatLShapedInnerBound, Xhat_Eval, cfg,
                  scenario_creator, scenario_denouement,
                  all_scenario_names, scenario_creator_kwargs, batch)


def slammax_spoke(cfg, scenario_creator, scenario_denouement,
                  all_scenario_names, scenario_creator_kwargs=None,
                  batch=None):
    return _spoke(SlamMaxHeuristic, Xhat_Eval, cfg, scenario_creator,
                  scenario_denouement, all_scenario_names,
                  scenario_creator_kwargs, batch)


def slammin_spoke(cfg, scenario_creator, scenario_denouement,
                  all_scenario_names, scenario_creator_kwargs=None,
                  batch=None):
    return _spoke(SlamMinHeuristic, Xhat_Eval, cfg, scenario_creator,
                  scenario_denouement, all_scenario_names,
                  scenario_creator_kwargs, batch)


def build_spokes(cfg, scenario_creator, scenario_denouement,
                 all_scenario_names, scenario_creator_kwargs=None,
                 batch=None, all_nodenames=None, scenario_dict=None):
    """Flag-driven spoke list — the single home of the cfg-flag ->
    factory dispatch (shared by Amalgamator and example drivers)."""
    sk = scenario_creator_kwargs
    spokes = []
    if cfg.get("fwph"):
        spokes.append(fwph_spoke(cfg, scenario_creator,
                                 scenario_denouement,
                                 all_scenario_names, sk, batch=batch))
    if cfg.get("lagrangian"):
        spokes.append(lagrangian_spoke(cfg, scenario_creator,
                                       scenario_denouement,
                                       all_scenario_names, sk,
                                       batch=batch))
    if cfg.get("lagranger"):
        spokes.append(lagranger_spoke(cfg, scenario_creator,
                                      scenario_denouement,
                                      all_scenario_names, sk,
                                      batch=batch))
    if cfg.get("xhatlooper"):
        spokes.append(xhatlooper_spoke(cfg, scenario_creator,
                                       scenario_denouement,
                                       all_scenario_names, sk,
                                       batch=batch))
    if cfg.get("xhatshuffle"):
        spokes.append(xhatshuffle_spoke(cfg, scenario_creator,
                                        scenario_denouement,
                                        all_scenario_names, sk,
                                        all_nodenames=all_nodenames,
                                        batch=batch))
    if cfg.get("xhatspecific"):
        spokes.append(xhatspecific_spoke(cfg, scenario_creator,
                                         scenario_denouement,
                                         all_scenario_names,
                                         scenario_dict=scenario_dict,
                                         scenario_creator_kwargs=sk,
                                         all_nodenames=all_nodenames,
                                         batch=batch))
    if cfg.get("xhatxbar"):
        spokes.append(xhatxbar_spoke(cfg, scenario_creator,
                                     scenario_denouement,
                                     all_scenario_names, sk,
                                     batch=batch))
    if cfg.get("xhatlshaped"):
        spokes.append(xhatlshaped_spoke(cfg, scenario_creator,
                                        scenario_denouement,
                                        all_scenario_names, sk,
                                        batch=batch))
    if cfg.get("slammax"):
        spokes.append(slammax_spoke(cfg, scenario_creator,
                                    scenario_denouement,
                                    all_scenario_names, sk,
                                    batch=batch))
    if cfg.get("slammin"):
        spokes.append(slammin_spoke(cfg, scenario_creator,
                                    scenario_denouement,
                                    all_scenario_names, sk,
                                    batch=batch))
    return spokes


def extension_adder(hub_dict, ext_class, ext_kwargs=None):
    """Attach an extension class to a hub dict (reference
    cfg_vanilla.py:164): promotes to MultiExtension on the second."""
    from ..extensions import MultiExtension
    kw = hub_dict["opt_kwargs"]
    cur = kw.get("extensions")
    if cur is None:
        kw["extensions"] = ext_class
        kw["extension_kwargs"] = ext_kwargs
    elif cur is MultiExtension:
        kw["extension_kwargs"]["ext_classes"].append(ext_class)
    else:
        kw["extensions"] = MultiExtension
        kw["extension_kwargs"] = {"ext_classes": [cur, ext_class]}
    return hub_dict


def add_fixer(hub_dict, cfg):
    """Reference cfg_vanilla.py:184."""
    from ..extensions.fixer import Fixer
    hub_dict["opt_kwargs"]["options"]["fixeroptions"] = {
        "boundtol": cfg.get("fixer_tol", 1e-2),
        "nb": cfg.get("fixer_nb", 3)}
    return extension_adder(hub_dict, Fixer)


def add_multi_rho(hub_dict, cfg):
    from ..extensions.mult_rho_updater import MultRhoUpdater
    hub_dict["opt_kwargs"]["options"]["mult_rho_options"] = {
        "convergence_tolerance":
            cfg.get("mult_rho_convergence_tolerance", 1e-4),
        "rho_update_stop_iteration":
            cfg.get("mult_rho_update_stop_iteration"),
        "rho_update_start_iteration":
            cfg.get("mult_rho_update_start_iteration", 2)}
    return extension_adder(hub_dict, MultRhoUpdater)


def add_norm_rho(hub_dict, cfg):
    from ..extensions.norm_rho_updater import NormRhoUpdater
    return extension_adder(hub_dict, NormRhoUpdater)


def add_wtracker(hub_dict, cfg):
    from ..extensions.wtracker_extension import Wtracker_extension
    hub_dict["opt_kwargs"]["options"]["wtracker_options"] = {
        "wlen": cfg.get("wtracker_wlen", 10)}
    return extension_adder(hub_dict, Wtracker_extension)

"""WTracker — W-history statistics to flag dual-weight oscillation
(reference: mpisppy/utils/wtracker.py:18-203).

Keeps a ring buffer of the last `wlen` iterations' W arrays and reports
per-slot moving mean / stdev; slots whose stdev stays large relative to
their mean after many iterations indicate PH cycling (the reference's
report_by_moving_stats).  Vectorized over the whole (S, K) W tensor.
"""

from __future__ import annotations

import collections

import numpy as np


class WTracker:
    def __init__(self, ph, wlen=10):
        self.opt = ph
        self.wlen = int(wlen)
        # (iter, (S, K) np array) entries; deque(maxlen) evicts the
        # oldest in O(1) — list.pop(0) is O(n) per iteration
        self._hist = collections.deque(maxlen=self.wlen)

    def grab_local_Ws(self):
        """Record this iteration's W (reference wtracker.py:46)."""
        st = self.opt.state
        if st is None:
            return
        self._hist.append((int(st.it), np.asarray(st.W).copy()))

    def moving_stats(self):
        """(mean, std) arrays (S, K) over the window; None if empty."""
        if not self._hist:
            return None, None
        stack = np.stack([w for _, w in self._hist])
        return stack.mean(axis=0), stack.std(axis=0)

    def report_by_moving_stats(self, stdevthresh=None, file=None):
        """Flag slots with stdev above `stdevthresh` (reference
        wtracker.py:76-133).  Returns the count of flagged slots."""
        mean, std = self.moving_stats()
        if mean is None:
            return 0
        if stdevthresh is None:
            stdevthresh = float(np.median(np.abs(mean)) + 1e-12)
        flagged = std > stdevthresh
        n = int(flagged.sum())
        lines = [f"WTracker: window={len(self._hist)} iters, "
                 f"{n} W slots with stdev > {stdevthresh:g}"]
        if n:
            s_idx, k_idx = np.nonzero(flagged)
            names = self.opt.batch.tree.nonant_names
            for s, k in list(zip(s_idx, k_idx))[:10]:
                nm = names[k] if k < len(names) else str(k)
                lines.append(f"  scen {s} {nm}: mean {mean[s, k]:.4g} "
                             f"stdev {std[s, k]:.4g}")
        out = "\n".join(lines)
        if file is not None:
            print(out, file=file)
        else:
            print(out)
        return n

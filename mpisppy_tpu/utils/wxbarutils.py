"""W / xbar persistence — PH warm-start checkpointing (reference:
mpisppy/utils/wxbarutils.py, 594 LoC incl. wxbarwriter/wxbarreader:
CSVs of W and xbar written each iteration, read at init).

Arrays here: one .npz holds W (S, K) and xbar (S, K) plus the nonant
names for sanity checks; CSV export/import kept for the reference's
file format (rows: scenario, varname, value).
"""

from __future__ import annotations

import csv
import os

import numpy as np


def _norm_npz(path):
    """np.savez appends '.npz' to suffix-less names; normalize so the
    writer and reader agree on the real filename."""
    path = str(path)
    return path if path.endswith(".npz") else path + ".npz"


def write_W_and_xbar(path, opt):
    """Persist the current PH dual state (reference ROOT usage:
    WXBarWriter extension).  Atomic through the one shared tmp-rename
    helper (resilience.checkpoint.atomic_write); savez on a FILE
    OBJECT keeps the name verbatim (the path form would append .npz)."""
    import io

    from ..resilience.checkpoint import atomic_write
    st = opt.state
    buf = io.BytesIO()
    np.savez_compressed(
        buf,
        W=np.asarray(st.W), xbar=np.asarray(st.xbar),
        nonant_names=np.array(opt.batch.tree.nonant_names,
                              dtype=object)
        if opt.batch.tree.nonant_names else np.array([], dtype=object),
        it=int(st.it))
    atomic_write(_norm_npz(path), buf.getvalue())


def read_W_and_xbar(path, opt):
    """Load and install W/xbar into the optimizer's state (after
    Iter0) — the reference's WXBarReader init path."""
    import dataclasses

    import jax.numpy as jnp
    z = np.load(_norm_npz(path), allow_pickle=True)
    W = np.asarray(z["W"])
    xbar = np.asarray(z["xbar"])
    st = opt.state
    S, K = np.asarray(st.W).shape
    if W.shape != (S, K) or xbar.shape != (S, K):
        raise ValueError(
            f"checkpoint shapes W{W.shape}/xbar{xbar.shape} != "
            f"current (S,K)=({S},{K})")
    saved_names = tuple(np.asarray(z["nonant_names"]).tolist())
    cur_names = tuple(opt.batch.tree.nonant_names or ())
    if saved_names and cur_names and saved_names != cur_names:
        raise ValueError(
            "checkpoint nonant names do not match this model: "
            f"{saved_names[:3]}... vs {cur_names[:3]}...")
    dt = np.asarray(st.W).dtype
    opt.state = dataclasses.replace(
        st, W=jnp.asarray(W, dt), xbar=jnp.asarray(xbar, dt))


def write_W_csv(path, opt):
    """Reference-format CSV: scenario, varname, W value."""
    st = opt.state
    W = np.asarray(st.W)
    names = opt.batch.tree.nonant_names or tuple(
        str(k) for k in range(W.shape[1]))
    scen_names = opt.batch.tree.scen_names or tuple(
        str(s) for s in range(W.shape[0]))
    with open(path, "w", newline="") as f:
        w = csv.writer(f)
        for s in range(min(opt.n_real_scens, W.shape[0])):
            for k in range(W.shape[1]):
                w.writerow([scen_names[s], names[k], W[s, k]])


def read_W_csv(path, opt):
    """Read the reference-format CSV back into an (S, K) array."""
    st = opt.state
    W = np.array(np.asarray(st.W), copy=True)
    names = {n: k for k, n in enumerate(
        opt.batch.tree.nonant_names
        or tuple(str(k) for k in range(W.shape[1])))}
    scen = {n: s for s, n in enumerate(
        opt.batch.tree.scen_names
        or tuple(str(s) for s in range(W.shape[0])))}
    with open(path, newline="") as f:
        for row in csv.reader(f):
            if len(row) != 3:
                continue
            s, k = scen.get(row[0]), names.get(row[1])
            if s is not None and k is not None:
                W[s, k] = float(row[2])
    return W

"""Xhat_Eval — candidate-solution evaluation engine
(reference: mpisppy/utils/xhat_eval.py, 434 LoC).

Fix the nonant variables to a candidate value, solve every scenario,
return the expected objective — an inner (upper, for minimization)
bound when feasible.  The reference fixes Pyomo vars and loops solver
calls (xhat_eval.py:293 evaluate, :261 evaluate_one); here fixing is a
bounds-array rewrite and the solve is one batched PDHG call.  Multiple
candidates can be evaluated in ONE solve by stacking them — the
"speculative parallelism" of the reference's xhat spokes
(SURVEY.md §2.10) becomes literal batching.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..spopt import SPOpt


class Xhat_Eval(SPOpt):
    """Standalone evaluator (constructible exactly like SPOpt); also
    usable as a mixin via `evaluate` on any SPOpt subclass."""

    def evaluate(self, nonant_values, upto_stage=None, tol=None):
        """Expected objective with nonants fixed to `nonant_values`
        ((K,) or (S, K)).  Returns (Eobj, feasible: bool).
        Reference: xhat_eval.py:293 + extensions/xhatbase.py:38 _try_one.
        """
        return self.evaluate_xhat(nonant_values, upto_stage=upto_stage,
                                  tol=tol)

    def evaluate_one(self, nonant_values, scen_index):
        """Single-scenario objective at a fixed candidate
        (reference xhat_eval.py:261)."""
        lb, ub = self.fixed_nonant_bounds(nonant_values)
        res = self.solve_loop(lb=lb, ub=ub, warm=False)
        return float(res.obj[scen_index])

    def evaluate_candidates(self, candidates, tol=None):
        """Evaluate k candidates at once: candidates (k, K).

        Builds a (k*S)-scenario stacked solve by tiling the batch along
        the scenario axis — one kernel launch evaluates every candidate
        against every scenario.  Returns (Eobjs (k,), feas (k,)).
        """
        cands = np.asarray(candidates)
        k = cands.shape[0]
        outs = []
        feass = []
        # Round 1: loop candidates (still one batched solve per
        # candidate); true k*S stacking lands with the cylinder layer.
        for i in range(k):
            e, f = self.evaluate(cands[i], tol=tol)
            outs.append(e)
            feass.append(f)
        return np.array(outs), np.array(feass)


def calculate_incumbent(ev: Xhat_Eval, candidates):
    """Best feasible candidate (reference xhat_eval.py:402)."""
    objs, feas = ev.evaluate_candidates(candidates)
    objs = np.where(feas, objs, np.inf)
    i = int(np.argmin(objs))
    if not np.isfinite(objs[i]):
        return None, None
    return i, float(objs[i])

"""Xhat_Eval — candidate-solution evaluation engine
(reference: mpisppy/utils/xhat_eval.py, 434 LoC).

Fix the nonant variables to a candidate value, solve every scenario,
return the expected objective — an inner (upper, for minimization)
bound when feasible.  The reference fixes Pyomo vars and loops solver
calls (xhat_eval.py:293 evaluate, :261 evaluate_one); here fixing is a
bounds-array rewrite and the solve is one batched PDHG call.  Multiple
candidates can be evaluated in ONE solve by stacking them — the
"speculative parallelism" of the reference's xhat spokes
(SURVEY.md §2.10) becomes literal batching.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..spopt import SPOpt


class Xhat_Eval(SPOpt):
    """Standalone evaluator (constructible exactly like SPOpt); also
    usable as a mixin via `evaluate` on any SPOpt subclass."""

    def evaluate(self, nonant_values, upto_stage=None, tol=None):
        """Expected objective with nonants fixed to `nonant_values`
        ((K,) or (S, K)).  Returns (Eobj, feasible: bool).
        Reference: xhat_eval.py:293 + extensions/xhatbase.py:38 _try_one.
        """
        return self.evaluate_xhat(nonant_values, upto_stage=upto_stage,
                                  tol=tol)

    def evaluate_one(self, nonant_values, scen_index):
        """Single-scenario objective at a fixed candidate
        (reference xhat_eval.py:261)."""
        lb, ub = self.fixed_nonant_bounds(nonant_values)
        res = self.solve_loop(lb=lb, ub=ub, warm=False)
        return float(res.obj[scen_index])

    # evaluate_candidates — k*S stacked single-launch evaluation — is
    # inherited from SPOpt (spopt.py): the reduced second-stage system
    # is tiled k-fold along the scenario axis, so one kernel launch
    # scores every candidate against every scenario.


def calculate_incumbent(ev: Xhat_Eval, candidates):
    """Best feasible candidate (reference xhat_eval.py:402).

    Two passes: the stacked screening solve ranks all candidates in one
    kernel launch, then the winner's bound is CERTIFIED through
    evaluate_xhat (f64 fallback for stragglers) so the published
    incumbent value is trustworthy.  If screening declares every
    candidate infeasible, the best-objective one still gets the
    certified re-check — a fast-solve pres failure is not proof of
    infeasibility."""
    cands = np.asarray(candidates)
    objs, feas = ev.evaluate_candidates(cands)
    ranked = np.where(feas, objs, np.inf)
    i = int(np.argmin(ranked))
    if not np.isfinite(ranked[i]):
        i = int(np.argmin(objs))
    obj, ok = ev.evaluate_xhat(cands[i], certify="auto")
    if not ok:
        return None, None
    return i, float(obj)

"""Candidate (xhat) construction helpers shared by xhat spokes and
in-hub xhat extensions (reference: mpisppy/extensions/xhatbase.py:38
_try_one walks the tree picking a source scenario per node and copying
its nonant values; cylinders/xhatshufflelooper_bounder.py ScenarioCycler
builds the node->scenario dicts).

Array form: a candidate is a (S, K) matrix of nonant values, built by
gathering value slot j of scenario s from the SOURCE scenario assigned
to the tree node owning (s, j).  For a two-stage problem that is one
row broadcast; multistage gets per-node sources.
"""

from __future__ import annotations

import numpy as np


def node_members(node_of):
    """{node_id: sorted list of scenario indices through that node},
    derived purely from the batch's node_of array (no tree object
    needed)."""
    node_of = np.asarray(node_of)
    out = {}
    for s in range(node_of.shape[0]):
        for n in np.unique(node_of[s]):
            out.setdefault(int(n), []).append(s)
    return out


def full_source_map(node_of, base_scen, members=None):
    """(num_used_nodes,)-dict {node: src}: base_scen wherever it passes
    through; else the smallest-index member scenario.  The analog of
    completing a partial xhat scenario dict over the whole tree."""
    node_of = np.asarray(node_of)
    if members is None:
        members = node_members(node_of)
    base_nodes = set(int(n) for n in np.unique(node_of[base_scen]))
    return {n: (base_scen if n in base_nodes else mem[0])
            for n, mem in members.items()}


def candidate_from_sources(x_na, node_of, node_to_src):
    """(S, K) candidate: value (s, j) taken from scenario
    node_to_src[node_of[s, j]].

    x_na: (S, K) per-scenario nonant values; node_to_src: dict or
    (num_nodes,) array."""
    x_na = np.asarray(x_na)
    node_of = np.asarray(node_of)
    if isinstance(node_to_src, dict):
        arr = np.zeros(int(node_of.max()) + 1, np.int64)
        for n, s in node_to_src.items():
            arr[int(n)] = int(s)
        node_to_src = arr
    srcs = node_to_src[node_of]                       # (S, K)
    return np.take_along_axis(x_na, srcs, axis=0)


def round_integer_nonants(batch, candidate):
    """Round candidate values on integer nonant slots (the fix-and-
    round MIP recovery step; reference xhat machinery relies on the
    solver for integrality — here integrality is restored by rounding
    before the fixed evaluation)."""
    cand = np.asarray(candidate, dtype=float).copy()
    imask = np.asarray(batch.integer_mask)[:, np.asarray(batch.nonant_idx)]
    if cand.ndim == 1:
        imask0 = imask[0] if imask.ndim == 2 else imask
        cand[imask0] = np.round(cand[imask0])
    else:
        cand[imask] = np.round(cand[imask])
    return cand

"""Test harness config: force an 8-virtual-device CPU platform with
float64 so the sharding/collective layer is exercised without TPU
hardware — the analog of the reference's `mpiexec -np N` single-box
test tier (reference: run-mpitests.py, mpisppy/tests/straight_tests.py).

The TPU plugin (axon) may be pre-registered by sitecustomize; it must be
deregistered BEFORE the first backend initialization or CPU-only test
runs can hang on the device tunnel.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

from mpisppy_tpu.utils.platform import ensure_cpu_backend  # noqa: E402

ensure_cpu_backend(force=True)

import jax  # noqa: E402

jax.config.update("jax_enable_x64", True)


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: long-running tests excluded from the tier-1 "
        "`-m 'not slow'` selection")
    config.addinivalue_line(
        "markers", "chaos: deterministic fault-injection tests "
        "(resilience layer); these RUN under tier-1's `-m 'not slow'`")
    config.addinivalue_line(
        "markers", "telemetry: observability-layer tests (tracing, "
        "metrics, trace export); these RUN under tier-1's "
        "`-m 'not slow'`")
    config.addinivalue_line(
        "markers", "serve: solver-as-a-service layer tests (compile "
        "cache, coalescing, admission control, parity); these RUN "
        "under tier-1's `-m 'not slow'`")
    config.addinivalue_line(
        "markers", "pdhg: adaptive-work solver tests (KKT-triggered "
        "restarts, compaction, inexactness ladder, trace-safety "
        "guard); these RUN under tier-1's `-m 'not slow'`")
    config.addinivalue_line(
        "markers", "precision: mixed-precision hot-loop tests "
        "(hot_dtype, promotion, sparse matvecs, dtype-aware MFU); "
        "these RUN under tier-1's `-m 'not slow'`")
    config.addinivalue_line(
        "markers", "streaming: minibatch randomized-PH streaming tests "
        "(ScenarioSource blocks, double-buffered stream, adaptive "
        "sampler, StreamingPH parity/checkpoint); these RUN under "
        "tier-1's `-m 'not slow'`")
    config.addinivalue_line(
        "markers", "mpmd: device-resident MPMD wheel tests (slice "
        "plans, device mailboxes, seqlock parity, slice supervision) "
        "on the faked 8-device fleet; these RUN under tier-1's "
        "`-m 'not slow'`")
    config.addinivalue_line(
        "markers", "storage: durable shard-store tests (checksummed "
        "corpus, readahead, quarantine + certified-gap accounting, "
        "storage-cursor resume); these RUN under tier-1's "
        "`-m 'not slow'`")
    config.addinivalue_line(
        "markers", "net: network front-door tests (wire protocol, "
        "gateway/client over real sockets, AOT executable persistence, "
        "rolling restart); these RUN under tier-1's `-m 'not slow'`")
    config.addinivalue_line(
        "markers", "procserve: process-replica fleet tests (OS-process "
        "workers over loopback sockets, SIGKILL fault paths, DRR "
        "dispatch fairness, AOT prewarm/eviction) with a CPU-safe "
        "small process count; these RUN under tier-1's `-m 'not slow'`")

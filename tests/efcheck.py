"""Independent EF oracle: assemble the extensive form of a
ScenarioBatch as one big scipy.optimize.linprog problem (continuous
relaxation) and solve it with HiGHS.

This is the tests' ground truth for new model lowerings AND for the
consensus-mode PDHG kernel: per-scenario blocks on the diagonal,
explicit nonanticipativity equality rows chaining scenarios that share
a tree node — exactly the reference's EF construction
(reference sputils.py:209-341) done in scipy instead of Pyomo.
"""

import numpy as np
from scipy.optimize import LinearConstraint, linprog, milp
from scipy.optimize import Bounds as ScipyBounds
from scipy.sparse import lil_matrix


def ef_linprog(batch, n_real=None):
    """Returns (optimal value, per-scenario x (S, N)) of the EF LP
    relaxation.  Uses only the first n_real scenarios (drop padding)."""
    A = np.asarray(batch.A)
    S = batch.num_scens if n_real is None else n_real
    if A.shape[0] == 1 and S > 1:     # shared-A batch (ir.shared_A)
        A = np.broadcast_to(A[0], (S,) + A.shape[1:])
    A = A[:S]
    N = A.shape[2]
    Mr = A.shape[1]
    prob = np.asarray(batch.prob)[:S]
    prob = prob / prob.sum()
    c = (prob[:, None] * np.asarray(batch.c)[:S]).reshape(-1)
    lo = np.asarray(batch.row_lo)[:S]
    hi = np.asarray(batch.row_hi)[:S]
    lb = np.asarray(batch.lb)[:S].reshape(-1)
    ub = np.asarray(batch.ub)[:S].reshape(-1)

    # inequality rows: block-diagonal, two-sided split into <=
    rows_ub = []
    rhs_ub = []
    rows_eq = []
    rhs_eq = []
    for s in range(S):
        for m in range(Mr):
            a = np.zeros(S * N)
            a[s * N:(s + 1) * N] = A[s, m]
            if np.isfinite(lo[s, m]) and np.isfinite(hi[s, m]) and \
                    lo[s, m] == hi[s, m]:
                rows_eq.append(a)
                rhs_eq.append(lo[s, m])
                continue
            if np.isfinite(hi[s, m]):
                rows_ub.append(a)
                rhs_ub.append(hi[s, m])
            if np.isfinite(lo[s, m]):
                rows_ub.append(-a)
                rhs_ub.append(-lo[s, m])

    # nonanticipativity: chain equal-node scenario pairs per slot
    na = np.asarray(batch.nonant_idx)
    node_of = np.asarray(batch.tree.node_of)[:S]
    for k, col in enumerate(na):
        by_node = {}
        for s in range(S):
            by_node.setdefault(int(node_of[s, k]), []).append(s)
        for members in by_node.values():
            for s1, s2 in zip(members, members[1:]):
                a = np.zeros(S * N)
                a[s1 * N + col] = 1.0
                a[s2 * N + col] = -1.0
                rows_eq.append(a)
                rhs_eq.append(0.0)

    res = linprog(
        c,
        A_ub=np.array(rows_ub) if rows_ub else None,
        b_ub=np.array(rhs_ub) if rhs_ub else None,
        A_eq=np.array(rows_eq) if rows_eq else None,
        b_eq=np.array(rhs_eq) if rhs_eq else None,
        bounds=list(zip(np.where(np.isfinite(lb), lb, None),
                        np.where(np.isfinite(ub), ub, None))),
        method="highs")
    assert res.status == 0, f"linprog failed: {res.message}"
    const = float(prob @ np.asarray(batch.obj_const)[:S])
    return res.fun + const, res.x.reshape(S, N)


def ef_milp(batch, n_real=None, mip_rel_gap=1e-6, time_limit=None):
    """Ground-truth EF MILP optimum via scipy/HiGHS branch-and-cut
    (integrality from batch.integer_mask).  Returns (optimal value,
    per-scenario x (S, N)).  The integer analog of ef_linprog, used to
    pin the reference's integer goldens (e.g. sizes-3 EF == 220000 at
    2 sig figs, reference test_ef_ph.py:137)."""
    A = np.asarray(batch.A)
    S = batch.num_scens if n_real is None else n_real
    if A.shape[0] == 1 and S > 1:     # shared-A batch (ir.shared_A)
        A = np.broadcast_to(A[0], (S,) + A.shape[1:])
    A = A[:S]
    N = A.shape[2]
    Mr = A.shape[1]
    prob = np.asarray(batch.prob)[:S]
    prob = prob / prob.sum()
    c = (prob[:, None] * np.asarray(batch.c)[:S]).reshape(-1)
    lo = np.asarray(batch.row_lo)[:S]
    hi = np.asarray(batch.row_hi)[:S]
    lb = np.asarray(batch.lb)[:S].reshape(-1)
    ub = np.asarray(batch.ub)[:S].reshape(-1)

    na = np.asarray(batch.nonant_idx)
    node_of = np.asarray(batch.tree.node_of)[:S]
    n_na_rows = 0
    for k in range(na.size):
        uniq = {}
        for s in range(S):
            uniq.setdefault(int(node_of[s, k]), []).append(s)
        n_na_rows += sum(len(m) - 1 for m in uniq.values())

    n_rows = S * Mr + n_na_rows
    Acon = lil_matrix((n_rows, S * N))
    rlo = np.empty(n_rows)
    rhi = np.empty(n_rows)
    r = 0
    for s in range(S):
        for m in range(Mr):
            nz = np.flatnonzero(A[s, m])
            Acon[r, s * N + nz] = A[s, m, nz]
            rlo[r] = lo[s, m]
            rhi[r] = hi[s, m]
            r += 1
    for k, col in enumerate(na):
        by_node = {}
        for s in range(S):
            by_node.setdefault(int(node_of[s, k]), []).append(s)
        for members in by_node.values():
            for s1, s2 in zip(members, members[1:]):
                Acon[r, s1 * N + col] = 1.0
                Acon[r, s2 * N + col] = -1.0
                rlo[r] = rhi[r] = 0.0
                r += 1
    assert r == n_rows

    integrality = np.asarray(batch.integer_mask)[:S].reshape(-1).astype(
        np.int8)
    opts = {"mip_rel_gap": mip_rel_gap}
    if time_limit is not None:
        opts["time_limit"] = time_limit
    res = milp(
        c,
        constraints=LinearConstraint(Acon.tocsr(), rlo, rhi),
        bounds=ScipyBounds(lb, ub),
        integrality=integrality,
        options=opts)
    # status 1 = time/iteration limit — still fine as an oracle if an
    # incumbent exists and its own MIP gap is tight enough for the
    # 2-sig-fig golden comparisons this feeds
    assert res.status == 0 or (res.status == 1 and res.x is not None), \
        f"milp failed: {res.message}"
    const = float(prob @ np.asarray(batch.obj_const)[:S])
    return res.fun + const, res.x.reshape(S, N)

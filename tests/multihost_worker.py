"""Worker for tests/test_multihost.py: one of N processes in a
jax.distributed CPU 'multi-host' run.

Each process owns 2 virtual CPU devices; the global mesh spans
N_PROCS x 2 devices.  Runs farmer PH (Iter0 + iterations) on the
GLOBAL mesh — the consensus segment-sum reduces across the process
boundary — and prints one JSON line with the trajectory so the parent
test can assert (a) both processes agree and (b) the numbers match a
single-process run of the same instance.
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=2").strip()

# the TPU plugin (axon) may be pre-registered by sitecustomize; it
# must be deregistered BEFORE the first backend init or this CPU-only
# worker can hang on the device tunnel (same rule as tests/conftest.py)
from mpisppy_tpu.utils.platform import ensure_cpu_backend  # noqa: E402

ensure_cpu_backend(force=True)

import jax  # noqa: E402

jax.config.update("jax_enable_x64", True)

from jax.experimental import multihost_utils  # noqa: E402

from mpisppy_tpu.parallel import distributed  # noqa: E402


def main():
    coord, nprocs, pid = sys.argv[1], int(sys.argv[2]), int(sys.argv[3])
    distributed.init_multihost(coordinator_address=coord,
                               num_processes=nprocs, process_id=pid)
    assert jax.process_count() == nprocs
    mesh = distributed.global_mesh()
    assert mesh.size == 2 * nprocs
    assert mesh.multihost

    from mpisppy_tpu.models import farmer
    from mpisppy_tpu.opt.ph import PH

    S = 8
    ph = PH({"defaultPHrho": 1.0, "PHIterLimit": 5, "convthresh": 0.0,
             "pdhg_eps": 1e-7,
             # np.asarray of a sharded global array is per-process;
             # the certified gather path is host-local by design and
             # exercised in the single-process tiers
             "iter0_certify": False},
            [f"scen{i}" for i in range(S)],
            batch=farmer.build_batch(S), mesh=mesh)
    ph.Iter0()
    convs = [ph.ph_iteration() for _ in range(5)]
    lag = ph.lagrangian_bound()
    out = {
        "pid": pid,
        "devices": mesh.size,
        "process_count": jax.process_count(),
        "trivial_bound": float(ph.trivial_bound),
        "convs": [float(c) for c in convs],
        "lagrangian": float(lag),
        "xbar0": [float(v) for v in multihost_utils.process_allgather(
            ph.state.xbar, tiled=True)[0][:3]],
    }
    print("RESULT " + json.dumps(out), flush=True)


if __name__ == "__main__":
    main()

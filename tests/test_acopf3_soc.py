"""Jabr SOC relaxation for acopf3 (VERDICT r4 missing item 5 — the
LP/QP-kernel-shaped step from the DC approximation toward the
reference's AC formulation, examples/acopf3/ccopf_multistage.py
convex_relaxation mode).

What must hold:
  * the outer-approximation loop monotonically TIGHTENS the relaxation
    (cone cuts forbid the fake negative line losses the initial LP
    exploits), so the objective is nondecreasing across refine rounds
    and the max cone violation decreases to ~0;
  * after refinement the physics is AC-sane: losses are nonnegative,
    no load is shed on the nominal network, dead (outaged) lines carry
    zero flow and zero lifted products;
  * the refined batch is an ordinary ScenarioBatch: PH runs on it
    unmodified (same kernel, same consensus machinery).
"""

import numpy as np
import pytest

from mpisppy_tpu.models import acopf3
from mpisppy_tpu.opt.ef import ExtensiveForm
from mpisppy_tpu.opt.ph import PH

OPTS = {"pdhg_eps": 1e-6, "pdhg_max_iters": 100000}


@pytest.fixture(scope="module")
def refined_synthetic():
    b = acopf3.build_soc_batch(branching_factors=(2, 2))
    b2, hist = acopf3.soc_refine(b, rounds=6, opts=dict(OPTS))
    return b, b2, hist


def test_soc_refine_monotone_tightening(refined_synthetic):
    _, _, hist = refined_synthetic
    objs = [h[1] for h in hist]
    viols = [h[2] for h in hist]
    # cuts only shrink the feasible set: objective nondecreasing
    # (small solver-tolerance wiggle allowed)
    for a, bb in zip(objs, objs[1:]):
        assert bb >= a - 1e-3 * abs(a)
    assert objs[-1] > objs[0] * 1.2     # the initial LP was far loose
    assert viols[-1] < 5e-3             # cones ~satisfied at the end
    assert viols[-1] < viols[0] / 10


def test_soc_dead_lines_zero(refined_synthetic):
    """Outaged lines carry no flow and no lifted product — enforced by
    per-scenario boxes, so it holds at ANY feasible point."""
    _, b2, _ = refined_synthetic
    ef = ExtensiveForm(dict(OPTS), list(b2.tree.scen_names), batch=b2)
    ef.solve_extensive_form()
    x = np.asarray(ef._result.x)
    m = b2.model_meta
    alive = np.asarray(m["soc_alive"])          # (S, T, nL)
    for key in ("soc_cc", "soc_ss"):
        v = x[:, np.asarray(m[key])]            # (S, T, nL)
        assert np.abs(v[alive == 0]).max(initial=0.0) < 1e-6


def test_soc_ph_runs_on_refined_batch(refined_synthetic):
    _, b2, _ = refined_synthetic
    ph = PH({"defaultPHrho": 50.0, "PHIterLimit": 10,
             "convthresh": 1e-6, **OPTS},
            list(b2.tree.scen_names), batch=b2)
    conv, eobj, triv = ph.ph_main()
    assert np.isfinite(eobj) and np.isfinite(triv)
    assert triv <= eobj + 1e-3 * abs(eobj)


def test_soc_ieee14_ac_sane():
    """Nominal IEEE14 (no outages): after refinement generation covers
    load PLUS positive AC losses (the DC model has none; measured
    ~7-9 MW at these settings vs the case's true ~13 MW), no shed,
    small residual cone violation.  Budgeted solver settings (40k
    iters/round, warm-started) keep the test under ~4 min; the
    uncapped protocol drives violation to ~1e-3 (examples)."""
    b = acopf3.build_soc_batch(branching_factors=(1,), case="ieee14",
                               soc_cut_slots=8)
    cheap = {"pdhg_eps": 1e-5, "pdhg_max_iters": 40000}
    b2, hist = acopf3.soc_refine(b, rounds=8, opts=dict(cheap))
    ef = ExtensiveForm(dict(cheap), list(b2.tree.scen_names), batch=b2)
    ef.solve_extensive_form()
    x = np.asarray(ef._result.x)[0]
    nG, nB, nL = 5, 14, 20
    pg_mw = x[:nG] * 100.0
    total_load = sum(acopf3._IEEE14_LOAD)
    mp = x[2 * nG + nB + 6 * nL: 2 * nG + 2 * nB + 6 * nL]
    assert np.abs(mp).max() < 1e-2              # no shed
    losses = pg_mw.sum() - total_load
    assert losses > -1.0                        # no fake generation
    # cone violation residual at the incumbent is small (and far
    # below the ~0.28 of the uncut LP)
    assert acopf3.soc_violation(b2, np.asarray(
        ef._result.x)).max() < 5e-2
    # cuts tightened the relaxation monotonically
    objs = [h[1] for h in hist]
    for a, bb in zip(objs, objs[1:]):
        assert bb >= a - 1e-3 * abs(a) - 1.0


def test_soc_violation_shape_and_mask():
    b = acopf3.build_soc_batch(branching_factors=(3,), n_bus=4,
                               n_line=5, n_gen=2)
    S, T, nL = b.num_scens, 2, 5
    x = np.asarray(b.ub) * 0.5
    v = acopf3.soc_violation(b, x)
    assert v.shape == (S, T, nL)
    alive = np.asarray(b.model_meta["soc_alive"])
    assert np.all(v[alive == 0] == 0.0)

"""aircondB (pickle-bundle aircond) + multistage proper bundles
(reference: mpisppy/tests/examples/aircondB.py, utils/pickle_bundle.py
— bundles consume entire stage-2 subtrees, making each bundle a
two-stage subproblem; written/read as per-bundle files)."""

import numpy as np
import pytest

from efcheck import ef_linprog
from mpisppy_tpu.models import aircond, aircondB
from mpisppy_tpu.opt.ph import PH
from mpisppy_tpu.utils.bundles import bundle_batch

BF = (3, 2)


def test_proper_bundle_is_two_stage():
    bb = aircondB.build_batch(BF)
    assert bb.num_scens == 3                # one bundle per subtree
    assert int(np.asarray(bb.tree.node_of).max()) == 0
    base = aircond.build_batch(BF)
    # only the ROOT slots remain nonanticipative across bundles
    stage = np.asarray(base.tree.stage_of)
    assert bb.num_nonants == int((stage == 1).sum())


def test_bundled_ef_matches_multistage_ef():
    base = aircond.build_batch(BF)
    bb = aircondB.build_batch(BF)
    ref, _ = ef_linprog(base, n_real=base.num_scens)
    got, _ = ef_linprog(bb, n_real=bb.num_scens)
    assert got == pytest.approx(ref, rel=1e-8)


def test_misaligned_bundle_raises():
    base = aircond.build_batch(BF)
    with pytest.raises(ValueError, match="entire subtrees"):
        bundle_batch(base, 3)   # 3 leaves != multiple of 2-leaf subtree


def test_pickle_roundtrip_dir(tmp_path):
    d = str(tmp_path / "bundles")
    bb = aircondB.build_batch(BF, pickle_bundles_dir=d)
    bb2 = aircondB.build_batch(BF, unpickle_bundles_dir=d)
    assert bb2.num_scens == bb.num_scens
    for f in ("c", "row_lo", "row_hi", "lb", "ub", "obj_const"):
        np.testing.assert_allclose(np.asarray(getattr(bb2, f)),
                                   np.asarray(getattr(bb, f)))
    ref, _ = ef_linprog(bb, n_real=bb.num_scens)
    got, _ = ef_linprog(bb2, n_real=bb2.num_scens)
    assert got == pytest.approx(ref, rel=1e-10)


def test_ph_on_proper_bundles():
    bb = aircondB.build_batch(BF)
    names = aircondB.scenario_names_creator(
        int(np.prod(BF)), scenarios_per_bundle=2)
    ph = PH({"defaultPHrho": 1.0, "PHIterLimit": 60,
             "convthresh": 1e-5, "pdhg_eps": 1e-7}, names, batch=bb)
    conv, eobj, triv = ph.ph_main()
    ref, _ = ef_linprog(aircond.build_batch(BF), n_real=6)
    assert eobj == pytest.approx(ref, abs=0.02 * abs(ref) + 1.0)

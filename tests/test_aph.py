"""APH tests (reference analog: mpisppy/tests/test_aph.py — farmer
smoke + convergence at low precision)."""

import numpy as np

from mpisppy_tpu.models import farmer
from mpisppy_tpu.opt.aph import APH


def make_aph(num_scens=3, **extra):
    opts = {"defaultPHrho": 1.0, "PHIterLimit": 100, "convthresh": 1e-3,
            "pdhg_eps": 1e-7, "APHgamma": 1.0, "APHnu": 1.0}
    opts.update(extra)
    b = farmer.build_batch(num_scens)
    return APH(opts, [f"scen{i}" for i in range(num_scens)], batch=b)


def test_aph_farmer_converges():
    aph = make_aph()
    conv, eobj, trivial = aph.APH_main()
    # projective splitting drives z to the consensus optimum
    z = np.asarray(aph.root_z())
    assert abs(eobj - -108390.0) < 300.0
    assert np.allclose(z, [170.0, 80.0, 250.0], atol=5.0)
    # the metric must have decreased below threshold or the limit hit
    assert conv < 1.0


def test_aph_theta_positive_while_unconverged():
    aph = make_aph(PHIterLimit=3, convthresh=0.0)
    aph.APH_main(finalize=False)
    # phi >= 0 always (phi = E[rho||x-z||^2] for dispatched-all case)
    assert float(aph.aph_state.phi) >= -1e-9


def test_aph_dispatch_frac():
    import math
    aph = make_aph(dispatch_frac=0.34, PHIterLimit=8, convthresh=0.0)
    aph.APH_main(finalize=False)
    # S is the PADDED scenario count (device-multiple); the dispatch
    # fraction applies to it
    S = aph.batch.num_scens
    assert aph.n_dispatch == max(1, math.ceil(0.34 * S))
    assert aph.n_dispatch < S   # genuinely partial
    # least-recently-dispatched rotation must touch every scenario
    ld = np.asarray(aph.aph_state.last_dispatch)
    assert (ld > 0).all()
    assert len(set(ld.tolist())) > 1


def test_aph_w_zero_mean():
    aph = make_aph(PHIterLimit=5, convthresh=0.0)
    aph.APH_main(finalize=False)
    W = np.asarray(aph.aph_state.W)
    p = np.asarray(aph.batch.prob)[:, None]
    # E[W] = 0 per node is the dual-feasibility invariant PH/APH share
    assert np.abs((p * W).sum(axis=0)).max() < 1e-6

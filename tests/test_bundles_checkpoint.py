"""Bundling + serialization + W/xbar checkpoint tests (reference
analog: test_ef_ph.py bundle cases, test_pickle_bundle.py,
test_w_writer.py)."""

import os

import numpy as np
import pytest

from efcheck import ef_linprog
from mpisppy_tpu.extensions.wxbarreader import WXBarReader
from mpisppy_tpu.extensions.wxbarwriter import WXBarWriter
from mpisppy_tpu.models import farmer
from mpisppy_tpu.opt.ph import PH
from mpisppy_tpu.utils.bundles import bundle_batch
from mpisppy_tpu.utils.pickle_bundle import dill_pickle, dill_unpickle
from mpisppy_tpu.utils.wxbarutils import read_W_csv, write_W_csv

OPTS = {"defaultPHrho": 1.0, "PHIterLimit": 60, "convthresh": 1e-5,
        "pdhg_eps": 1e-7}


def test_bundled_ef_matches_unbundled():
    b = farmer.build_batch(6)
    bb = bundle_batch(b, 2)
    assert bb.num_scens == 3
    ref, _ = ef_linprog(b, n_real=6)
    got, _ = ef_linprog(bb, n_real=3)
    assert got == pytest.approx(ref, rel=1e-8)


def test_bundled_ph_converges_to_same_objective():
    b = farmer.build_batch(6)
    bb = bundle_batch(b, 3)
    ph = PH(OPTS, [f"b{i}" for i in range(2)], batch=bb)
    conv, eobj, triv = ph.ph_main()
    ref, _ = ef_linprog(b, n_real=6)
    assert eobj == pytest.approx(ref, abs=0.01 * abs(ref))


def test_bundle_probability_weighting():
    # NON-UNIFORM scenario probabilities: the within-bundle conditional
    # weighting (w = p_s / p_B) must reproduce the exact EF value
    import dataclasses

    from mpisppy_tpu.ir import TreeInfo
    b = farmer.build_batch(4)
    p = np.array([0.4, 0.1, 0.3, 0.2])
    tree = dataclasses.replace(b.tree, prob=p)
    b = dataclasses.replace(b, tree=tree)
    bb = bundle_batch(b, 2)
    pb = np.asarray(bb.prob)
    assert pb == pytest.approx([0.5, 0.5])
    ref, _ = ef_linprog(b, n_real=4)
    got, _ = ef_linprog(bb, n_real=2)
    assert got == pytest.approx(ref, rel=1e-8)


def test_pickle_roundtrip(tmp_path):
    b = farmer.build_batch(3)
    path = os.path.join(tmp_path, "farmer3.npz")
    dill_pickle(b, path)
    b2 = dill_unpickle(path)
    assert b2.num_scens == 3
    assert np.allclose(np.asarray(b.A), np.asarray(b2.A))
    assert b2.tree.nonant_names == b.tree.nonant_names
    ref, _ = ef_linprog(b, n_real=3)
    got, _ = ef_linprog(b2, n_real=3)
    assert got == pytest.approx(ref)


def test_wxbar_checkpoint_roundtrip(tmp_path):
    path = os.path.join(tmp_path, "wchk.npz")
    opts = dict(OPTS, PHIterLimit=20, W_fname=path)
    ph = PH(opts, [f"scen{i}" for i in range(3)],
            batch=farmer.build_batch(3), extensions=WXBarWriter)
    ph.ph_main()
    assert os.path.exists(path)
    W_end = np.asarray(ph.state.W)

    # warm-started run must pick up where the first left off: its W
    # right after the reader installs matches the checkpoint
    opts2 = dict(OPTS, PHIterLimit=1, init_W_fname=path)
    ph2 = PH(opts2, [f"scen{i}" for i in range(3)],
             batch=farmer.build_batch(3), extensions=WXBarReader)
    ph2.Iter0()
    assert np.allclose(np.asarray(ph2.state.W), W_end)


def test_w_csv_roundtrip(tmp_path):
    path = os.path.join(tmp_path, "w.csv")
    ph = PH(dict(OPTS, PHIterLimit=3), [f"scen{i}" for i in range(3)],
            batch=farmer.build_batch(3))
    ph.ph_main()
    write_W_csv(path, ph)
    W = read_W_csv(path, ph)
    assert np.allclose(W[:3], np.asarray(ph.state.W)[:3])


def test_bundle_shared_A_stays_shared():
    """Bundling a shared-A batch keeps ONE block-diagonal matrix
    (members share A, chain rows are constant), and the bundled system
    matches the densely-bundled one exactly."""
    from mpisppy_tpu.models import uc

    b_shared = uc.build_batch(8, H=4)
    assert b_shared.shared_A
    bb_s = bundle_batch(b_shared, 4)
    assert bb_s.A.shape[0] == 1 and bb_s.num_scens == 2
    assert bb_s.shared_A

    b_dense = uc.build_batch(8, H=4, shared_A=False)
    bb_d = bundle_batch(b_dense, 4)
    assert bb_d.A.shape[0] == 2
    A_s = np.asarray(bb_s.A)[0]
    for bidx in range(2):
        assert np.array_equal(A_s, np.asarray(bb_d.A)[bidx])
    for f in ("row_lo", "row_hi", "c", "qdiag", "lb", "ub",
              "obj_const"):
        assert np.allclose(np.asarray(getattr(bb_s, f)),
                           np.asarray(getattr(bb_d, f))), f

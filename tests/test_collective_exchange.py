"""Collective exchange fabric tests (mpmd/collective.py): the fused
all-gather/broadcast slabs behind the "collective" window backend.

Covers the Window-contract parity of CollectiveWindow (ids, checksums,
kill, chaos corruption, stale accounting), the lazy flush-on-read
commit discipline (N writes coalesce into ONE fused exchange), the
single-compile-per-geometry guarantee, bit-identical bound-trajectory
parity with the seqlock and device-mailbox backends, corrupt-window
accounting parity, and the reslice paths: fabric-level slab regrow and
the clean fallback onto device mailboxes when the regrow breaks.

Everything runs on the 8 virtual CPU devices conftest.py forces, so
the lane-sharded placements and the shard_map all-gather are real
multi-device programs, just over host memory.
"""

import ast
import os

import numpy as np
import pytest

import jax

from mpisppy_tpu import telemetry
from mpisppy_tpu.mpmd import MPMDWheel
from mpisppy_tpu.mpmd.collective import (
    HEADER_LANES, CollectiveFabric, CollectiveWindow,
    collective_window_pair)
from mpisppy_tpu.mpmd.exchange import DeviceWindow
from mpisppy_tpu.mpmd.slice_plan import slab_width
from mpisppy_tpu.spin_the_wheel import WheelSpinner

from test_mpmd_wheel import (S, RecordingHub, farmer_dicts,
                             fresh_telemetry)  # noqa: F401

pytestmark = pytest.mark.mpmd

PKG_ROOT = os.path.join(os.path.dirname(__file__), "..", "mpisppy_tpu")


def two_lane_fabric(hub_len=5, spoke_len=4, n_devices=2, **kw):
    """A sealed-geometry-ready fabric with 2 pairs on the first
    `n_devices` fleet devices: the smallest interesting lane mesh."""
    fab = CollectiveFabric(devices=jax.devices()[:n_devices], **kw)
    pairs = [fab.add_pair(hub_len, spoke_len, tag=f"p{j}")
             for j in range(2)]
    return fab, pairs


class TestSlabWidth:
    def test_rounds_to_multiple(self):
        assert slab_width([3, 7, 5]) == 7
        assert slab_width([3, 7, 5], multiple=6) == 12
        assert slab_width([], multiple=4) == 4   # degenerate: 1 lane min
        assert slab_width([1]) == 1


class TestCollectiveWindowContract:
    """CollectiveWindow must be indistinguishable from Window /
    DeviceWindow above the WindowPair seam."""

    def test_roundtrip_ids_and_prewrite_zeros(self, fresh_telemetry):
        fab, [(to_spoke, to_hub), _] = two_lane_fabric()
        # pre-first-write: zeros under id 0, and read_checked validates
        # (the header is initialized to the zero payload's checksum)
        data, wid = to_spoke.read()
        assert wid == 0 and np.array_equal(data, np.zeros(5))
        data, wid, ok, reason = to_hub.read_checked()
        assert wid == 0 and ok and reason is None
        assert to_spoke.write(np.arange(5.0)) == 1
        data, wid = to_spoke.read()
        assert wid == 1 and np.array_equal(data, np.arange(5.0))
        assert data.dtype == np.float64
        # explicit id (the regrow protocol re-posts under a chosen id)
        assert to_spoke.write(np.ones(5), write_id=7) == 7
        assert to_spoke.write_id == 7
        # lanes are independent mailboxes of the shared slab
        data, wid = to_hub.read()
        assert wid == 0 and np.array_equal(data, np.zeros(4))

    def test_shape_mismatch(self, fresh_telemetry):
        _, [(to_spoke, _), _] = two_lane_fabric()
        with pytest.raises(ValueError, match="expects shape"):
            to_spoke.write(np.zeros(3))

    def test_kill_flushes_staged_payload(self, fresh_telemetry):
        """The seqlock contract: kill overwrites only the id — AND the
        staged generation still commits, so the reader's final pass
        sees the writer's final payload (the overlap-mode finalize
        regression: spokes must see the hub's last W's, not the last
        ones somebody happened to read before the kill)."""
        _, [(to_spoke, _), _] = two_lane_fabric()
        to_spoke.write(np.arange(5.0))
        _ = to_spoke.read()                       # commit gen 1
        to_spoke.write(np.arange(5.0) * 3)        # staged, never read
        to_spoke.send_kill()
        data, wid = to_spoke.read()
        assert wid == to_spoke.KILL
        np.testing.assert_array_equal(data, np.arange(5.0) * 3)
        # read_checked treats KILL like Window: ok, id exempt
        data, wid, ok, _ = to_spoke.read_checked()
        assert wid == to_spoke.KILL and ok

    def test_corrupt_write_detected_and_counted(self, fresh_telemetry):
        """Chaos corrupt_window parity: the perturbed payload ships
        under the TRUE checksum and only read_checked catches it."""
        fab, [(to_spoke, _), _] = two_lane_fabric()
        to_spoke.corrupt_next_write()
        to_spoke.write(np.arange(5.0))
        data, wid = to_spoke.read()               # plain read: fooled
        assert data[0] == 1.0 and wid == 1
        to_spoke.write(np.arange(5.0))
        to_spoke.corrupt_next_write()
        to_spoke.write(np.arange(5.0))
        data, wid, ok, reason = to_spoke.read_checked()
        assert not ok and "checksum mismatch" in reason
        c = telemetry.wheel_counters()
        assert c["wheel_stale_reads"] >= 1        # corrupt counts stale

    def test_stale_read_accounting(self, fresh_telemetry):
        fab, [(to_spoke, _), _] = two_lane_fabric()
        to_spoke.write(np.ones(5))
        to_spoke.read()
        to_spoke.read()                           # same id again: stale
        c = telemetry.wheel_counters()
        assert c["wheel_stale_reads"] == 1
        assert c["wheel_exchange_writes"] == 1

    def test_read_device_is_lane_slice(self, fresh_telemetry):
        _, [(to_spoke, _), (to_spoke2, _)] = two_lane_fabric()
        to_spoke.write(np.arange(5.0))
        to_spoke2.write(np.arange(5.0) + 10)
        dev, wid = to_spoke.read_device()
        assert isinstance(dev, jax.Array) and wid == 1
        np.testing.assert_array_equal(np.asarray(dev), np.arange(5.0))
        dev2, _ = to_spoke2.read_device()
        np.testing.assert_array_equal(np.asarray(dev2),
                                      np.arange(5.0) + 10)

    def test_more_lanes_than_devices_wrap(self, fresh_telemetry):
        """K lanes on fewer devices: the row count pads to a device
        multiple at exchange time and every lane still round-trips."""
        fab = CollectiveFabric(devices=jax.devices()[:2])
        pairs = [fab.add_pair(3, 3) for _ in range(3)]
        for j, (to_spoke, _) in enumerate(pairs):
            to_spoke.write(np.full(3, float(j)))
        for j, (to_spoke, _) in enumerate(pairs):
            data, wid = to_spoke.read()
            assert wid == 1
            np.testing.assert_array_equal(data, np.full(3, float(j)))

    def test_single_device_fabric(self, fresh_telemetry):
        fab = CollectiveFabric(devices=jax.devices()[:1])
        to_spoke, to_hub = fab.add_pair(2, 2)
        to_hub.write(np.array([1.0, 2.0]))
        data, wid = to_hub.read()
        assert wid == 1 and np.array_equal(data, [1.0, 2.0])


class TestFabricCommitDiscipline:
    def test_writes_coalesce_into_one_exchange(self, fresh_telemetry):
        """N staged writes across all lanes of a direction commit with
        ONE fused exchange at the first read — the whole point of the
        backend — and the byte counter reports slab bytes, not
        per-write bytes."""
        fab, pairs = two_lane_fabric(hub_len=5, spoke_len=4)
        for k in range(5):
            for to_spoke, to_hub in pairs:
                to_hub.write(np.full(4, float(k)))
        data, wid = pairs[0][1].read()            # triggers the flush
        assert wid == 5
        np.testing.assert_array_equal(data, np.full(4, 4.0))
        _ = pairs[1][1].read()                    # same generation: free
        c = telemetry.wheel_counters()
        assert c["wheel_collective_exchanges"] == 1
        assert c["wheel_exchange_writes"] == 10
        # 2 lanes x (3 header + v_pad) float64 — nothing per-write
        width = HEADER_LANES + slab_width([4, 4])
        assert c["wheel_exchange_bytes"] == 2 * width * 8
        assert c["wheel_exchange_latency_seconds"] > 0.0
        # a read with nothing newly staged exchanges nothing
        _ = pairs[0][1].read()
        assert telemetry.wheel_counters()[
            "wheel_collective_exchanges"] == 1

    def test_directions_commit_independently(self, fresh_telemetry):
        fab, [(to_spoke, to_hub), _] = two_lane_fabric()
        to_spoke.write(np.ones(5))
        to_hub.write(np.ones(4))
        to_spoke.read()
        assert telemetry.wheel_counters()[
            "wheel_collective_exchanges"] == 1    # down slab only
        to_hub.read()
        assert telemetry.wheel_counters()[
            "wheel_collective_exchanges"] == 2

    def test_sealed_after_first_write(self, fresh_telemetry):
        fab, pairs = two_lane_fabric()
        pairs[0][0].write(np.zeros(5))
        with pytest.raises(RuntimeError, match="sealed"):
            fab.add_pair(5, 4)

    def test_pair_factory_requires_fabric(self):
        with pytest.raises(RuntimeError, match="shared CollectiveFabric"):
            collective_window_pair(4, 4)

    def test_staged_payload_no_device_work(self, fresh_telemetry):
        fab, [(to_spoke, _), _] = two_lane_fabric()
        to_spoke.write(np.arange(5.0))
        data, wid = fab.staged_payload(to_spoke)
        assert wid == 1
        np.testing.assert_array_equal(data, np.arange(5.0))
        assert telemetry.wheel_counters()[
            "wheel_collective_exchanges"] == 0    # nothing exchanged

    def test_describe_json_safe(self, fresh_telemetry):
        import json
        fab, pairs = two_lane_fabric()
        pairs[0][1].write(np.ones(4))
        pairs[0][1].read()
        d = json.loads(json.dumps(fab.describe()))
        assert d["backend"] == "collective" and d["lanes"] == 2
        assert d["slab_bytes"]["to_hub"] > 0


class TestSingleCompile:
    def test_one_trace_per_geometry(self, fresh_telemetry):
        """The fused gather traces ONCE for a slab geometry no matter
        how many supersteps run — steady state never recompiles."""
        fab, pairs = two_lane_fabric()
        for k in range(8):
            for to_spoke, to_hub in pairs:
                to_hub.write(np.full(4, float(k)))
                to_spoke.write(np.full(5, float(k)))
            for to_spoke, to_hub in pairs:
                to_hub.read()
                to_spoke.read()
        assert fab._up.traces == 1                # one gather compile
        assert fab.trace_count == 2               # + the bcast placement
        assert telemetry.wheel_counters()[
            "wheel_collective_exchanges"] == 16


class TestRegrowAndFallback:
    def test_regrow_carries_payload_under_old_wid(self, fresh_telemetry):
        """Fabric-level reslice support: the hub->spoke slab regrows to
        the post-reslice width, every lane's last payload re-staged —
        truncated/zero-extended, CRC recomputed — under its OLD
        write_id, and the next read commits the new geometry with one
        exchange that still validates."""
        fab, pairs = two_lane_fabric(hub_len=6)
        pairs[0][0].write(np.arange(6.0), write_id=9)
        pairs[1][0].write(np.arange(6.0) * 2, write_id=4)
        pairs[0][0].read()
        fab.regrow_to_spoke(8)
        for (to_spoke, _), wid_want, base in ((pairs[0], 9, 1.0),
                                              (pairs[1], 4, 2.0)):
            assert to_spoke.length == 8
            data, wid, ok, reason = to_spoke.read_checked()
            assert wid == wid_want and ok, reason
            np.testing.assert_array_equal(
                data, np.r_[np.arange(6.0) * base, 0.0, 0.0])
        # shrink truncates
        fab.regrow_to_spoke(3)
        data, wid, ok, _ = pairs[1][0].read_checked()
        assert wid == 4 and ok
        np.testing.assert_array_equal(data, np.arange(3.0) * 2)

    def test_regrow_retraces_but_only_once(self, fresh_telemetry):
        fab, pairs = two_lane_fabric(hub_len=6)
        pairs[0][0].write(np.ones(6))
        pairs[0][0].read()
        before = fab.trace_count
        fab.regrow_to_spoke(9)
        pairs[0][0].read()
        pairs[1][0].read()
        pairs[0][0].write(np.ones(9))
        pairs[0][0].read()
        # bcast direction: geometry change costs no jit retrace (it is
        # a replicated placement), trace_count stays flat
        assert fab.trace_count == before

    @pytest.mark.chaos
    def test_device_loss_reslice_regrows_collective_slab(
            self, fresh_telemetry):
        """End-to-end regression for the regrow path: a chaos device
        loss prunes the Lagrangian slice, the reslice barrier grows the
        hub (pad 6 -> 7) and the surviving pair's hub->spoke lane is
        resized in place — still a CollectiveWindow — under its old
        write_id, and the wheel finishes with finite bounds."""
        hub_dict, spoke_dicts = farmer_dicts(
            spoke_chaos={"device_loss": 1})
        ws = MPMDWheel(hub_dict, spoke_dicts, lockstep=True)
        ws.spin()
        assert ws.exchange_backend_used == "collective"
        assert len(ws.supervisor.reslice_log) == 1
        new_S = ws.spcomm.opt.batch.num_scens
        assert new_S == 7
        K = ws.spcomm.opt.batch.num_nonants
        surviving = ws.supervisor.spokes[1].pair
        assert isinstance(surviving.to_spoke, CollectiveWindow)
        assert surviving.to_spoke.length == new_S * K
        assert np.isfinite(ws.BestInnerBound)
        assert np.isfinite(ws.BestOuterBound)

    @pytest.mark.chaos
    def test_regrow_failure_falls_back_to_device_mailboxes(
            self, fresh_telemetry, monkeypatch):
        """When the fabric-level regrow breaks, the surviving pairs
        swap cleanly onto DeviceWindow mailboxes (payloads re-posted
        under their old ids straight from the staging slab) and the
        wheel finishes on the per-pair backend."""
        monkeypatch.setattr(
            CollectiveFabric, "regrow_to_spoke",
            lambda self, n: (_ for _ in ()).throw(
                RuntimeError("injected regrow failure")))
        hub_dict, spoke_dicts = farmer_dicts(
            spoke_chaos={"device_loss": 1})
        ws = MPMDWheel(hub_dict, spoke_dicts, lockstep=True)
        ws.spin()
        assert ws.exchange_backend_used == "collective"
        assert len(ws.supervisor.reslice_log) == 1
        surviving = ws.supervisor.spokes[1].pair
        assert isinstance(surviving.to_spoke, DeviceWindow)
        assert isinstance(surviving.to_hub, DeviceWindow)
        new_S = ws.spcomm.opt.batch.num_scens
        K = ws.spcomm.opt.batch.num_nonants
        assert surviving.to_spoke.length == new_S * K
        assert np.isfinite(ws.BestInnerBound)
        assert np.isfinite(ws.BestOuterBound)
        reg = fresh_telemetry.registry
        assert reg._counters["wheel.collective_fallbacks"].value == 1


class TestExchangeParityCollective:
    def test_collective_vs_device_bound_trajectory(self):
        """The fused fabric is pure transport: the interleaved wheel's
        per-iteration bound trajectory on farmer must be BIT-IDENTICAL
        through device mailboxes and the collective slabs (same float64
        vectors, same deterministic inline schedule)."""
        traces = {}
        for backend in ("device", "collective"):
            hub_dict, spoke_dicts = farmer_dicts(hub_class=RecordingHub)
            ws = WheelSpinner(hub_dict, spoke_dicts, mode="interleaved",
                              exchange_backend=backend)
            ws.spin()
            assert ws.exchange_backend_used == backend
            traces[backend] = np.array(ws.spcomm.bound_trace)
        a, b = traces["device"], traces["collective"]
        assert a.shape == b.shape and len(a) > 0
        assert np.array_equal(a, b)
        assert np.isfinite(a[-1]).all()

    def test_mpmd_lockstep_backend_parity(self, fresh_telemetry):
        """Acceptance check at the MPMDWheel level: the disjoint-slice
        lockstep wheel produces bit-identical trajectories AND
        identical stale-read/write accounting on both on-device
        backends (the schedule is deterministic, so the accounting is
        too)."""
        runs = {}
        for backend in ("device", "collective"):
            telemetry.reset()
            telemetry.configure(True)
            hub_dict, spoke_dicts = farmer_dicts(
                hub_class=RecordingHub,
                opt_overrides={"telemetry": True},
                hub_opts={"window_backend": backend})
            ws = MPMDWheel(hub_dict, spoke_dicts, lockstep=True)
            ws.spin()
            assert ws.exchange_backend_used == backend
            runs[backend] = (np.array(ws.spcomm.bound_trace),
                             telemetry.wheel_counters())
        telemetry.reset()
        (ta, ca), (tb, cb) = runs["device"], runs["collective"]
        assert ta.shape == tb.shape and len(ta) > 0
        assert np.array_equal(ta, tb)
        assert ca["wheel_stale_reads"] == cb["wheel_stale_reads"]
        assert ca["wheel_exchange_writes"] == cb["wheel_exchange_writes"]
        # only the fused backend runs collectives
        assert ca["wheel_collective_exchanges"] == 0
        assert cb["wheel_collective_exchanges"] > 0

    @pytest.mark.chaos
    def test_corrupt_window_accounting_parity(self):
        """corrupt_window chaos through the slab header lane: the
        collective backend detects, counts and prunes EXACTLY like the
        device mailboxes — the integrity contract survives the fused
        transport bit-for-bit."""
        runs = {}
        for backend in ("device", "collective"):
            telemetry.reset()
            telemetry.configure(True)
            hub_dict, spoke_dicts = farmer_dicts(
                spoke_chaos={"corrupt_window": 1},
                opt_overrides={"PHIterLimit": 12, "telemetry": True},
                hub_opts={"max_corrupt_reads": 3,
                          "window_backend": backend})
            ws = MPMDWheel(hub_dict, spoke_dicts, lockstep=True)
            ws.spin()
            hub = ws.spcomm
            runs[backend] = (np.asarray(hub.corrupt_reads).copy(),
                             list(hub.failed_spokes),
                             telemetry.wheel_counters())
        telemetry.reset()
        (ra, fa, ca), (rb, fb, cb) = runs["device"], runs["collective"]
        np.testing.assert_array_equal(ra, rb)
        assert [n for n, _ in fa] == [n for n, _ in fb] \
            == ["LagrangianOuterBound"]
        assert "corrupt window reads" in fb[0][1]
        assert ca["wheel_corrupt_reads"] == cb["wheel_corrupt_reads"] >= 3
        assert ca["wheel_reslice_events"] == cb["wheel_reslice_events"]


class TestLayering:
    def test_cylinders_never_import_collective(self):
        """The satellite's sharper form of the mpmd layering guard:
        cylinders/ must not name mpmd.collective anywhere, even inside
        function bodies."""
        cyl_dir = os.path.join(PKG_ROOT, "cylinders")
        for fn in sorted(os.listdir(cyl_dir)):
            if not fn.endswith(".py"):
                continue
            tree = ast.parse(open(os.path.join(cyl_dir, fn)).read())
            for node in ast.walk(tree):
                mods = []
                if isinstance(node, ast.Import):
                    mods = [a.name for a in node.names]
                elif isinstance(node, ast.ImportFrom):
                    mods = [node.module or ""]
                for m in mods:
                    assert "collective" not in m.split("."), \
                        f"cylinders/{fn} imports mpmd.collective"

    def test_counters_stable_when_disabled(self):
        telemetry.reset()
        c = telemetry.wheel_counters()
        assert c["wheel_collective_exchanges"] == 0
        assert c["wheel_exchange_bytes"] == 0

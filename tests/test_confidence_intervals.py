"""Confidence-interval layer tests (reference analog:
mpisppy/tests/test_conf_int_farmer.py + test_conf_int_aircond.py)."""

import numpy as np
import pytest

from mpisppy_tpu.confidence_intervals import ciutils
from mpisppy_tpu.confidence_intervals.mmw_ci import MMWConfidenceIntervals
from mpisppy_tpu.confidence_intervals.multi_seqsampling import (
    IndepScens_SeqSampling,
)
from mpisppy_tpu.confidence_intervals.sample_tree import SampleSubtree
from mpisppy_tpu.confidence_intervals.seqsampling import SeqSampling
from mpisppy_tpu.confidence_intervals.zhat4xhat import zhat4xhat
from mpisppy_tpu.models import aircond, farmer

XHAT_STAR = np.array([170.0, 80.0, 250.0])   # farmer optimum
OPTS = {"solver_eps": 1e-7}


def test_sample_batch_seeds_differ():
    b1 = ciutils.sample_batch(farmer, 5, seed=100)
    b2 = ciutils.sample_batch(farmer, 5, seed=200)
    # different seeds -> different yields (scenarios >= 3 perturb)
    assert not np.allclose(np.asarray(b1.A), np.asarray(b2.A))


def test_gap_estimator_at_optimum_small():
    est = ciutils.gap_estimators(XHAT_STAR, farmer, num_scens=20,
                                 seed=500, cfg=OPTS)
    # the true optimum's gap on a sample is small relative to |z| and
    # nonnegative up to solver tolerance
    assert est["G"] >= -1.0
    assert est["G"] < 0.02 * abs(est["zstar"])
    assert est["std"] >= 0.0
    assert est["seed"] == 520


def test_gap_estimator_bad_candidate_positive():
    bad = np.array([500.0, 0.0, 0.0])
    est = ciutils.gap_estimators(bad, farmer, num_scens=15, seed=700,
                                 cfg=OPTS)
    good = ciutils.gap_estimators(XHAT_STAR, farmer, num_scens=15,
                                  seed=700, cfg=OPTS)
    assert est["G"] > good["G"] + 100.0   # clearly worse candidate


def test_mmw_interval():
    mmw = MMWConfidenceIntervals(farmer, dict(OPTS), XHAT_STAR,
                                 num_batches=3, batch_size=10,
                                 start=1000, mname_is_module=True)
    r = mmw.run(confidence_level=0.95)
    assert r["gap_inner_bound"] >= 0.0
    # at the optimum the gap CI must be tight relative to |z| ~ 1e5
    assert r["gap_inner_bound"] < 0.05 * abs(r["zstar_bar"])
    assert len(r["Glist"]) == 3


def test_seqsampling_bm_farmer():
    ss = SeqSampling(farmer, {"BM_h": 2.0, "BM_eps": 500.0,
                              "n0min": 10, "max_seq_iters": 5,
                              **OPTS}, seed=42,
                     stopping_criterion="BM")
    r = ss.run()
    assert "xhat_one" in r
    assert r["xhat_one"].shape == (3,)
    # the sampled-EF candidate should be close to the true optimum
    assert abs(r["xhat_one"][2] - 250.0) < 60.0


def test_seqsampling_bpl_farmer():
    ss = SeqSampling(farmer, {"BPL_eps": 2000.0, "n0min": 10,
                              "max_seq_iters": 4, **OPTS},
                     seed=99, stopping_criterion="BPL")
    r = ss.run()
    assert r["num_scens"] >= 10


def test_xhat_io_roundtrip(tmp_path):
    import os
    p = os.path.join(tmp_path, "xhat.npy")
    ciutils.write_xhat(XHAT_STAR, p)
    assert np.allclose(ciutils.read_xhat(p), XHAT_STAR)
    pt = os.path.join(tmp_path, "xhat.txt")
    ciutils.writetxt_xhat(XHAT_STAR, pt)
    assert np.allclose(ciutils.readtxt_xhat(pt), XHAT_STAR)


def test_zhat4xhat_farmer():
    zbar, s, (lo, hi) = zhat4xhat(farmer, XHAT_STAR, num_samples=4,
                                  sample_size=8, seed=300,
                                  options=OPTS)
    assert lo <= zbar <= hi
    # z(xhat*) on perturbed-yield samples stays in the right region
    assert -130000 < zbar < -90000


def test_sample_subtree_aircond():
    b = aircond.build_batch(branching_factors=(2, 2))
    stage_of = np.asarray(b.tree.stage_of)
    # candidate: stage-1 decisions from the EF of the nominal tree
    from mpisppy_tpu.opt.ef import ExtensiveForm
    ef = ExtensiveForm({"pdhg_eps": 1e-7},
                       list(b.tree.scen_names), batch=b)
    ef.solve_extensive_form()
    xhat = np.asarray(ef.get_root_solution())
    st = SampleSubtree(aircond, xhat, starting_stage=1,
                       branching_factors=[2, 2], seed=17, options={})
    eobj, feas = st.run()
    assert feas
    assert eobj > 0


def test_indepscens_seqsampling_aircond():
    ss = IndepScens_SeqSampling(
        aircond,
        {"branching_factors": [2, 2], "BM_h": 3.0, "BM_eps": 100.0,
         "n0min": 4, "max_seq_iters": 3, "num_eval_samples": 2,
         **OPTS},
        seed=5, stopping_criterion="BM")
    r = ss.run()
    assert "xhat_one" in r and r["xhat_one"] is not None
    assert np.isfinite(r["G"])

"""Config / vanilla / amalgamator layer tests (reference analog:
config + cfg_vanilla + amalgamator usage in examples and
test_ef_ph.py)."""

import numpy as np
import pytest

from mpisppy_tpu.models import farmer, hydro
from mpisppy_tpu.utils import amalgamator, config, vanilla


def fresh_cfg():
    cfg = config.Config()
    cfg.popular_args()
    cfg.ph_args()
    cfg.two_sided_args()
    return cfg


def test_config_declare_and_parse():
    cfg = fresh_cfg()
    cfg.add_to_config("my_flag", "test flag", int, 7)
    cfg.parse_command_line("t", args=["--my-flag", "9",
                                      "--max-iterations", "12"])
    assert cfg.my_flag == 9
    assert cfg.max_iterations == 12
    assert cfg["default_rho"] == 1.0


def test_config_bool_flags():
    cfg = config.Config()
    cfg.add_to_config("switch", "bool flag", bool, False)
    cfg.parse_command_line("t", args=["--switch"])
    assert cfg.switch is True


def test_config_redeclare_no_clobber():
    cfg = fresh_cfg()
    cfg["max_iterations"] = 55
    cfg.popular_args()     # re-declare group must not clobber values
    assert cfg.max_iterations == 55


def test_options_dict_mapping():
    cfg = fresh_cfg()
    cfg["max_iterations"] = 5
    cfg["default_rho"] = 2.5
    o = cfg.options_dict()
    assert o["PHIterLimit"] == 5
    assert o["defaultPHrho"] == 2.5


def test_vanilla_wheel_runs():
    cfg = fresh_cfg()
    cfg.xhatshuffle_args()
    cfg.lagrangian_args()
    cfg["max_iterations"] = 20
    cfg["rel_gap"] = 1e-3
    cfg["solver_eps"] = 1e-7
    names = farmer.scenario_names_creator(3)
    batch = farmer.build_batch(3)
    hub = vanilla.ph_hub(cfg, farmer.scenario_creator, None, names,
                         batch=batch)
    spokes = [
        vanilla.lagrangian_spoke(cfg, farmer.scenario_creator, None,
                                 names, batch=batch),
        vanilla.xhatshuffle_spoke(cfg, farmer.scenario_creator, None,
                                  names, batch=batch),
    ]
    from mpisppy_tpu.spin_the_wheel import WheelSpinner
    ws = WheelSpinner(hub, spokes).spin()
    assert abs(ws.BestInnerBound - -108390.0) < 100.0
    assert ws.BestOuterBound <= ws.BestInnerBound + 1e-4 * abs(
        ws.BestInnerBound)


def test_extension_adder_promotes_to_multi():
    from mpisppy_tpu.extensions import MultiExtension
    from mpisppy_tpu.extensions.fixer import Fixer
    from mpisppy_tpu.extensions.mipgapper import Gapper
    cfg = fresh_cfg()
    cfg.fixer_args()
    names = farmer.scenario_names_creator(3)
    hub = vanilla.ph_hub(cfg, farmer.scenario_creator, None, names,
                         batch=farmer.build_batch(3))
    vanilla.add_fixer(hub, cfg)
    assert hub["opt_kwargs"]["extensions"] is Fixer
    vanilla.extension_adder(hub, Gapper)
    assert hub["opt_kwargs"]["extensions"] is MultiExtension
    assert Gapper in hub["opt_kwargs"]["extension_kwargs"]["ext_classes"]


def test_amalgamator_ef_farmer():
    cfg = config.Config()
    cfg.popular_args()
    cfg.ph_args()
    cfg.quick_assign("EF", bool, True)
    cfg.quick_assign("EF_solver_eps", float, 1e-7)
    ama = amalgamator.from_module(
        "mpisppy_tpu.models.farmer", cfg, use_command_line=True,
        args=["--num-scens", "3"])
    ama.run()
    assert ama.EF_Obj == pytest.approx(-108390.0, abs=10.0)
    assert ama.first_stage_solution is not None


def test_amalgamator_wheel_farmer():
    cfg = config.Config()
    cfg.popular_args()
    cfg.ph_args()
    cfg.two_sided_args()
    cfg.xhatxbar_args()
    cfg.lagrangian_args()
    ama = amalgamator.from_module(
        "mpisppy_tpu.models.farmer", cfg, use_command_line=True,
        args=["--num-scens", "3", "--xhatxbar", "--lagrangian",
              "--max-iterations", "20", "--rel-gap", "1e-3",
              "--solver-eps", "1e-7"])
    ama.run()
    assert abs(ama.best_inner_bound - -108390.0) < 100.0


def test_amalgamator_multistage_hydro():
    cfg = config.Config()
    cfg.popular_args()
    cfg.ph_args()
    cfg.quick_assign("EF", bool, True)
    ama = amalgamator.from_module(
        "mpisppy_tpu.models.hydro", cfg, use_command_line=True,
        args=["--branching-factors", "3,3"])
    ama.run()
    # reference golden: hydro EF objective ~ 190 at 2 sig figs
    assert ama.EF_Obj == pytest.approx(190.0, rel=0.05)


def test_cli_driver_main():
    import sys
    sys.path.insert(0, "examples")
    import farmer_cylinders
    ws = farmer_cylinders.main(
        args=["--num-scens", "3", "--lagrangian", "--xhatxbar",
              "--max-iterations", "40", "--rel-gap", "1e-3",
              "--solver-eps", "1e-7"])
    assert abs(ws.BestInnerBound - -108390.0) < 100.0

"""Cross-scenario cuts tests (reference analog: cs_farmer /
netdes cross-scenario-cuts usage)."""

import numpy as np
import pytest

from efcheck import ef_linprog
from mpisppy_tpu.cylinders.cross_scen_spoke import CrossScenarioCutSpoke
from mpisppy_tpu.cylinders.hub import PHHub
from mpisppy_tpu.extensions.cross_scen_extension import (
    CrossScenarioExtension,
)
from mpisppy_tpu.models import farmer
from mpisppy_tpu.opt.ph import PH
from mpisppy_tpu.spin_the_wheel import WheelSpinner
from mpisppy_tpu.utils.cross_scenario import (
    add_cross_scenario_capacity, cross_meta,
)
from mpisppy_tpu.utils.xhat_eval import Xhat_Eval

OPTS = {"defaultPHrho": 1.0, "PHIterLimit": 30, "convthresh": 1e-5,
        "pdhg_eps": 1e-7}


def test_augment_and_meta():
    b = farmer.build_batch(3)
    ab = add_cross_scenario_capacity(b, max_cuts=5, eta_weight=0.1)
    assert ab.num_vars == b.num_vars + 1
    assert ab.num_rows == b.num_rows + 5
    m = cross_meta(ab)
    assert m["max_cuts"] == 5
    assert m["n_cuts"] == 0
    assert m["first_cut_row"] == b.num_rows


def test_blended_objective_consistent_at_consensus():
    # with w>0 and a TIGHT cut at the optimum, the blended EF value
    # equals the original EF value
    b = farmer.build_batch(3)
    ref, _ = ef_linprog(b, n_real=3)
    ab = add_cross_scenario_capacity(b, max_cuts=2, eta_weight=0.25)
    # install the exact cut eta >= E[f](x*) (gradient 0 at optimum in
    # the nonant directions is not exact, but a constant lower bound
    # eta >= ref is valid and tight at x*)
    import dataclasses

    import jax.numpy as jnp
    A = np.array(np.asarray(ab.A))
    lo = np.array(np.asarray(ab.row_lo))
    m = cross_meta(ab)
    r = m["first_cut_row"]
    A[:, r, ab.num_vars - 1] = 1.0
    lo[:, r] = ref
    ab = dataclasses.replace(ab, A=jnp.asarray(A), row_lo=jnp.asarray(lo))
    got, _ = ef_linprog(ab, n_real=3)
    assert got == pytest.approx(ref, rel=1e-6)


def test_cross_scenario_wheel():
    names = [f"scen{i}" for i in range(3)]
    base = farmer.build_batch(3)
    ab = add_cross_scenario_capacity(base, max_cuts=40, eta_weight=0.1)

    hub = {"hub_class": PHHub, "opt_class": PH,
           "hub_kwargs": {"options": {"rel_gap": 1e-4}},
           "opt_kwargs": {"options": dict(OPTS, PHIterLimit=60),
                          "all_scenario_names": names,
                          "batch": ab,
                          "extensions": CrossScenarioExtension}}
    spoke = {"spoke_class": CrossScenarioCutSpoke, "opt_class": Xhat_Eval,
             "opt_kwargs": {"options": dict(OPTS),
                            "all_scenario_names": names,
                            "batch": base}}
    ws = WheelSpinner(hub, [spoke]).spin()
    opt = ws.spcomm.opt
    # cuts must have been installed
    assert opt.extobject.n_cuts > 0
    # and PH still lands near the farmer optimum (the eta blend pulls
    # the iterate until the cut bank is tight at x*)
    xbar = np.asarray(opt.root_xbar())
    assert np.allclose(xbar, [170.0, 80.0, 250.0], atol=10.0)
    # the seeded constant cut repaired the trivial bound
    assert abs(opt.trivial_bound - -115405.55) < 5.0

"""Hub-and-spoke (cylinders) tests — the analog of the reference's
mpiexec smoke drivers (straight_tests.py) plus bound-quality checks.

Reference: farmer cylinders with PH hub + Lagrangian outer bound +
xhat shuffle inner bound should converge the inter-cylinder gap
(examples/farmer/farmer_cylinders.py).
"""

import numpy as np
import pytest

from mpisppy_tpu.models import farmer, hydro
from mpisppy_tpu.opt.ph import PH
from mpisppy_tpu.utils.xhat_eval import Xhat_Eval
from mpisppy_tpu.cylinders.hub import PHHub
from mpisppy_tpu.cylinders.lagrangian_bounder import LagrangianOuterBound
from mpisppy_tpu.cylinders.lagranger_bounder import LagrangerOuterBound
from mpisppy_tpu.cylinders.xhatshufflelooper_bounder import (
    ScenarioCycler, XhatShuffleInnerBound)
from mpisppy_tpu.cylinders.xhatxbar_bounder import XhatXbarInnerBound
from mpisppy_tpu.cylinders.slam_heuristic import SlamMaxHeuristic
from mpisppy_tpu.cylinders.spcommunicator import Window
from mpisppy_tpu.spin_the_wheel import WheelSpinner

OPTS = {"defaultPHrho": 1.0, "PHIterLimit": 40, "convthresh": 0.0,
        "pdhg_eps": 1e-7, "pdhg_max_iters": 20000}


def farmer_wheel(spoke_classes, mode="interleaved", S=3, hub_opts=None):
    names = [f"scen{i}" for i in range(S)]
    b = farmer.build_batch(S)
    hub_dict = {
        "hub_class": PHHub,
        "hub_kwargs": {"options": {"rel_gap": 1e-4, "abs_gap": 1.0,
                                   **(hub_opts or {})}},
        "opt_class": PH,
        "opt_kwargs": {"options": dict(OPTS), "all_scenario_names": names,
                       "batch": b},
    }
    spoke_dicts = []
    for cls, opt_cls in spoke_classes:
        spoke_dicts.append({
            "spoke_class": cls,
            "spoke_kwargs": {"options": {}},
            "opt_class": opt_cls,
            "opt_kwargs": {"options": dict(OPTS),
                           "all_scenario_names": names},
        })
    return WheelSpinner(hub_dict, spoke_dicts, mode=mode)


class TestWindow:
    def test_write_read_ids(self):
        w = Window(4)
        data, wid = w.read()
        assert wid == 0
        w.write([1, 2, 3, 4])
        data, wid = w.read()
        assert wid == 1 and data.tolist() == [1, 2, 3, 4]
        w.write([5, 6, 7, 8])
        assert w.read()[1] == 2
        w.send_kill()
        assert w.read()[1] == Window.KILL

    def test_shape_guard(self):
        w = Window(3)
        with pytest.raises(ValueError):
            w.write([1.0, 2.0])


class TestScenarioCycler:
    def test_epochs_reverse(self):
        c = ScenarioCycler([2, 0, 1], reverse=True)
        first = [c.get_next() for _ in range(3)]
        assert first == [2, 0, 1]
        nxt = [c.get_next() for _ in range(3)]
        assert nxt == [1, 0, 2]  # reversed epoch


class TestFarmerCylinders:
    def test_lagrangian_plus_xhat(self):
        """PH hub + Lagrangian outer + xhat-shuffle inner closes the
        gap on farmer-3 (true optimum -108390)."""
        ws = farmer_wheel([(LagrangianOuterBound, PH),
                           (XhatShuffleInnerBound, Xhat_Eval)])
        ws.spin()
        assert np.isfinite(ws.BestInnerBound)
        assert np.isfinite(ws.BestOuterBound)
        # bounds bracket the known optimum
        assert ws.BestOuterBound <= -108389.0
        assert ws.BestInnerBound >= -108391.0
        gap = (ws.BestInnerBound - ws.BestOuterBound) / abs(
            ws.BestOuterBound)
        assert gap < 5e-3
        sol = ws.best_nonant_solution()
        assert sol is not None

    def test_threaded_mode(self):
        ws = farmer_wheel([(LagrangianOuterBound, PH),
                           (XhatXbarInnerBound, Xhat_Eval)],
                          mode="threads")
        ws.spin()
        assert np.isfinite(ws.BestInnerBound)
        assert ws.BestInnerBound >= ws.BestOuterBound - 1.0

    def test_lagranger_and_slam(self):
        ws = farmer_wheel([(LagrangerOuterBound, PH),
                           (SlamMaxHeuristic, Xhat_Eval)])
        ws.spin()
        # slam-max on farmer: acreage slammed to max is feasible
        # (total acreage constraint may bind -> maybe infeasible;
        # inner bound may stay inf) — outer bound must hold
        assert np.isfinite(ws.BestOuterBound)
        assert ws.BestOuterBound <= -108389.0

    def test_solution_writers(self, tmp_path):
        ws = farmer_wheel([(XhatXbarInnerBound, Xhat_Eval)])
        ws.spin()
        f = tmp_path / "first_stage.csv"
        ws.write_first_stage_solution(str(f))
        lines = f.read_text().strip().splitlines()
        assert len(lines) == 3  # 3 crops
        ws.write_tree_solution(str(tmp_path / "tree"))
        assert (tmp_path / "tree" / "scen0.csv").exists()


class TestHydroCylinders:
    def test_multistage_wheel(self):
        names = [f"Scen{i+1}" for i in range(9)]
        b = hydro.build_batch()
        opts = {**OPTS, "PHIterLimit": 60, "pdhg_eps": 1e-8}
        hub_dict = {
            "hub_class": PHHub,
            "hub_kwargs": {"options": {"rel_gap": 5e-3}},
            "opt_class": PH,
            "opt_kwargs": {"options": opts, "all_scenario_names": names,
                           "batch": b},
        }
        spokes = [
            {"spoke_class": LagrangianOuterBound,
             "spoke_kwargs": {"options": {}},
             "opt_class": PH,
             "opt_kwargs": {"options": dict(opts),
                            "all_scenario_names": names}},
            {"spoke_class": XhatShuffleInnerBound,
             "spoke_kwargs": {"options": {}},
             "opt_class": Xhat_Eval,
             "opt_kwargs": {"options": dict(opts),
                            "all_scenario_names": names}},
        ]
        ws = WheelSpinner(hub_dict, spokes).spin()
        # true EF optimum ~186.17; bounds must bracket it
        assert ws.BestOuterBound <= 186.3
        assert ws.BestInnerBound >= 186.0
        gap = (ws.BestInnerBound - ws.BestOuterBound) / abs(
            ws.BestOuterBound)
        assert gap < 2e-2


class TestFailureTolerance:
    def test_spoke_crash_does_not_kill_wheel(self):
        """Graceful degradation (beyond the reference, where a lost
        MPI rank aborts the job): a spoke whose step() raises is
        removed from the wheel; the hub completes with its own valid
        bounds and records the failure."""

        class ExplodingSpoke(LagrangianOuterBound):
            def step(self):
                raise RuntimeError("synthetic spoke crash")

        ws = farmer_wheel([(ExplodingSpoke, PH),
                           (XhatShuffleInnerBound, Xhat_Eval)])
        ws.spin()
        hub = ws.spcomm
        assert len(hub.failed_spokes) == 1
        assert hub.failed_spokes[0][0] == "ExplodingSpoke"
        assert "synthetic spoke crash" in hub.failed_spokes[0][1]
        # the healthy inner-bound spoke and the hub's own bounds
        # still produce a usable answer
        assert np.isfinite(ws.BestInnerBound)
        assert np.isfinite(ws.BestOuterBound)
        assert ws.BestOuterBound <= ws.BestInnerBound + 1.0
        assert abs(ws.BestInnerBound - -108390.0) < 50.0

    def test_spoke_crash_threaded_mode(self):
        """Threaded mode: the crash is reported from the spoke thread
        and pruned on the hub thread."""

        class ExplodingSpoke(LagrangianOuterBound):
            def step(self):
                raise RuntimeError("synthetic thread crash")

        ws = farmer_wheel([(ExplodingSpoke, PH),
                           (XhatShuffleInnerBound, Xhat_Eval)],
                          mode="threads")
        ws.spin()
        hub = ws.spcomm
        assert len(hub.failed_spokes) == 1
        assert hub.failed_spokes[0][0] == "ExplodingSpoke"
        assert np.isfinite(ws.BestInnerBound)
        assert np.isfinite(ws.BestOuterBound)

    def test_threads_hung_spoke_bounded_shutdown(self):
        """A spoke stuck in a pathological solve (never checks the
        kill signal) must not block shutdown forever: the bounded join
        escalates it through the spoke-failure pruning path and the
        wheel terminates with the healthy spokes' results (the
        reference's kill protocol always terminates,
        spin_the_wheel.py:119-144)."""
        import time as _time

        class HungSpoke(LagrangianOuterBound):
            def main(self):
                t0 = _time.time()
                while _time.time() - t0 < 60.0:   # ignores the kill
                    _time.sleep(0.05)             # signal entirely

        ws = farmer_wheel([(HungSpoke, PH),
                           (XhatShuffleInnerBound, Xhat_Eval)],
                          mode="threads",
                          hub_opts={"shutdown_join_timeout": 5.0})
        t0 = _time.time()
        ws.spin()
        hub = ws.spcomm
        # shutdown took the bounded join, not the 60 s hang
        hung = [sp for sp in hub.spokes
                if getattr(sp, "_failed", False)]
        assert len(hung) == 1
        assert isinstance(hung[0], HungSpoke)
        assert any("did not exit" in msg for _, msg in hub.failed_spokes)
        assert np.isfinite(ws.BestInnerBound)
        assert abs(ws.BestInnerBound - -108390.0) < 50.0

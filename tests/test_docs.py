"""The documentation tree must stay buildable: every index link
resolves, every referenced repo path exists (doc/build.py validate),
and rendering produces HTML for each chapter."""

import subprocess
import sys
from pathlib import Path

ROOT = Path(__file__).parent.parent


def test_doc_build():
    r = subprocess.run([sys.executable, str(ROOT / "doc" / "build.py")],
                       capture_output=True, text=True)
    assert r.returncode == 0, r.stderr
    built = list((ROOT / "doc" / "build").glob("*.html"))
    src = list((ROOT / "doc" / "src").glob("*.md"))
    assert len(built) == len(src) >= 20

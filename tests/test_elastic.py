"""Elastic fault-domain tests (PR 10): dynamic re-slicing after a
slice dies, wheel-level ensemble checkpoint/resume, integrity-guarded
window reads, the new slice-granular chaos modes, the supervisor's
per-thread shutdown shares, and the resilience<-/->mpmd layering guard.

Every failure is injected deterministically through
mpisppy_tpu/resilience/chaos.py; the end-to-end reslice tests run the
wheel in LOCKSTEP mode so the kill -> prune -> reslice -> recover
sequence lands on exact iterations (no thread-scheduling slack).
"""

import ast
import os
import time
import types

import numpy as np
import pytest

import jax

from efcheck import ef_linprog
from mpisppy_tpu import telemetry
from mpisppy_tpu.cylinders.spcommunicator import Window
from mpisppy_tpu.models import farmer
from mpisppy_tpu.mpmd import CylinderSlice, MPMDWheel, SlicePlan
from mpisppy_tpu.mpmd.reslice import ReslicePlanner
from mpisppy_tpu.mpmd.wheel import SliceSupervisor
from mpisppy_tpu.resilience.bounds import PayloadGuard, payload_checksum
from mpisppy_tpu.resilience.chaos import (ChaosError, ChaosInjector,
                                          DeviceLossError)
from mpisppy_tpu.resilience.checkpoint import (
    is_wheel_checkpoint, load_drain_checkpoint, load_wheel_ensemble,
    save_drain_checkpoint, save_run_checkpoint)

from test_mpmd_wheel import (OPTS, S, RecordingHub, farmer_dicts,
                             fresh_telemetry)  # noqa: F401

pytestmark = pytest.mark.mpmd

PKG_ROOT = os.path.join(os.path.dirname(__file__), "..", "mpisppy_tpu")


# ---- integrity-guarded exchange (unit) -----------------------------------

class TestPayloadGuard:
    def test_checksum_mismatch_rejected(self):
        w = Window(3)
        w.write(np.arange(3.0))
        data, wid, ok, reason = w.read_checked()
        assert ok and wid == 1 and np.array_equal(data, np.arange(3.0))
        # chaos corrupt_window: perturbed payload under the TRUE
        # checksum — only payload validation can catch it
        w.corrupt_next_write()
        w.write(np.arange(3.0))
        data, wid, ok, reason = w.read_checked()
        assert not ok and "checksum mismatch" in reason
        assert wid == 2
        # an honest re-post clears the fault
        w.write(np.arange(3.0))
        assert w.read_checked()[2]

    def test_write_id_regression_rejected(self):
        g = PayloadGuard()
        v = np.ones(2)
        assert g.check(v, 5, payload_checksum(v))[0]
        ok, reason = g.check(v, 3, payload_checksum(v))
        assert not ok and "regressed" in reason
        assert g.corrupt == 1
        # same id again is NOT a regression (stale, but intact)
        assert g.check(v, 5, payload_checksum(v))[0]

    def test_kill_id_exempt(self):
        g = PayloadGuard()
        assert g.check(np.zeros(2), 9, payload_checksum(np.zeros(2)))[0]
        ok, _ = g.check(np.zeros(2), Window.KILL, None)
        assert ok                      # -1 carries no payload


# ---- slice-granular chaos modes (unit) -----------------------------------

class TestChaosModes:
    def test_device_loss_raises_and_is_chaos_error(self):
        c = ChaosInjector({"device_loss": 2})
        c.step_tick()
        with pytest.raises(DeviceLossError):
            c.step_tick()
        assert issubclass(DeviceLossError, ChaosError)

    def test_write_fate_corrupt_from_nth_write(self):
        c = ChaosInjector({"corrupt_window": 3})
        assert [c.write_fate() for _ in range(4)] == \
            ["ok", "ok", "corrupt", "corrupt"]

    def test_write_fate_partition_drops(self):
        c = ChaosInjector({"partition_slice": 2})
        assert [c.write_fate() for _ in range(3)] == \
            ["ok", "drop", "drop"]

    def test_block_build_fail_budget(self):
        c = ChaosInjector({"block_build_fail": 2})
        for _ in range(2):
            with pytest.raises(ChaosError, match="block build failure"):
                c.block_build_tick()
        c.block_build_tick()           # third build passes
        assert ChaosInjector().write_fate() == "ok"   # inert injector


# ---- ReslicePlanner (unit, jax-free device bookkeeping) ------------------

def _plan(spec):
    """spec: [(name, ndev), ...] with string stand-in devices."""
    slices, n = [], 0
    for i, (name, nd) in enumerate(spec):
        slices.append(CylinderSlice(
            name, i, tuple(f"d{n + j}" for j in range(nd))))
        n += nd
    return SlicePlan(slices)


class TestReslicePlanner:
    def test_hub_target_appends_reclaimed(self):
        plan = _plan([("hub", 6), ("lag", 1), ("xhat", 1)])
        dead = plan.spokes[0]
        new, reclaimed = ReslicePlanner().successor(plan, dead)
        assert reclaimed == ("d6",)
        assert new.n_slices == 2
        # hub keeps its first device (to_hub mailboxes live there) and
        # the dead slice's devices APPEND after the existing ones
        assert new.hub.devices[0] == "d0"
        assert new.hub.devices == tuple(f"d{i}" for i in range(7))
        assert new.spokes[0] is plan.spokes[1]   # survivor untouched
        assert new.pad_multiple() == 7           # lcm(7, 1)

    def test_starved_target_grows_smallest_spoke(self):
        plan = _plan([("hub", 4), ("big", 2), ("small", 1), ("dead", 1)])
        new, reclaimed = ReslicePlanner(target="starved").successor(
            plan, plan.spokes[2])
        assert reclaimed == ("d7",)
        grown = [s for s in new.spokes if s.name == "small"][0]
        assert grown.devices == ("d6", "d7")
        assert new.hub.n_devices == 4            # hub untouched

    def test_hub_cannot_die(self):
        plan = _plan([("hub", 2), ("lag", 1)])
        with pytest.raises(ValueError, match="cannot be resliced"):
            ReslicePlanner().successor(plan, plan.hub)

    def test_foreign_slice_rejected(self):
        plan = _plan([("hub", 2), ("lag", 1)])
        foreign = CylinderSlice("ghost", 9, ("z0",))
        with pytest.raises(ValueError, match="not part of this plan"):
            ReslicePlanner().successor(plan, foreign)

    def test_equality_fallback_for_roundtripped_slices(self):
        plan = _plan([("hub", 2), ("lag", 1)])
        # an equal-but-not-identical CylinderSlice (a plan rebuilt from
        # describe()) still matches
        twin = CylinderSlice("lag", 1, ("d2",))
        new, reclaimed = ReslicePlanner().successor(plan, twin)
        assert reclaimed == ("d2",) and new.n_slices == 1
        with pytest.raises(ValueError, match="'hub' or 'starved'"):
            ReslicePlanner(target="bogus")


# ---- end-to-end: kill -> reslice -> recover ------------------------------

@pytest.mark.chaos
class TestResliceEndToEnd:
    def test_device_loss_returns_devices_to_hub(self, fresh_telemetry):
        """A chaos device loss on the Lagrangian slice prunes the spoke
        (unrestartable), the next sync's reslice barrier returns its
        device to the hub (8-device fleet: hub 6 -> 7, pad 6 -> 7), and
        the wheel finishes with the same certified verdict as the
        failure-free run."""
        # failure-free reference on the identical lockstep schedule
        hub_dict, spoke_dicts = farmer_dicts(hub_class=RecordingHub)
        ref = MPMDWheel(hub_dict, spoke_dicts, lockstep=True)
        ref.spin()

        hub_dict, spoke_dicts = farmer_dicts(
            hub_class=RecordingHub,
            spoke_chaos={"device_loss": 1})
        ws = MPMDWheel(hub_dict, spoke_dicts, lockstep=True)
        ws.spin()
        sup = ws.supervisor
        hub = ws.spcomm

        # pruned through the standard path, resliced within 2 supersteps
        assert len(hub.failed_spokes) == 1
        assert hub.failed_spokes[0][0] == "LagrangianOuterBound"
        assert "injected device loss" in hub.failed_spokes[0][1]
        assert len(sup.reslice_log) == 1
        ev = sup.reslice_log[0]
        assert ev["name"] == "spoke0"
        assert ev["devices_reclaimed"] == 1
        assert ev["hub_devices"] == 7            # 6 + the dead slice's 1
        assert ev["padded_scens"] == 7           # lcm(7, 1) re-pad
        assert ev["iteration"] <= 3              # within 2 supersteps
        assert sup.devices_reclaimed == 1
        # the hub really reshards: batch + mesh now span 7 devices
        assert ws.spcomm.opt.batch.num_scens == 7
        assert ws.spcomm.opt.mesh.size == 7
        # plan bookkeeping follows (health keeps per-spoke devices)
        assert ws.supervisor.plan.hub.n_devices == 7
        assert sup.health()[0]["devices"] == []  # dead slice emptied

        # certified verdict parity with the failure-free run: the
        # surviving xhat slice certifies the same incumbent (a reslice
        # changes summation shapes by a zero-probability pad row, so
        # this is rtol parity, not bit parity)
        opt_val = ef_linprog(farmer.build_batch(S))[0]
        for run in (ref, ws):
            assert run.BestOuterBound <= opt_val + 1.0
            assert run.BestInnerBound >= opt_val - 1.0
        assert ws.BestInnerBound == pytest.approx(
            ref.BestInnerBound, rel=1e-6)
        np.testing.assert_allclose(
            np.asarray(ws.best_nonant_solution()),
            np.asarray(ref.best_nonant_solution()), rtol=1e-5)

        # counters the bench JSON reads
        c = telemetry.wheel_counters()
        assert c["wheel_reslice_events"] == 1
        assert c["wheel_devices_reclaimed"] == 1
        assert c["wheel_n_slices"] == 2


@pytest.mark.chaos
class TestCorruptWindowPrune:
    def test_corrupt_writes_counted_then_pruned(self, fresh_telemetry):
        """corrupt_window chaos flips every posted payload under an
        honest checksum: the hub's read_checked rejects each snapshot,
        the per-spoke corrupt-read counter climbs to the budget, the
        spoke is pruned like a crashed one — and the reslice barrier
        reclaims its device."""
        hub_dict, spoke_dicts = farmer_dicts(
            spoke_chaos={"corrupt_window": 1},
            opt_overrides={"PHIterLimit": 12},
            hub_opts={"max_corrupt_reads": 3})
        ws = MPMDWheel(hub_dict, spoke_dicts, lockstep=True)
        ws.spin()
        hub = ws.spcomm
        assert int(hub.corrupt_reads[0]) >= 3
        assert len(hub.failed_spokes) == 1
        assert "corrupt window reads" in hub.failed_spokes[0][1]
        # the poison never reached the bound state
        assert np.isfinite(ws.BestInnerBound)
        assert np.isfinite(ws.BestOuterBound)
        # prune feeds the same elastic path as a crash
        assert len(ws.supervisor.reslice_log) == 1
        c = telemetry.wheel_counters()
        assert c["wheel_corrupt_reads"] >= 3
        assert c["wheel_reslice_events"] == 1


# ---- wheel-level ensemble checkpoint / resume ----------------------------

@pytest.mark.chaos
class TestWheelEnsembleCheckpoint:
    def test_mid_spin_resume_replays_bit_equally(self, tmp_path,
                                                 fresh_telemetry):
        """Run A: uninterrupted lockstep spin.  Run B: identical run
        capped at iter 4, writing the ensemble checkpoint at the end of
        every sync.  Run C: MPMDWheel(resume_from=B's file) — the hub's
        PH state, every spoke's algorithm state, and the window
        payloads come back, so C's bound trajectory is the BIT-EQUAL
        tail of A's."""
        hub_dict, spoke_dicts = farmer_dicts(hub_class=RecordingHub)
        a = MPMDWheel(hub_dict, spoke_dicts, lockstep=True)
        a.spin()
        trace_a = np.array(a.spcomm.bound_trace)

        ck = os.fspath(tmp_path / "wheel_ensemble")
        hub_dict, spoke_dicts = farmer_dicts(
            opt_overrides={"PHIterLimit": 4},
            hub_opts={"wheel_checkpoint": ck})
        b = MPMDWheel(hub_dict, spoke_dicts, lockstep=True)
        b.spin()
        assert is_wheel_checkpoint(ck)
        with np.load(ck + ".npz", allow_pickle=True) as z:
            assert int(z["it"]) == 4
            assert int(z["wheel_n_spokes"]) == 2
            import json
            plan = json.loads(str(z["wheel_plan"]))
            assert plan[0]["name"] == "hub" and len(plan) == 3
            assert not os.path.exists(ck + ".npz.tmp")   # atomic
        # a wheel with a different spoke count must refuse the file
        with pytest.raises(ValueError, match="spokes"):
            load_wheel_ensemble(ck, types.SimpleNamespace(spokes=[None]))

        hub_dict, spoke_dicts = farmer_dicts(hub_class=RecordingHub)
        c = MPMDWheel(hub_dict, spoke_dicts, lockstep=True,
                      resume_from=ck)
        c.spin()
        trace_c = np.array(c.spcomm.bound_trace)

        # C ran only iterations 5.. — its trajectory is A's tail,
        # bitwise (np.array_equal, no tolerance)
        assert 0 < len(trace_c) < len(trace_a)
        assert np.array_equal(trace_c, trace_a[-len(trace_c):])
        assert c.BestOuterBound == a.BestOuterBound
        assert c.BestInnerBound == a.BestInnerBound
        np.testing.assert_array_equal(
            np.asarray(c.best_nonant_solution()),
            np.asarray(a.best_nonant_solution()))

    def test_plain_run_checkpoint_still_resumes_hub(self, tmp_path):
        """Back-compat both directions: a pre-PR-10 PLAIN run
        checkpoint is a valid MPMDWheel resume_from (hub restores,
        spokes start fresh), and load_wheel_ensemble refuses it with a
        pointed error instead of a KeyError."""
        from mpisppy_tpu.opt.ph import PH
        from mpisppy_tpu.parallel.mesh import ScenarioMesh
        ck = os.fspath(tmp_path / "plain_run")
        names = [f"scen{i}" for i in range(S)]
        # same 6-device mesh the wheel hub gets, so the padded S (and
        # with it the checkpoint's W shape) matches on resume
        ph = PH(dict(OPTS, PHIterLimit=3), names,
                batch=farmer.build_batch(S),
                mesh=ScenarioMesh(devices=jax.devices()[:6]))
        ph.ph_main(finalize=False)
        save_run_checkpoint(ck, ph)
        assert not is_wheel_checkpoint(ck)
        with pytest.raises(ValueError, match="plain PH run checkpoint"):
            load_wheel_ensemble(ck, types.SimpleNamespace(spokes=[]))
        # the wheel still consumes it: hub-only restore, full spin
        hub_dict, spoke_dicts = farmer_dicts()
        ws = MPMDWheel(hub_dict, spoke_dicts, lockstep=True,
                       resume_from=ck)
        ws.spin()
        assert np.isfinite(ws.BestInnerBound)
        # the restored state really seeded the loop: iteration count
        # moved past the checkpoint's it=3 (gap termination may stop
        # it anywhere after that)
        assert int(ws.spcomm.opt.state.it) > 3

    def test_is_wheel_checkpoint_missing_file(self, tmp_path):
        assert not is_wheel_checkpoint(os.fspath(tmp_path / "absent"))


# ---- drain checkpoint round-trip (serve satellite's file format) ---------

class TestDrainCheckpoint:
    def test_roundtrip_preserves_order_and_payload(self, tmp_path):
        p = os.fspath(tmp_path / "drainfile")
        reqs = [{"id": 3, "options": {"PHIterLimit": 4},
                 "scenario_names": ["a", "b"], "model": "farmer",
                 "batch": {"c": np.arange(3.0)}},
                {"id": 7, "options": {}, "scenario_names": None,
                 "model": None, "batch": {"c": np.zeros(2)}}]
        real = save_drain_checkpoint(p, reqs)
        assert real.endswith(".npz") and not os.path.exists(real + ".tmp")
        back = load_drain_checkpoint(p)
        assert [d["id"] for d in back] == [3, 7]
        assert back[0]["options"] == {"PHIterLimit": 4}
        np.testing.assert_array_equal(back[0]["batch"]["c"],
                                      np.arange(3.0))

    def test_rejects_foreign_npz(self, tmp_path):
        p = os.fspath(tmp_path / "notdrain.npz")
        np.savez(p, W=np.zeros(3))
        with pytest.raises(ValueError, match="not a drain checkpoint"):
            load_drain_checkpoint(p)


# ---- shutdown joins with per-thread shares -------------------------------

class _HungSpoke:
    """Stub spoke whose main() never returns (daemon thread leaks with
    the process, exactly the case shutdown() must bound)."""

    options = {}
    pair = None
    _failed = False

    def timed_step(self):
        return False

    def got_kill_signal(self):
        return True

    def main(self):                    # pragma: no cover - hung on purpose
        while True:
            time.sleep(0.05)


class TestShutdownShares:
    def test_hung_threads_split_the_global_budget(self):
        reports = []
        hub = types.SimpleNamespace(
            options={}, telemetry=None,
            report_spoke_failure=lambda sp, exc: reports.append(exc))
        spokes = [_HungSpoke(), _HungSpoke()]
        plan = types.SimpleNamespace(spokes=["s1", "s2"])
        sup = SliceSupervisor(hub, spokes, plan)
        sup.start()
        t0 = time.monotonic()
        sup.shutdown(timeout=0.6)
        elapsed = time.monotonic() - t0
        # the first hung thread consumed only ITS share — both got
        # joined within one global budget, not 2 x 0.6s serially
        assert elapsed < 1.5
        assert len(reports) == 2
        assert all(isinstance(e, TimeoutError) for e in reports)
        assert all("share" in str(e) for e in reports)


# ---- layering guard: resilience/ never imports mpmd or serve -------------

class TestResilienceLayering:
    def _assert_never_imports(self, forbidden):
        """resilience/ is the BOTTOM of the robustness stack: both the
        wheel (mpmd) and the replica-set front door (serve) build on
        it, so ANY import the other way (even lazy, anywhere in a
        function body) inverts the dependency."""
        res_dir = os.path.join(PKG_ROOT, "resilience")
        for fn in sorted(os.listdir(res_dir)):
            if not fn.endswith(".py"):
                continue
            tree = ast.parse(open(os.path.join(res_dir, fn)).read())
            for node in ast.walk(tree):
                if isinstance(node, ast.Import):
                    for a in node.names:
                        assert forbidden not in a.name.split("."), \
                            f"resilience/{fn} imports {forbidden}"
                elif isinstance(node, ast.ImportFrom):
                    mod = node.module or ""
                    assert forbidden not in mod.split("."), \
                        f"resilience/{fn} imports from {forbidden}"
                    for a in node.names:
                        assert a.name != forbidden, \
                            f"resilience/{fn} imports {forbidden}"

    def test_resilience_never_imports_mpmd(self):
        self._assert_never_imports("mpmd")

    def test_resilience_never_imports_serve(self):
        """PR 11: serve/ consumes resilience (chaos, restart_delay,
        drain checkpoints); resilience/ must never know serve exists."""
        self._assert_never_imports("serve")

"""Extensions + convergers tests (reference analog:
mpisppy/tests/test_ef_ph.py extension cases + convergers usage).

Uses small farmer instances; integer-fixing paths use the integer
farmer variant (use_integer=True marks DevotedAcreage integral).
"""

import numpy as np
import pytest

from mpisppy_tpu.convergers.fracintsnotconv import FractionalConverger
from mpisppy_tpu.convergers.norm_rho_converger import NormRhoConverger
from mpisppy_tpu.convergers.primal_dual_converger import PrimalDualConverger
from mpisppy_tpu.extensions import Extension, MultiExtension
from mpisppy_tpu.extensions.avgminmaxer import MinMaxAvg
from mpisppy_tpu.extensions.fixer import Fixer
from mpisppy_tpu.extensions.mipgapper import Gapper
from mpisppy_tpu.extensions.mult_rho_updater import MultRhoUpdater
from mpisppy_tpu.extensions.norm_rho_updater import NormRhoUpdater
from mpisppy_tpu.extensions.wtracker_extension import Wtracker_extension
from mpisppy_tpu.models import farmer
from mpisppy_tpu.opt.ph import PH


def make_ph(extensions=None, ext_kwargs=None, num_scens=3, opts_extra=None,
            use_integer=False):
    opts = {"defaultPHrho": 1.0, "PHIterLimit": 10, "convthresh": 1e-6,
            "pdhg_eps": 1e-6, "pdhg_max_iters": 4000}
    opts.update(opts_extra or {})
    b = farmer.build_batch(num_scens, use_integer=use_integer)
    return PH(opts, [f"scen{i}" for i in range(num_scens)], batch=b,
              extensions=extensions, extension_kwargs=ext_kwargs)


class HookRecorder(Extension):
    calls = []

    def __init__(self, ph):
        super().__init__(ph)
        HookRecorder.calls = []

    def pre_iter0(self):
        HookRecorder.calls.append("pre_iter0")

    def post_iter0(self):
        HookRecorder.calls.append("post_iter0")

    def miditer(self):
        HookRecorder.calls.append("miditer")

    def enditer(self):
        HookRecorder.calls.append("enditer")

    def post_everything(self):
        HookRecorder.calls.append("post_everything")


def test_hooks_fire_in_order():
    ph = make_ph(extensions=HookRecorder,
                 opts_extra={"PHIterLimit": 2, "convthresh": 0.0})
    ph.ph_main()
    calls = HookRecorder.calls
    assert calls[0] == "pre_iter0"
    assert calls[1] == "post_iter0"
    assert "miditer" in calls and "enditer" in calls
    assert calls[-1] == "post_everything"
    assert calls.index("post_iter0") < calls.index("miditer")


def test_multi_extension_fans_out():
    ph = make_ph(
        extensions=MultiExtension,
        ext_kwargs={"ext_classes": [HookRecorder, MinMaxAvg]},
        opts_extra={"PHIterLimit": 1, "convthresh": 0.0})
    ph.ph_main()
    assert "post_everything" in HookRecorder.calls


def test_gapper_sets_eps():
    ph = make_ph(
        extensions=Gapper,
        opts_extra={"PHIterLimit": 3, "convthresh": 0.0,
                    "gapperoptions": {"mipgapdict": {0: 1e-3, 2: 1e-5}}})
    ph.ph_main()
    assert float(ph.solver_eps) == pytest.approx(1e-5)


def test_fixer_fixes_integers():
    # integer farmer: DevotedAcreage integral; with the known optimum
    # (170, 80, 250) integral anyway, PH agrees quickly and the Fixer
    # should pin slots after nb consecutive ripe iterations
    ph = make_ph(
        extensions=Fixer, use_integer=True,
        opts_extra={"PHIterLimit": 12, "convthresh": 0.0,
                    "defaultPHrho": 2.0,
                    "fixeroptions": {"boundtol": 0.5, "nb": 2,
                                     "verbose": True}})
    ph.ph_main()
    assert ph.count_fixed() > 0
    # fixed slots must carry equal lb/ub at integral values
    na = np.asarray(ph.batch.nonant_idx)
    lb = np.asarray(ph.lb_eff)[:, na]
    ub = np.asarray(ph.ub_eff)[:, na]
    fixed = lb == ub
    assert np.allclose(lb[fixed], np.round(lb[fixed]))


def test_norm_rho_updater_changes_rho():
    ph = make_ph(
        extensions=NormRhoUpdater,
        opts_extra={"PHIterLimit": 6, "convthresh": 0.0,
                    "defaultPHrho": 1e-4,   # absurdly low -> primal dominates
                    "norm_rho_options": {"ratio": 2.0, "step": 2.0}})
    rho0 = float(np.mean(np.asarray(ph.rho)))
    ph.ph_main()
    assert float(np.mean(np.asarray(ph.rho))) > rho0


def test_mult_rho_updater():
    ph = make_ph(
        extensions=MultRhoUpdater,
        opts_extra={"PHIterLimit": 6, "convthresh": 0.0,
                    "defaultPHrho": 1e-5,
                    "mult_rho_options": {"convergence_tolerance": 1e-12,
                                         "rho_multiplier": 3.0}})
    rho0 = float(np.mean(np.asarray(ph.rho)))
    ph.ph_main()
    assert float(np.mean(np.asarray(ph.rho))) >= rho0


def test_wtracker_runs(capsys):
    ph = make_ph(
        extensions=Wtracker_extension,
        opts_extra={"PHIterLimit": 4, "convthresh": 0.0,
                    "wtracker_options": {"wlen": 3}})
    ph.ph_main()
    out = capsys.readouterr().out
    assert "WTracker" in out


def test_primal_dual_converger_stops():
    ph = make_ph(opts_extra={
        "PHIterLimit": 100, "convthresh": 0.0,
        "ph_converger": PrimalDualConverger,
        "primal_dual_converger_options": {"tol": 1e-2}})
    ph.ph_main()
    assert int(ph.state.it) < 100
    assert ph.convobject.convergence_value < 1e-2


def test_norm_rho_converger_stops():
    ph = make_ph(opts_extra={
        "PHIterLimit": 100, "convthresh": 0.0,
        "ph_converger": NormRhoConverger,
        "norm_rho_converger_tol": 1e-2})
    ph.ph_main()
    assert int(ph.state.it) < 100


def test_fractional_converger_integer_farmer():
    ph = make_ph(use_integer=True, opts_extra={
        "PHIterLimit": 60, "convthresh": 0.0,
        "defaultPHrho": 2.0,
        "ph_converger": FractionalConverger,
        "fracintsnotconv_tol": 0.5})
    ph.ph_main()
    assert ph.convobject.convergence_value is not None

"""FWPH tests on farmer (reference analog: fwph usage in
examples/farmer + test_ef_ph.py FWPH cases)."""

import numpy as np

from mpisppy_tpu.fwph import FWPH
from mpisppy_tpu.models import farmer


def make_fwph(num_scens=3, **extra):
    opts = {"defaultPHrho": 2.0, "PHIterLimit": 20, "convthresh": 1e-4,
            "pdhg_eps": 1e-7, "FW_iter_limit": 3, "column_bank": 20}
    opts.update(extra)
    b = farmer.build_batch(num_scens)
    return FWPH(opts, [f"scen{i}" for i in range(num_scens)], batch=b)


def test_fwph_farmer_dual_bound():
    fw = make_fwph(PHIterLimit=60, convthresh=1e-5)
    conv, eobj, dual_bound = fw.fwph_main()
    # the dual bound must be a valid outer bound on -108390, and for
    # the continuous farmer the Lagrangian dual is tight
    assert dual_bound <= -108389.0
    assert dual_bound >= -115406.0   # at least the wait-and-see bound
    assert abs(dual_bound - -108390.0) < 50.0


def test_fwph_hull_point_converges():
    fw = make_fwph(PHIterLimit=60, convthresh=1e-5)
    conv, eobj, _ = fw.fwph_main()
    xbar = np.asarray(fw.state.xbar[0])
    assert np.allclose(xbar, [170.0, 80.0, 250.0], atol=10.0)
    assert abs(eobj - -108390.0) < 200.0


def test_fwph_dual_bounds_monotone_best():
    fw = make_fwph(PHIterLimit=8, convthresh=0.0)
    fw.fwph_main()
    seq = fw._dual_bounds
    assert len(seq) >= 8
    assert fw.dual_bound == max(seq)


def test_fwph_spoke_with_ph_hub():
    from mpisppy_tpu.cylinders.fwph_spoke import FrankWolfeOuterBound
    from mpisppy_tpu.cylinders.hub import PHHub
    from mpisppy_tpu.opt.ph import PH
    from mpisppy_tpu.spin_the_wheel import WheelSpinner

    names = [f"scen{i}" for i in range(3)]
    opts = {"defaultPHrho": 1.0, "PHIterLimit": 25, "convthresh": 1e-5,
            "pdhg_eps": 1e-7}
    hub = {"hub_class": PHHub, "opt_class": PH,
           "hub_kwargs": {"options": {"rel_gap": 1e-3}},
           "opt_kwargs": {"options": opts, "all_scenario_names": names,
                          "batch": farmer.build_batch(3)}}
    spoke = {"spoke_class": FrankWolfeOuterBound, "opt_class": FWPH,
             "opt_kwargs": {"options": dict(opts, FW_iter_limit=2),
                            "all_scenario_names": names}}
    ws = WheelSpinner(hub, [spoke]).spin()
    assert ws.BestOuterBound <= -108388.0
    assert ws.BestOuterBound >= -115406.0


def test_fw_gap_early_stopping():
    """The SDM Gamma test (reference fwph.py:268-287) must end inner
    passes early once the hull contains the vertex optimum."""
    from mpisppy_tpu.models import farmer
    from mpisppy_tpu.fwph.fwph import FWPH
    b = farmer.build_batch(3)
    fw = FWPH({"defaultPHrho": 1.0, "PHIterLimit": 10,
               "convthresh": 1e-6, "pdhg_eps": 1e-7,
               "FW_iter_limit": 4, "FW_eps": 1e-5},
              list(b.tree.scen_names), batch=b)
    fw.fwph_main()
    assert fw.sdm_early_stops > 0

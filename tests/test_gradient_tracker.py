"""Gradient-rho + PHTracker tests (reference analog:
mpisppy/tests/test_gradient_rho.py + phtracker usage)."""

import os

import numpy as np

from mpisppy_tpu.extensions.gradient_extension import Gradient_extension
from mpisppy_tpu.extensions.phtracker import PHTracker
from mpisppy_tpu.models import farmer
from mpisppy_tpu.opt.ph import PH
from mpisppy_tpu.utils.gradient import (find_rho, grad_cost,
                                        read_grad_cost, write_grad_cost)

OPTS = {"defaultPHrho": 1.0, "PHIterLimit": 8, "convthresh": 1e-6,
        "pdhg_eps": 1e-6}


def make_ph(**kw):
    return PH(dict(OPTS, **kw.pop("opts", {})),
              [f"scen{i}" for i in range(3)],
              batch=farmer.build_batch(3), **kw)


def test_grad_cost_shape_and_values():
    ph = make_ph()
    ph.Iter0()
    g = grad_cost(ph)
    assert g.shape == (ph.batch.num_scens, 3)
    # farmer acreage gradient = planting cost (no qdiag)
    assert np.allclose(g[0], [150.0, 230.0, 260.0])


def test_find_rho_positive_bounded():
    ph = make_ph()
    ph.Iter0()
    rho = find_rho(ph, order_stat=0.5)
    assert rho.shape == (3,)
    assert (rho > 0).all()


def test_gradient_extension_sets_rho():
    ph = make_ph(extensions=Gradient_extension)
    rho0 = np.asarray(ph.rho).copy()
    ph.ph_main()
    assert not np.allclose(np.asarray(ph.rho), rho0)


def test_grad_csv_roundtrip(tmp_path):
    ph = make_ph()
    ph.Iter0()
    p = os.path.join(tmp_path, "grad.csv")
    write_grad_cost(p, ph)
    g = read_grad_cost(p, ph)
    assert np.allclose(g[:3], grad_cost(ph)[:3])


def test_phtracker_writes(tmp_path):
    folder = os.path.join(tmp_path, "trk")
    ph = make_ph(opts={"phtracker_options": {
        "results_folder": folder, "plot_bounds": True,
        "plot_xbars": True}}, extensions=PHTracker)
    ph.ph_main()
    # per-cylinder folder layout (reference phtracker.py): no spcomm
    # here, so the cylinder name defaults to "hub"
    cyl = os.path.join(folder, "hub")
    for name in ("bounds", "gaps", "xbars", "duals", "nonants",
                 "scen_costs"):
        path = os.path.join(cyl, f"{name}.csv")
        assert os.path.exists(path)
        with open(path) as f:
            lines = f.read().strip().splitlines()
        assert len(lines) >= 3   # header + iter0 + iterations
    # plots are optional exactly like in the production code (the
    # _plot_csv ImportError guard): only assert when matplotlib exists
    import pytest
    pytest.importorskip("matplotlib")
    for name in ("bounds", "xbars"):
        assert os.path.exists(os.path.join(cyl, f"{name}.png"))


def test_rho_csv_roundtrip(tmp_path):
    from mpisppy_tpu.utils import gradient
    ph = make_ph()
    ph.Iter0()      # all find_rho needs (matches the sibling tests)
    rho = gradient.find_rho(ph)
    p = os.path.join(tmp_path, "rhos.csv")
    gradient.write_rho(p, ph, rho)
    back = gradient.read_rho(p, ph)
    assert np.allclose(back, rho, rtol=1e-6)

"""Multistage tests: tree utilities + hydro golden values.

Reference analog: mpisppy/tests/test_ef_ph.py Test_hydro (3-stage,
branching factors [3,3]): PH trivial bound == 180 and consensus
E[objective] == 190 at 2 significant figures.
"""

import numpy as np
import pytest

from mpisppy_tpu.scenario_tree import (
    MultistageTree, create_nodenames_from_branching_factors)
from mpisppy_tpu.models import hydro


def round_pos_sig(x, sig=2):
    """Reference tests/utils.py round_pos_sig."""
    return round(x, -int(np.floor(np.log10(abs(x)))) + (sig - 1))


class TestTree:
    def test_nodenames(self):
        names = create_nodenames_from_branching_factors([3, 3])
        assert names == ["ROOT", "ROOT_0", "ROOT_1", "ROOT_2"]
        names = create_nodenames_from_branching_factors([2, 2, 2])
        assert names == ["ROOT", "ROOT_0", "ROOT_1",
                         "ROOT_0_0", "ROOT_0_1", "ROOT_1_0", "ROOT_1_1"]

    def test_scen_paths(self):
        t = MultistageTree([3, 3])
        assert t.num_scens == 9
        assert t.num_nodes == 4
        assert t.nodes_for_scen(0) == [0, 1]
        assert t.nodes_for_scen(4) == [0, 2]
        assert t.nodes_for_scen(8) == [0, 3]
        assert t.nodenames_for_scen(6) == ["ROOT", "ROOT_2"]
        assert abs(t.scen_probability(5) - 1 / 9) < 1e-12

    def test_three_level(self):
        t = MultistageTree([2, 2, 2])
        assert t.num_scens == 8
        assert t.num_nodes == 7
        # scenario 5 = digits (1, 0, 1): ROOT -> ROOT_1 -> ROOT_1_0
        assert t.nodes_for_scen(5) == [0, 2, 5]
        assert t.parent_of(5) == 2
        assert t.parent_of(2) == 0
        assert t.parent_of(0) is None
        assert t.stage_of_node(0) == 1
        assert t.stage_of_node(2) == 2
        assert t.stage_of_node(5) == 3

    def test_node_of_slots(self):
        t = MultistageTree([3, 3])
        node_of = t.node_of_slots(7, (1, 1, 2, 2))
        assert node_of.tolist() == [0, 0, 3, 3]


class TestHydro:
    def test_batch_shapes(self):
        b = hydro.build_batch()
        assert b.num_scens == 9
        assert b.num_vars == 13
        assert b.num_nonants == 8
        assert b.tree.num_nodes == 4
        assert float(np.sum(np.asarray(b.prob))) == pytest.approx(1.0)

    def test_creator_matches_batch(self):
        """LinearModel creator path agrees with the vectorized builder."""
        b = hydro.build_batch()
        s4 = hydro.scenario_creator("Scen5", branching_factors=[3, 3])
        np.testing.assert_allclose(np.asarray(s4.c[0]),
                                   np.asarray(b.c[4]), rtol=1e-12)
        np.testing.assert_allclose(np.asarray(s4.row_hi[0]),
                                   np.asarray(b.row_hi[4]), rtol=1e-12)
        assert np.asarray(s4.nonant_idx).tolist() == \
            np.asarray(b.nonant_idx).tolist()
        assert np.asarray(s4.tree.node_of[0]).tolist() == \
            np.asarray(b.tree.node_of[4]).tolist()

    def test_ef_golden(self):
        """EF objective: reference asserts consensus E[obj] == 190 at
        2 sig figs (test_ef_ph.py Test_hydro.test_ph_solve)."""
        from mpisppy_tpu.opt.ef import ExtensiveForm
        b = hydro.build_batch()
        ef = ExtensiveForm({"pdhg_eps": 1e-8, "pdhg_max_iters": 60000},
                           [f"Scen{i+1}" for i in range(9)], batch=b)
        ef.solve_extensive_form()
        obj = ef.get_objective_value()
        assert round_pos_sig(obj, 2) == 190
        # nonanticipativity holds: stage-2 nonants agree within groups
        xna = np.asarray(ef.nonants())
        for g in range(3):
            grp = xna[3 * g:3 * g + 3, 4:]
            assert np.max(np.abs(grp - grp[0])) < 1e-4
        # stage-1 nonants agree across ALL scenarios
        assert np.max(np.abs(xna[:, :4] - xna[0, :4])) < 1e-4

    def test_ph_golden(self):
        """PH on hydro: trivial bound 180, converged E[obj] 190
        (reference Test_hydro.test_ph_solve)."""
        from mpisppy_tpu.opt.ph import PH
        b = hydro.build_batch()
        ph = PH({"defaultPHrho": 1.0, "PHIterLimit": 100,
                 "convthresh": 1e-6, "pdhg_eps": 1e-8,
                 "pdhg_max_iters": 40000},
                [f"Scen{i+1}" for i in range(9)], batch=b)
        conv, eobj, tbound = ph.ph_main()
        assert round_pos_sig(tbound, 2) == 180
        # evaluate the implementable consensus solution, stage-by-stage
        inner, feas = ph.evaluate_xhat(ph.state.xbar)
        assert feas
        assert round_pos_sig(inner, 2) == 190

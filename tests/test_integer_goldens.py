"""Integer-golden validation (VERDICT r2 item 4): drive the MIP
machinery to the reference's asserted optima.

Reference goldens (mpisppy/tests/test_ef_ph.py):
  * sizes-3 EF MIP objective rounds to 220000.0 at 2 significant
    figures (test_ef_ph.py:137) — the Lokketangen-Woodruff SIZES
    instance with the published SIZES3 data.
Cross-checked against an independent scipy/HiGHS branch-and-cut oracle
(efcheck.ef_milp gave 224377.9 on this instance, which also rounds to
220000; our LP-diving incumbent lands within 0.3% of it).
"""

import jax
import numpy as np
import pytest

from mpisppy_tpu.models import farmer, sizes
from mpisppy_tpu.opt.mip import ExtensiveFormMIP
from mpisppy_tpu.parallel.mesh import ScenarioMesh


def _mesh1():
    """1-device mesh: the dive's host-side loop is sequential anyway,
    and padding 3 scenarios to the 8 virtual test devices triples the
    solve work (measured 1007s vs ~190s)."""
    return ScenarioMesh(devices=jax.devices()[:1])


def round_pos_sig(x, sig=2):
    """Reference tests/utils.py round_pos_sig: round to `sig`
    significant figures (positive numbers)."""
    import math
    return round(x, -int(math.floor(math.log10(abs(x)))) + (sig - 1))


def test_sizes3_mip_golden_slow():
    """The reference's sizes-3 EF golden: objective == 220000 at 2 sig
    figs (test_ef_ph.py:137), via the three-phase LP dive."""
    b = sizes.build_batch(3)
    ef = ExtensiveFormMIP({"pdhg_eps": 1e-6, "pdhg_max_iters": 200000},
                          b.tree.scen_names, batch=b, mesh=_mesh1())
    out = ef.solve_mip()
    assert round_pos_sig(out["incumbent"], 2) == 220000.0
    # the root bound is a VALID outer bound; the incumbent is integer
    # feasible, so this is a true optimality certificate
    assert out["bound"] <= out["incumbent"]
    assert out["gap"] < 0.025
    assert out["viol"] < 1e-3
    # integer slots integral (ef.batch: the possibly padded batch the
    # dive ran on)
    imask = np.asarray(ef.batch.integer_mask)
    xi = out["x"][imask]
    assert np.allclose(xi, np.round(xi))


def test_sizes_lp_relaxation_matches_oracle():
    """Tightened-M sizes LP relaxation vs the scipy/HiGHS oracle."""
    import sys
    from pathlib import Path
    sys.path.insert(0, str(Path(__file__).parent))
    from efcheck import ef_linprog

    from mpisppy_tpu.opt.ef import ExtensiveForm
    b = sizes.build_batch(3)
    lp, _ = ef_linprog(b)
    ef = ExtensiveForm({"pdhg_eps": 1e-6, "pdhg_max_iters": 200000},
                       b.tree.scen_names, batch=b)
    ef.solve_extensive_form()
    assert ef.get_objective_value() == pytest.approx(lp, rel=1e-4)
    # reference-parity data points: 10 sizes, capacity 200000,
    # first-period demand from the published SIZES3 .dat files
    assert b.num_nonants == 65          # x1 (10) + y1 (55); z derived
    assert float(np.asarray(b.row_hi)[0, -1]) == 200000.0


def test_sslp_siplib_golden_slow():
    """The published SIPLIB sslp_5_25_50 optimum is -121.6; the HiGHS
    oracle on our embedded instance data reproduces it exactly
    (efcheck.ef_milp: -121.60, LP relaxation -160.06).  The LP dive
    must find an integer-feasible incumbent within 2 sig figs
    (round_pos_sig -> -120.0)."""
    from mpisppy_tpu.models import sslp
    b = sslp.build_batch(50, instance="sslp_5_25")
    ef = ExtensiveFormMIP({"pdhg_eps": 1e-6, "pdhg_max_iters": 200000},
                          b.tree.scen_names, batch=b, mesh=_mesh1())
    out = ef.solve_mip()
    assert -round_pos_sig(-out["incumbent"], 2) == -120.0
    assert out["incumbent"] >= -121.6 - 1e-6     # oracle is optimal
    assert out["bound"] <= out["incumbent"]
    imask = np.asarray(ef.batch.integer_mask)
    xi = out["x"][imask]
    assert np.allclose(xi, np.round(xi))


def test_farmer_integer_mip_dive():
    """Integer farmer (acreage integrality, reference farmer.py
    use_integer): the dive returns an integral incumbent within a few
    percent of the LP bound."""
    b = farmer.build_batch(6, use_integer=True)
    ef = ExtensiveFormMIP({"pdhg_eps": 1e-7, "pdhg_max_iters": 200000},
                          b.tree.scen_names, batch=b, mesh=_mesh1())
    out = ef.solve_mip()
    assert out["bound"] <= out["incumbent"] + 1e-6
    assert out["gap"] < 0.02
    na = np.asarray(ef.batch.nonant_idx)
    xi = out["x"][:, na]
    assert np.allclose(xi, np.round(xi))
    # farmer-6 integer EF optimum, verified against the scipy/HiGHS
    # branch-and-cut oracle (efcheck.ef_milp): -123483.8788 — the dive
    # reproduces it exactly
    assert out["incumbent"] == pytest.approx(-123483.879, rel=1e-4)

"""IR + modeling layer tests (stack, pad, LinearModel lowering parity)."""

import numpy as np

from mpisppy_tpu.ir import stack_scenarios, pad_scenarios
from mpisppy_tpu.models import farmer


def test_linear_model_matches_vectorized_builder():
    """scenario_creator (declarative API) and build_batch (vectorized)
    must lower to identical arrays."""
    fast = farmer.build_batch(3)
    slow = stack_scenarios(
        [farmer.scenario_creator(f"scen{i}", num_scens=3)
         for i in range(3)],
        scen_names=[f"scen{i}" for i in range(3)])
    assert np.allclose(np.asarray(fast.c), np.asarray(slow.c))
    assert np.allclose(np.asarray(fast.lb), np.asarray(slow.lb))
    assert np.allclose(np.asarray(fast.ub), np.asarray(slow.ub))
    assert np.array_equal(np.asarray(fast.nonant_idx),
                          np.asarray(slow.nonant_idx))
    # constraint rows may be ordered differently in principle; here the
    # builders emit the same order by construction
    assert np.allclose(np.asarray(fast.A), np.asarray(slow.A))
    assert np.allclose(np.asarray(fast.row_lo), np.asarray(slow.row_lo))
    assert np.allclose(np.asarray(fast.row_hi), np.asarray(slow.row_hi))


def test_random_yields_match_reference_protocol():
    """Scenario i>=3 yields = base + RandomState(i).rand(3)
    (reference farmer.py:60,159-165)."""
    y = farmer.scenario_yields(5)
    rng = np.random.RandomState(5)
    expected = np.array([3.0, 3.6, 24.0]) + rng.rand(3)
    assert np.allclose(y, expected)
    # scenarios 0..2 are the unperturbed base cases
    assert np.allclose(farmer.scenario_yields(1), [2.5, 3.0, 20.0])


def test_pad_scenarios_zero_prob():
    b = farmer.build_batch(3)
    p = pad_scenarios(b, 8)
    assert p.num_scens == 8
    prob = np.asarray(p.tree.prob)
    assert np.allclose(prob[3:], 0.0)
    assert abs(prob.sum() - 1.0) < 1e-12


def test_probability_normalization():
    b = stack_scenarios(
        [farmer.scenario_creator(f"scen{i}") for i in range(4)])
    assert abs(float(np.sum(np.asarray(b.tree.prob))) - 1.0) < 1e-12

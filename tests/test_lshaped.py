"""L-shaped / Benders tests on farmer (reference analog:
test_ef_ph.py L-shaped cases + examples/farmer/farmer_lshapedhub.py)."""

import numpy as np
import pytest

from mpisppy_tpu.models import farmer
from mpisppy_tpu.opt.lshaped import LShapedMethod


def make_ls(num_scens=3, **extra):
    opts = {"max_iter": 40, "tol": 1e-5, "pdhg_eps": 1e-7}
    opts.update(extra)
    b = farmer.build_batch(num_scens)
    return LShapedMethod(opts, [f"scen{i}" for i in range(num_scens)],
                         batch=b)


def test_lshaped_farmer_golden():
    ls = make_ls()
    outer, inner, xhat = ls.lshaped_algorithm()
    # both bounds bracket and approach the EF optimum -108390
    assert outer <= -108389.0 + 1.0
    assert inner >= -108391.0 - 1.0
    assert abs(inner - -108390.0) < 30.0
    assert abs(outer - -108390.0) < 30.0
    assert np.allclose(xhat, [170.0, 80.0, 250.0], atol=2.0)


def test_lshaped_single_cut():
    ls = make_ls(single_cut=True, max_iter=80)
    outer, inner, xhat = ls.lshaped_algorithm()
    assert abs(inner - -108390.0) < 50.0


def test_lshaped_bounds_bracket_each_iteration():
    ls = make_ls(max_iter=10, tol=0.0)
    outer, inner, _ = ls.lshaped_algorithm()
    # outer (root relaxation) must never exceed inner (feasible eval)
    # beyond first-order solver tolerance
    assert outer <= inner + 1e-5 * abs(inner)


def test_lshaped_hub_with_xhat_spoke():
    from mpisppy_tpu.cylinders.hub import LShapedHub
    from mpisppy_tpu.cylinders.lshaped_bounder import XhatLShapedInnerBound
    from mpisppy_tpu.spin_the_wheel import WheelSpinner
    from mpisppy_tpu.utils.xhat_eval import Xhat_Eval

    opts = {"max_iter": 25, "tol": 1e-6, "pdhg_eps": 1e-7,
            "rel_gap": 1e-4}
    names = [f"scen{i}" for i in range(3)]
    b = farmer.build_batch(3)
    hub = {"hub_class": LShapedHub, "opt_class": LShapedMethod,
           "hub_kwargs": {"options": {"rel_gap": 1e-4}},
           "opt_kwargs": {"options": opts, "all_scenario_names": names,
                          "batch": b}}
    spoke = {"spoke_class": XhatLShapedInnerBound, "opt_class": Xhat_Eval,
             "opt_kwargs": {"options": dict(opts),
                            "all_scenario_names": names}}
    ws = WheelSpinner(hub, [spoke]).spin()
    assert abs(ws.BestInnerBound - -108390.0) < 50.0
    assert ws.BestOuterBound <= ws.BestInnerBound + 1e-5 * abs(
        ws.BestInnerBound)

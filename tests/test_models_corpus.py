"""Model-corpus validation (reference analog: examples/run_all.py +
the golden-value asserts of test_ef_ph.py).

Every model's lowering is checked against the independent scipy/HiGHS
EF oracle (efcheck.ef_linprog) — this validates BOTH the model arrays
and the consensus-mode PDHG kernel — plus a PH smoke run.
"""

import numpy as np
import pytest

from efcheck import ef_linprog
from mpisppy_tpu.models import (aircond, apl1p, battery, farmer, netdes,
                                sizes, sslp, uc)
from mpisppy_tpu.opt.ef import ExtensiveForm
from mpisppy_tpu.opt.ph import PH

EF_OPTS = {"pdhg_eps": 1e-7, "pdhg_max_iters": 200000}


def _names(batch):
    return list(batch.tree.scen_names)


def _check_ef(batch, n_real, rtol=2e-4):
    """Consensus-PDHG EF objective must match the scipy oracle."""
    ref_obj, _ = ef_linprog(batch, n_real=n_real)
    ef = ExtensiveForm(dict(EF_OPTS), _names(batch)[:n_real], batch=batch)
    ef.solve_extensive_form()
    got = ef.get_objective_value()
    assert got == pytest.approx(ref_obj, rel=rtol, abs=1e-4 + rtol * abs(ref_obj))
    return ref_obj


def _check_ph(batch, n_real, ref_obj, rtol=0.02):
    opts = {"defaultPHrho": 10.0, "PHIterLimit": 60, "convthresh": 1e-5,
            "pdhg_eps": 1e-6}
    ph = PH(opts, _names(batch)[:n_real], batch=batch)
    conv, eobj, triv = ph.ph_main()
    # trivial bound below optimum; E[obj] near it at loose tolerance
    assert triv <= ref_obj + 1e-3 * abs(ref_obj) + 1.0
    assert eobj == pytest.approx(ref_obj, rel=rtol, abs=rtol * abs(ref_obj) + 1.0)


def test_sizes_ef_and_ph():
    b = sizes.build_batch(3, num_sizes=3)
    ref = _check_ef(b, 3)
    # PH on the real (tight-M, degenerate) SIZES data reaches x~xbar
    # well before W equilibrates; the reference's own sizes goldens
    # accept PH ~3% off the EF value (test_ef_ph.py: 230000 vs 220000)
    _check_ph(b, 3, ref, rtol=0.06)


def test_sizes_rho_setter():
    # reference sizes _rho_setter: rho = 0.001 * cost coefficient
    # (unit production cost for x1 slots, reduction cost for y1 slots)
    b = sizes.build_batch(3, num_sizes=3)
    rho = sizes.rho_setter(b)
    assert rho.shape == (3, b.num_nonants)
    assert (rho > 0).all()
    assert rho[0, 0] == pytest.approx(0.001 * sizes.UNIT_COST[0])


def test_sslp_ef():
    b = sslp.build_batch(4, m_sites=3, n_clients=6)
    _check_ef(b, 4)


def test_apl1p_ef_and_ph():
    b = apl1p.build_batch()
    ref = _check_ef(b, apl1p.max_num_scens())
    _check_ph(b, apl1p.max_num_scens(), ref)


def test_battery_ef():
    b = battery.build_batch(4, H=8)
    _check_ef(b, 4)


def test_netdes_ef():
    b = netdes.build_batch(4, n_nodes=5)
    _check_ef(b, 4)


def test_aircond_multistage_ef():
    b = aircond.build_batch(branching_factors=(3, 2))
    assert b.tree.num_nodes == 1 + 3      # ROOT + 3 stage-2 nodes
    _check_ef(b, 6)


def test_uc_ef():
    # UC's relaxation is degenerate (ramping + Pmin rows); PDHG stalls
    # near 4e-4 relative KKT, so the oracle match is looser here
    b = uc.build_batch(3, H=4)
    _check_ef(b, 3, rtol=2e-3)


def test_farmer_oracle_agrees_with_golden():
    # sanity of the oracle itself on the known value
    b = farmer.build_batch(3)
    ref, _ = ef_linprog(b, n_real=3)
    assert ref == pytest.approx(-108390.0, abs=1.0)


def test_aircond_demand_structure():
    b = aircond.build_batch(branching_factors=(2, 2))
    # scenarios sharing the stage-2 node must share stage-2 demand
    # (encoded in row_lo of the balance equality)
    lo = np.asarray(b.row_lo)
    node2 = np.asarray(b.tree.node_of)[:, 4]   # a stage-2 slot
    for nd in set(node2.tolist()):
        members = np.where(node2 == nd)[0]
        assert np.allclose(lo[members, 1], lo[members[0], 1])

"""MPMD-wheel tests: slice plans over the faked 8-device fleet,
device-resident mailboxes vs. the host seqlock, the exchange-backend
seam, crash/prune parity with the multiproc supervisor, and the
import-layering guards (cylinders/ never imports mpmd/; mpmd/ keeps
jax lazy).

Everything runs on the 8 virtual CPU devices conftest.py forces with
--xla_force_host_platform_device_count, so the cross-slice device_put
hops are real resharding transfers, just over host memory.
"""

import ast
import os
import subprocess
import sys

import numpy as np
import pytest

import jax

from efcheck import ef_linprog
from mpisppy_tpu import telemetry
from mpisppy_tpu.cylinders.hub import PHHub
from mpisppy_tpu.cylinders.lagrangian_bounder import LagrangianOuterBound
from mpisppy_tpu.cylinders.spcommunicator import (
    _WINDOW_BACKENDS, Window, WindowPair)
from mpisppy_tpu.cylinders.xhatshufflelooper_bounder import (
    XhatShuffleInnerBound)
from mpisppy_tpu.models import farmer
from mpisppy_tpu.mpmd import (
    CylinderSlice, DeviceWindow, MPMDWheel, SlicePlan)
from mpisppy_tpu.opt.ph import PH
from mpisppy_tpu.parallel.mesh import ScenarioMesh
from mpisppy_tpu.runtime import native
from mpisppy_tpu.spin_the_wheel import WheelSpinner
from mpisppy_tpu.utils.xhat_eval import Xhat_Eval

pytestmark = pytest.mark.mpmd

PKG_ROOT = os.path.join(os.path.dirname(__file__), "..", "mpisppy_tpu")

OPTS = {"defaultPHrho": 1.0, "PHIterLimit": 40, "convthresh": 0.0,
        "pdhg_eps": 1e-7, "pdhg_max_iters": 20000}
S = 3
NAMES = [f"scen{i}" for i in range(S)]


def farmer_dicts(hub_class=PHHub, spoke_chaos=None, opt_overrides=None,
                 hub_opts=None):
    """hub+Lagrangian+xhat wheel dicts on farmer S=3 (the
    test_resilience.farmer_wheel shapes, separated so both WheelSpinner
    and MPMDWheel can consume them)."""
    opts = {**OPTS, **(opt_overrides or {})}
    lag_opts = {"chaos": spoke_chaos} if spoke_chaos else {}
    hub_dict = {
        "hub_class": hub_class,
        "hub_kwargs": {"options": {"rel_gap": 1e-4, "abs_gap": 1.0,
                                   **(hub_opts or {})}},
        "opt_class": PH,
        "opt_kwargs": {"options": opts, "all_scenario_names": NAMES,
                       "batch": farmer.build_batch(S)},
    }
    spoke_dicts = [
        {"spoke_class": LagrangianOuterBound,
         "spoke_kwargs": {"options": lag_opts},
         "opt_class": PH,
         "opt_kwargs": {"options": dict(opts),
                        "all_scenario_names": NAMES}},
        {"spoke_class": XhatShuffleInnerBound,
         "spoke_kwargs": {"options": {}},
         "opt_class": Xhat_Eval,
         "opt_kwargs": {"options": dict(opts),
                        "all_scenario_names": NAMES}},
    ]
    return hub_dict, spoke_dicts


@pytest.fixture
def fresh_telemetry():
    """Enabled telemetry with a fresh registry, dropped after the test
    so later tests see the default (env-driven, disabled) instance."""
    tel = telemetry.configure(True)
    yield tel
    telemetry.reset()


class TestMesh2D:
    """Satellite: the 2-D cylinder x scenario ScenarioMesh."""

    def test_2d_shape_and_scen_size(self):
        m = ScenarioMesh(n_cyl=4)
        assert m.size == 8
        assert m.scen_size == 2
        assert m.mesh.axis_names == ("cyl", "scen")
        with pytest.raises(ValueError, match="do not split"):
            ScenarioMesh(devices=jax.devices()[:6], n_cyl=4)

    def test_slice_axis_disjoint_and_cover(self):
        m = ScenarioMesh(n_cyl=4)
        rows = m.slice_axis("cyl")
        assert len(rows) == 4
        seen = []
        for sub in rows:
            assert isinstance(sub, ScenarioMesh)
            assert sub.n_cyl is None          # rows are 1-D
            assert sub.size == 2
            for d in sub.devices:
                assert d not in seen           # pairwise disjoint
                seen.append(d)
        assert seen == m.devices               # together they cover

    def test_slice_axis_names_and_1d(self):
        m2 = ScenarioMesh(n_cyl=2)
        with pytest.raises(ValueError, match="cylinder axis"):
            m2.slice_axis("rows")
        m1 = ScenarioMesh()
        assert m1.slice_axis() == [m1]

    def test_submesh_membership(self):
        m = ScenarioMesh(devices=jax.devices()[:4])
        sub = m.submesh(jax.devices()[1:3])
        assert sub.devices == jax.devices()[1:3]
        with pytest.raises(ValueError, match="not part of this mesh"):
            m.submesh([jax.devices()[5]])
        with pytest.raises(ValueError, match="at least one device"):
            m.submesh([])

    def test_2d_rows_shard_batch_identically(self):
        """Each cylinder row pads to its own scen_size — equal rows
        mean every cylinder agrees on the padded S (the window-length
        invariant the MPMD wheel needs)."""
        m = ScenarioMesh(n_cyl=2)
        b = farmer.build_batch(3)
        sharded = m.shard_batch(b)
        assert sharded.num_scens == 4          # padded to scen_size=4
        for sub in m.slice_axis():
            assert sub.shard_batch(b).num_scens == 4


class TestSlicePlan:
    def test_partition_hub_heavy(self):
        plan = SlicePlan.partition(2, devices=jax.devices())
        assert plan.n_slices == 3
        assert plan.hub.name == "hub" and plan.hub.n_devices == 6
        assert [s.n_devices for s in plan.spokes] == [1, 1]
        assert plan.pad_multiple() == 6        # lcm(6, 1, 1)
        assert plan.devices == jax.devices()
        # slice meshes are real ScenarioMeshes over their devices
        assert plan.hub.mesh().size == 6

    def test_disjointness_enforced(self):
        d = jax.devices()
        with pytest.raises(ValueError, match="disjoint"):
            SlicePlan([CylinderSlice("hub", 0, (d[0], d[1])),
                       CylinderSlice("spoke0", 1, (d[1],))])
        with pytest.raises(ValueError, match="no devices"):
            SlicePlan([CylinderSlice("hub", 0, ())])
        with pytest.raises(ValueError, match="at least the hub"):
            SlicePlan([])

    def test_partition_too_few_devices(self):
        with pytest.raises(ValueError, match="need at least"):
            SlicePlan.partition(2, devices=jax.devices()[:2])

    def test_uniform_from_2d_mesh(self):
        m = ScenarioMesh(n_cyl=4)
        plan = SlicePlan.uniform(m, spoke_names=["lag", "xhat", "cut"])
        assert [s.name for s in plan.slices] == \
            ["hub", "lag", "xhat", "cut"]
        assert all(s.n_devices == 2 for s in plan.slices)
        assert plan.pad_multiple() == 2
        with pytest.raises(ValueError, match="n_cyl >= 2"):
            SlicePlan.uniform(ScenarioMesh())

    def test_from_mesh_validates_membership(self):
        m = ScenarioMesh(devices=jax.devices()[:4])
        plan = SlicePlan.from_mesh(m, 2)
        assert plan.hub.n_devices == 2
        with pytest.raises(ValueError, match="need at least"):
            SlicePlan.from_mesh(ScenarioMesh(devices=jax.devices()[:2]), 2)

    def test_describe_json_safe(self):
        import json
        plan = SlicePlan.partition(2, devices=jax.devices())
        desc = json.loads(json.dumps(plan.describe()))
        assert desc[0]["name"] == "hub" and len(desc) == 3


class TestDeviceWindow:
    def test_roundtrip_and_ids(self):
        w = DeviceWindow(4)
        data, wid = w.read()
        assert wid == 0 and np.array_equal(data, np.zeros(4))
        assert w.write(np.arange(4.0)) == 1
        data, wid = w.read()
        assert wid == 1 and np.array_equal(data, np.arange(4.0))
        assert data.dtype == np.float64
        # explicit id (the spoke-side heartbeat protocol re-posts
        # under a chosen id)
        assert w.write(np.ones(4), write_id=7) == 7
        assert w.write_id == 7

    def test_shape_mismatch(self):
        w = DeviceWindow(4)
        with pytest.raises(ValueError, match="expects shape"):
            w.write(np.zeros(3))

    def test_kill_signal(self):
        w = DeviceWindow(2)
        w.write(np.ones(2))
        w.send_kill()
        assert w.write_id == Window.KILL == DeviceWindow.KILL
        _, wid = w.read()
        assert wid == -1

    def test_payload_lives_on_the_pinned_device(self):
        target = jax.devices()[5]
        w = DeviceWindow(3, device=target)
        w.write(np.arange(3.0))
        arr, wid = w.read_device()
        assert wid == 1
        assert list(arr.devices()) == [target]
        # read_device hands back the committed device array, no host copy
        assert isinstance(arr, jax.Array)
        np.testing.assert_array_equal(np.asarray(arr), np.arange(3.0))

    def test_stale_read_accounting(self, fresh_telemetry):
        w = DeviceWindow(2)
        reg = fresh_telemetry.registry
        w.write(np.ones(2))
        w.read()
        assert reg.counter("wheel.stale_reads").value == 0
        w.read()                               # same id again -> stale
        assert reg.counter("wheel.stale_reads").value == 1
        w.write(np.zeros(2))
        w.read()                               # fresh id -> not stale
        assert reg.counter("wheel.stale_reads").value == 1
        # pre-first-write id 0 and the kill id never count as stale
        w.send_kill()
        w.read()
        w.read()
        assert reg.counter("wheel.stale_reads").value == 1
        assert reg.counter("wheel.exchange_writes").value == 2
        assert reg.counter("wheel.exchange_bytes").value == 32
        assert reg.histogram("wheel.exchange_seconds").total > 0.0


class TestPySeqlockFallback:
    """Satellite: the pure-Python mmap seqlock behind NativeWindow."""

    def test_roundtrip_ids_kill(self):
        w = native.PySeqlockWindow(3)
        data, wid = w.read()
        assert wid == 0 and np.array_equal(data, np.zeros(3))
        assert w.write(np.arange(3.0)) == 1
        assert w.write(np.arange(3.0) + 1, write_id=9) == 9
        data, wid = w.read()
        assert wid == 9 and np.array_equal(data, np.arange(3.0) + 1)
        with pytest.raises(ValueError, match="expects shape"):
            w.write(np.zeros(4))
        w.send_kill()
        assert w.write_id == -1
        w.close()
        w.close()                               # idempotent

    def test_file_backed_cross_handle(self, tmp_path):
        p = str(tmp_path / "win.to_hub")
        a = native.PySeqlockWindow(4, path=p)
        b = native.PySeqlockWindow(4, path=p)   # attach, not reset
        a.write(np.full(4, 2.5))
        data, wid = b.read()
        assert wid == 1 and np.array_equal(data, np.full(4, 2.5))
        with pytest.raises(RuntimeError, match="length mismatch"):
            native.PySeqlockWindow(5, path=p)
        a.close()
        b.close()

    def test_native_window_delegates_when_lib_missing(self, monkeypatch):
        monkeypatch.setattr(native, "_load", lambda: None)
        assert not native.available()
        w = native.NativeWindow(3)
        assert w._py is not None                # pure-Python inside
        w.write(np.arange(3.0))
        data, wid = w.read()
        assert wid == 1 and np.array_equal(data, np.arange(3.0))
        w.send_kill()
        assert w.write_id == -1
        w.close()

    @pytest.mark.skipif(not native.available(),
                        reason="compiled exchange library unavailable")
    def test_interop_with_native_layout(self, tmp_path):
        """One mmap file, C++ writer + Python reader and vice versa —
        the fallback really is the same memory layout."""
        p = str(tmp_path / "interop")
        cpp = native.NativeWindow(3, path=p, reset=True)
        py = native.PySeqlockWindow(3, path=p)
        cpp.write(np.array([1.0, 2.0, 3.0]))
        data, wid = py.read()
        assert wid == 1 and np.array_equal(data, [1.0, 2.0, 3.0])
        py.write(np.array([4.0, 5.0, 6.0]))
        data, wid = cpp.read()
        assert wid == 2 and np.array_equal(data, [4.0, 5.0, 6.0])
        py.send_kill()
        assert cpp.write_id == -1
        cpp.close()
        py.close()


class TestBackendSeam:
    def test_registry_has_device_backend(self):
        assert "device" in _WINDOW_BACKENDS   # mpmd imported above
        pair = WindowPair(4, 2, backend="device")
        assert isinstance(pair.to_spoke, DeviceWindow)
        assert pair.to_spoke.length == 4 and pair.to_hub.length == 2

    def test_backend_kwargs_flow_through(self):
        d = jax.devices()
        pair = WindowPair(4, 2, backend="device",
                          backend_kwargs={"spoke_device": d[6],
                                          "hub_device": d[0],
                                          "tag": "pair0"})
        pair.to_spoke.write(np.zeros(4))
        pair.to_hub.write(np.zeros(2))
        # each mailbox sits on the RECEIVING slice
        assert list(pair.to_spoke.read_device()[0].devices()) == [d[6]]
        assert list(pair.to_hub.read_device()[0].devices()) == [d[0]]

    def test_unregistered_backend_raises(self):
        with pytest.raises(RuntimeError, match="not registered"):
            WindowPair(4, 2, backend="bogus")

    def test_seqlock_alias(self):
        pair = WindowPair(4, 2, backend="seqlock")
        assert type(pair.to_spoke) is Window
        assert type(pair.to_hub) is Window

    def test_select_backend(self):
        hub_dict, spoke_dicts = farmer_dicts()

        class FakeOpt:
            def __init__(self, n):
                self.mesh = type("M", (), {"size": n})()

        ws = WheelSpinner(hub_dict, spoke_dicts)
        # auto on a fleet: the fused collective fabric (interleaved)
        assert ws._select_backend(FakeOpt(8)) == "collective"
        assert ws.exchange_backend is None
        assert ws._select_backend(FakeOpt(1)) == "python"   # auto, solo
        # threads mode keeps per-pair mailboxes under auto
        ws = WheelSpinner(hub_dict, spoke_dicts, mode="threads")
        assert ws._select_backend(FakeOpt(8)) == "device"
        ws = WheelSpinner(hub_dict, spoke_dicts,
                          exchange_backend="seqlock")
        assert ws._select_backend(FakeOpt(8)) == "python"   # forced host
        ws = WheelSpinner(hub_dict, spoke_dicts,
                          exchange_backend="native")
        assert ws._select_backend(FakeOpt(8)) == "native"
        ws = WheelSpinner(hub_dict, spoke_dicts,
                          exchange_backend="device")
        assert ws._select_backend(FakeOpt(1)) == "device"   # forced device
        ws = WheelSpinner(hub_dict, spoke_dicts,
                          exchange_backend="collective")
        assert ws._select_backend(FakeOpt(1)) == "collective"  # forced


class RecordingHub(PHHub):
    """PHHub that logs (BestOuterBound, BestInnerBound) after every
    sync — the bound trajectory the parity test compares."""

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self.bound_trace = []

    def sync(self):
        super().sync()
        self.bound_trace.append((float(self.BestOuterBound),
                                 float(self.BestInnerBound)))


class TestExchangeParity:
    def test_device_vs_seqlock_bound_trajectory(self):
        """The exchange backend is pure transport: the interleaved
        wheel's per-iteration bound trajectory on farmer must be
        IDENTICAL through host seqlock windows and device mailboxes
        (both carry the same float64 vectors; the schedule is the
        deterministic inline one)."""
        traces = {}
        for backend in ("seqlock", "device"):
            hub_dict, spoke_dicts = farmer_dicts(hub_class=RecordingHub)
            ws = WheelSpinner(hub_dict, spoke_dicts, mode="interleaved",
                              exchange_backend=backend)
            ws.spin()
            assert ws.spcomm.options["window_backend"] == \
                ("python" if backend == "seqlock" else "device")
            traces[backend] = np.array(ws.spcomm.bound_trace)
        a, b = traces["seqlock"], traces["device"]
        assert a.shape == b.shape and len(a) > 0
        np.testing.assert_allclose(a, b, rtol=1e-9)
        # same certified verdict, and the run actually produced bounds
        assert np.isfinite(a[-1]).all()


class TestMPMDWheelEndToEnd:
    def test_overlapped_wheel_brackets_ef(self, fresh_telemetry):
        hub_dict, spoke_dicts = farmer_dicts(
            opt_overrides={"telemetry": True})
        ws = MPMDWheel(hub_dict, spoke_dicts)
        ws.spin()
        # disjoint 3-slice plan over the faked fleet
        plan = ws.plan
        assert plan.n_slices == 3
        assert len(set(plan.devices)) == sum(
            s.n_devices for s in plan.slices)
        # bounds bracket the true EF optimum (minimization)
        opt_val = ef_linprog(farmer.build_batch(S))[0]
        assert ws.BestOuterBound <= opt_val + 1.0
        assert ws.BestInnerBound >= opt_val - 1.0
        assert ws.BestInnerBound - ws.BestOuterBound < 500.0
        # accounting the bench JSON reads
        assert 0.0 <= ws.hub_overlap_fraction <= 1.0
        keys = set(ws.slice_phase_seconds)
        assert "hub" in keys
        assert any(k.startswith("slice1:") for k in keys)
        assert any(k.startswith("slice2:") for k in keys)
        c = telemetry.wheel_counters()
        assert c["wheel_n_slices"] == 3
        assert c["wheel_exchange_writes"] > 0
        assert c["wheel_exchange_bytes"] > 0
        assert c["wheel_exchange_latency_seconds"] > 0.0
        assert c["wheel_slice_restarts"] == 0
        assert c["wheel_slices_failed"] == 0
        # per-slice bound progression gauges (keyed by trace track)
        tracks = set(c["wheel_slice_bounds"])
        assert any("LagrangianOuterBound" in t for t in tracks)
        assert any("XhatShuffleInnerBound" in t for t in tracks)
        # supervisor health covers both spoke slices, nothing failed
        health = ws.supervisor.health()
        assert len(health) == 2
        assert not any(h["failed"] for h in health)

    def test_lockstep_matches_plan_padding(self, fresh_telemetry):
        """lockstep drives spokes inline on their own slices; the one
        shared batch is pre-padded to the plan's lcm so every slice
        agrees on S (window lengths line up — the run would deadlock
        on a mismatch)."""
        hub_dict, spoke_dicts = farmer_dicts(
            opt_overrides={"telemetry": True})
        ws = MPMDWheel(hub_dict, spoke_dicts, lockstep=True)
        ws.spin()
        assert ws.spcomm.opt.batch.num_scens % ws.plan.pad_multiple() == 0
        assert np.isfinite(ws.BestOuterBound)
        assert np.isfinite(ws.BestInnerBound)
        assert ws.hub_overlap_fraction == 0.0   # nothing overlaps

    def test_missing_batch_rejected(self):
        hub_dict, spoke_dicts = farmer_dicts()
        hub_dict = dict(hub_dict,
                        opt_kwargs={k: v
                                    for k, v in
                                    hub_dict["opt_kwargs"].items()
                                    if k != "batch"})
        with pytest.raises(RuntimeError, match="opt_kwargs\\['batch'\\]"):
            MPMDWheel(hub_dict, spoke_dicts).spin()


@pytest.mark.chaos
class TestSliceSupervision:
    def test_crash_restart_then_prune_parity(self, fresh_telemetry):
        """An injected crash in the Lagrangian slice restarts the
        slice thread (fresh chaos schedule, like a respawned process),
        crashes again, exhausts the budget, and prunes through the
        SAME report_spoke_failure path the threaded/multiproc wheels
        use (test_resilience.py parity) — while the xhat slice and the
        hub still finish the run."""
        hub_dict, spoke_dicts = farmer_dicts(
            spoke_chaos={"crash_at_step": 1},
            opt_overrides={"telemetry": True},
            hub_opts={"spoke_max_restarts": 1,
                      "spoke_restart_backoff": 0.01,
                      "spoke_restart_backoff_cap": 0.02,
                      "supervise_interval": 0.01})
        ws = MPMDWheel(hub_dict, spoke_dicts)
        ws.spin()
        sup = ws.supervisor
        assert sup.spoke_restarts == 1
        assert sup.spokes_failed == 1
        # both incarnations reported their exits
        assert [r["incarnation"] for r in sup.exit_reports] == [0, 1]
        assert all("injected spoke crash" in r["error"]
                   for r in sup.exit_reports)
        hub = ws.spcomm
        assert len(hub.failed_spokes) == 1
        name, msg = hub.failed_spokes[0]
        assert name == "LagrangianOuterBound"
        assert "injected spoke crash" in msg and "1 restart" in msg
        # the healthy inner slice still closed the wheel
        assert np.isfinite(ws.BestInnerBound)
        c = telemetry.wheel_counters()
        assert c["wheel_slice_restarts"] == 1
        assert c["wheel_slices_failed"] == 1


def _top_level_import_roots(path):
    """Root module name of every TOP-LEVEL import statement (the
    test_streaming.py laziness-guard idiom): body-level only, so
    function-local lazy imports stay allowed."""
    tree = ast.parse(open(path).read())
    roots = []
    for node in tree.body:
        if isinstance(node, ast.Import):
            roots += [a.name.split(".")[0] for a in node.names]
        elif isinstance(node, ast.ImportFrom):
            if node.module:
                roots.append(node.module.split(".")[0])
            else:                     # "from . import x" — the names
                roots += [a.name.split(".")[0] for a in node.names]
    return roots


class TestImportLayering:
    """Satellite: the dependency direction is cylinders <- mpmd (via
    the backend registry), never cylinders -> mpmd; and mpmd itself
    must not touch jax (or the jax-importing ir/parallel layers) at
    import time."""

    def test_cylinders_never_import_mpmd(self):
        cyl_dir = os.path.join(PKG_ROOT, "cylinders")
        for fn in sorted(os.listdir(cyl_dir)):
            if not fn.endswith(".py"):
                continue
            tree = ast.parse(open(os.path.join(cyl_dir, fn)).read())
            for node in ast.walk(tree):   # ANY import, even lazy ones
                if isinstance(node, ast.Import):
                    for a in node.names:
                        assert "mpmd" not in a.name.split("."), \
                            f"cylinders/{fn} imports mpmd"
                elif isinstance(node, ast.ImportFrom):
                    mod = node.module or ""
                    assert "mpmd" not in mod.split("."), \
                        f"cylinders/{fn} imports from mpmd"
                    for a in node.names:
                        assert a.name != "mpmd", \
                            f"cylinders/{fn} imports mpmd"

    @pytest.mark.parametrize("fn", ["__init__.py", "collective.py",
                                    "exchange.py",
                                    "reslice.py", "slice_plan.py",
                                    "wheel.py"])
    def test_mpmd_keeps_jax_lazy(self, fn):
        roots = _top_level_import_roots(os.path.join(PKG_ROOT, "mpmd", fn))
        for forbidden in ("jax", "ir", "parallel"):
            assert forbidden not in roots, \
                f"mpmd/{fn} imports {forbidden} at module top level"

    def test_importing_mpmd_does_not_initialize_jax(self):
        """The authoritative runtime check for the AST guard: a fresh
        interpreter importing mpisppy_tpu.mpmd must not pull jax."""
        code = ("import mpisppy_tpu.mpmd, sys; "
                "assert 'jax' not in sys.modules, 'mpmd imported jax'")
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        r = subprocess.run([sys.executable, "-c", code],
                           cwd=os.path.dirname(PKG_ROOT),
                           env=env, capture_output=True, text=True)
        assert r.returncode == 0, r.stderr


class TestWheelCountersOff:
    def test_stable_zero_keys_when_disabled(self):
        telemetry.reset()
        try:
            c = telemetry.wheel_counters()
            assert c["wheel_exchange_writes"] == 0
            assert c["wheel_n_slices"] == 0
            assert c["wheel_exchange_latency_seconds"] == 0.0
            assert c["wheel_slice_bounds"] == {}
        finally:
            telemetry.reset()

"""Real jax.distributed multi-host path (2 processes x 2 CPU devices):
the consensus psum crosses a PROCESS boundary — the single-box stand-in
for the reference's inter-node MPI traffic (reference
spin_the_wheel.py:219-237 rank grid over cluster nodes; SURVEY §2.3).

Spawns tests/multihost_worker.py twice with a shared coordinator; both
processes run farmer PH on the GLOBAL 4-device mesh and print their
trajectory.  Asserts (a) the two processes agree exactly (they execute
one SPMD program), and (b) the numbers match a plain single-process
run of the same instance (the mesh is invisible to the math).
"""

import json
import os
import socket
import subprocess
import sys

import numpy as np
import pytest


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


@pytest.fixture(scope="module")
def multihost_results():
    port = _free_port()
    coord = f"127.0.0.1:{port}"
    worker = os.path.join(os.path.dirname(__file__),
                          "multihost_worker.py")
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
    procs = [subprocess.Popen(
        [sys.executable, worker, coord, "2", str(pid)],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        text=True, env=env) for pid in range(2)]
    outs = []
    for p in procs:
        try:
            out, err = p.communicate(timeout=600)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        assert p.returncode == 0, f"worker failed:\n{err[-3000:]}"
        line = [ln for ln in out.splitlines()
                if ln.startswith("RESULT ")][-1]
        outs.append(json.loads(line[len("RESULT "):]))
    return outs


def test_processes_agree(multihost_results):
    a, b = multihost_results
    assert a["process_count"] == b["process_count"] == 2
    assert a["devices"] == b["devices"] == 4
    # one SPMD program: identical numbers on both controllers
    assert a["trivial_bound"] == pytest.approx(b["trivial_bound"],
                                               rel=1e-12)
    np.testing.assert_allclose(a["convs"], b["convs"], rtol=1e-10)
    assert a["lagrangian"] == pytest.approx(b["lagrangian"], rel=1e-12)
    np.testing.assert_allclose(a["xbar0"], b["xbar0"], rtol=1e-10)


def test_matches_single_process(multihost_results):
    from mpisppy_tpu.models import farmer
    from mpisppy_tpu.opt.ph import PH

    a = multihost_results[0]
    S = 8
    ph = PH({"defaultPHrho": 1.0, "PHIterLimit": 5, "convthresh": 0.0,
             "pdhg_eps": 1e-7, "iter0_certify": False},
            [f"scen{i}" for i in range(S)],
            batch=farmer.build_batch(S))
    ph.Iter0()
    convs = [ph.ph_iteration() for _ in range(5)]
    assert a["trivial_bound"] == pytest.approx(ph.trivial_bound,
                                               rel=1e-8)
    np.testing.assert_allclose(a["convs"], convs, rtol=1e-5, atol=1e-9)
    assert a["lagrangian"] == pytest.approx(ph.lagrangian_bound(),
                                            rel=1e-6)

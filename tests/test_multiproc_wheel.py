"""Separate-process cylinder deployment test — the analog of the
reference's multi-rank `mpiexec` cylinder runs (reference
spin_the_wheel.py:219-237 launches hub + spokes as distinct MPI
programs over RMA windows; here they are distinct OS processes over the
C++ mmap seqlock exchange, runtime/exchange.cpp).

Asserts the end-to-end contract: the hub PH process and two spoke
processes (Lagrangian outer bound, xhat-shuffle inner bound) exchange
through the window files, the children exit cleanly on the kill signal,
and the resulting bounds BRACKET the independently computed EF optimum.
"""

import numpy as np
import pytest

from efcheck import ef_linprog
from mpisppy_tpu.cylinders.hub import PHHub
from mpisppy_tpu.cylinders.lagrangian_bounder import LagrangianOuterBound
from mpisppy_tpu.cylinders.xhatshufflelooper_bounder import (
    XhatShuffleInnerBound,
)
from mpisppy_tpu.models import farmer
from mpisppy_tpu.opt.ph import PH
from mpisppy_tpu.runtime import native
from mpisppy_tpu.spin_the_wheel import WheelSpinner
from mpisppy_tpu.utils.xhat_eval import Xhat_Eval

S = 6
OPTS = {"defaultPHrho": 1.0, "PHIterLimit": 25, "convthresh": 0.0,
        "pdhg_eps": 1e-7, "pdhg_max_iters": 20000}


@pytest.mark.skipif(not native.available(),
                    reason="native exchange library unavailable")
def test_multiproc_wheel_farmer():
    names = [f"scen{i}" for i in range(S)]
    b = farmer.build_batch(S)
    batch_spec = {"module": "mpisppy_tpu.models.farmer",
                  "builder": "build_batch",
                  "kwargs": {"num_scens": S}}
    hub_dict = {
        "hub_class": PHHub,
        "hub_kwargs": {"options": {"rel_gap": 1e-4}},
        "opt_class": PH,
        "opt_kwargs": {"options": dict(OPTS), "all_scenario_names": names,
                       "batch": b},
    }
    spoke_dicts = [
        {"spoke_class": LagrangianOuterBound, "opt_class": PH,
         "spoke_kwargs": {"options": {}},
         "opt_kwargs": {"options": dict(OPTS),
                        "all_scenario_names": names},
         "proc": {"batch": batch_spec}},
        {"spoke_class": XhatShuffleInnerBound, "opt_class": Xhat_Eval,
         "spoke_kwargs": {"options": {}},
         "opt_kwargs": {"options": dict(OPTS),
                        "all_scenario_names": names},
         "proc": {"batch": batch_spec}},
    ]
    ws = WheelSpinner(hub_dict, spoke_dicts, mode="multiproc").spin()

    # children exited cleanly on the kill signal
    for h in ws.spcomm.spokes:
        assert h.proc is not None and h.proc.returncode == 0

    ib, ob = ws.BestInnerBound, ws.BestOuterBound
    assert np.isfinite(ob), "no outer bound crossed the process boundary"
    ref, _ = ef_linprog(b, n_real=S)
    # bounds must bracket the EF optimum (tolerances: solver eps scale)
    tol = 1e-4 * abs(ref)
    assert ob <= ref + tol
    if np.isfinite(ib):
        assert ib >= ref - tol
        # with both spokes alive the gap should have closed well
        assert (ib - ob) / abs(ref) < 0.05

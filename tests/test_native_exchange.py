"""Native (C++) exchange-layer tests — the analog of the reference's
mpi_one_sided_test.py RMA correctness probe (README install gate)."""

import threading

import numpy as np
import pytest

from mpisppy_tpu.runtime import available

pytestmark = pytest.mark.skipif(
    not available(), reason="no C++ toolchain for the native exchange")


def make_window(n, path=None):
    from mpisppy_tpu.runtime import NativeWindow
    return NativeWindow(n, path=path)


def test_write_read_roundtrip():
    w = make_window(8)
    data = np.arange(8.0)
    wid = w.write(data)
    assert wid == 1
    out, rid = w.read()
    assert rid == 1
    assert np.array_equal(out, data)
    wid2 = w.write(data * 2)
    assert wid2 == 2
    out2, rid2 = w.read()
    assert np.array_equal(out2, data * 2)


def test_kill_signal():
    w = make_window(4)
    w.write(np.ones(4))
    w.send_kill()
    assert w.write_id == -1


def test_explicit_write_id():
    w = make_window(2)
    assert w.write(np.zeros(2), write_id=7) == 7
    _, rid = w.read()
    assert rid == 7


def test_length_mismatch_raises():
    w = make_window(3)
    with pytest.raises(ValueError):
        w.write(np.zeros(5))


def test_mmap_file_cross_handle(tmp_path):
    # two handles on the same file see each other's writes — the
    # cross-process layout exercised in-process
    p = str(tmp_path / "win.bin")
    a = make_window(6, path=p)
    b = make_window(6, path=p)
    a.write(np.full(6, 3.25))
    out, wid = b.read()
    assert wid == 1
    assert np.all(out == 3.25)
    b.send_kill()
    assert a.write_id == -1


def test_seqlock_no_torn_reads():
    """Writer spins constant-valued payloads; every read snapshot must
    be internally consistent (all elements equal) — the property the
    reference's write_id consensus protocol provides."""
    n = 1024
    w = make_window(n)
    w.write(np.zeros(n))
    stop = threading.Event()
    torn = []

    def writer():
        k = 0
        while not stop.is_set():
            k += 1
            w.write(np.full(n, float(k)))

    def reader():
        for _ in range(3000):
            out, wid = w.read()
            if not np.all(out == out[0]):
                torn.append(out.copy())
                return

    t = threading.Thread(target=writer, daemon=True)
    t.start()
    rs = [threading.Thread(target=reader) for _ in range(3)]
    for r in rs:
        r.start()
    for r in rs:
        r.join()
    stop.set()
    t.join(timeout=5)
    assert not torn, f"torn read detected: {torn[0][:8]}..."


def test_threaded_wheel_with_native_backend():
    """Full hub+spoke run over the native windows."""
    from mpisppy_tpu.cylinders.hub import PHHub
    from mpisppy_tpu.cylinders.lagrangian_bounder import (
        LagrangianOuterBound,
    )
    from mpisppy_tpu.models import farmer
    from mpisppy_tpu.opt.ph import PH
    from mpisppy_tpu.spin_the_wheel import WheelSpinner

    names = [f"scen{i}" for i in range(3)]
    opts = {"defaultPHrho": 1.0, "PHIterLimit": 15, "convthresh": 1e-5,
            "pdhg_eps": 1e-7}
    hub = {"hub_class": PHHub, "opt_class": PH,
           "hub_kwargs": {"options": {"rel_gap": 1e-3,
                                      "window_backend": "native"}},
           "opt_kwargs": {"options": opts, "all_scenario_names": names,
                          "batch": farmer.build_batch(3)}}
    spoke = {"spoke_class": LagrangianOuterBound, "opt_class": PH,
             "opt_kwargs": {"options": dict(opts),
                            "all_scenario_names": names}}
    ws = WheelSpinner(hub, [spoke], mode="threads").spin()
    assert ws.BestOuterBound <= -108388.0
    assert ws.BestOuterBound >= -115406.0

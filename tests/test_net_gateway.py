"""Network front-door tests (ISSUE 19): the serve/net wire protocol,
Gateway/Client over real loopback sockets, AOT executable persistence
(`MPISPPY_TPU_COMPILE_CACHE_DIR`), and the zero-downtime rolling
restart — plus the package-hygiene and import-laziness guards.

All tests are tier-1 (`net` marker, no `slow`): farmer-sized batches,
and every service uses the SAME solver config so the process-shared
jit registries amortize compiles across tests (the test_serve.py
discipline)."""

import ast
import json
import os
import pathlib
import socket
import struct
import subprocess
import sys
import threading
import time
import zlib

import numpy as np
import pytest

from mpisppy_tpu import telemetry
from mpisppy_tpu.models import farmer
from mpisppy_tpu.opt.ph import PH
from mpisppy_tpu.serve import compile_cache as cc
from mpisppy_tpu.serve.net import Client, ClientError, Gateway
from mpisppy_tpu.serve.net import protocol as P

pytestmark = pytest.mark.net

REPO = pathlib.Path(__file__).resolve().parents[1]

GOLDEN_OPTS = {"defaultPHrho": 1.0, "PHIterLimit": 200,
               "convthresh": 1e-5, "pdhg_eps": 1e-7}
FAST_OPTS = {"defaultPHrho": 1.0, "PHIterLimit": 4, "convthresh": 1e-4,
             "pdhg_eps": 1e-7, "superstep_eps": 1e-5}

# quick-loop gateway/router config: tight ticks, singleton groups
# (bitwise path), fast supervision — the test_serve_router timings
GW_OPTS = {
    "serve_replicas": 1,
    "serve_max_batch": 1,
    "serve_restart_backoff": 0.01,
    "serve_restart_backoff_cap": 0.05,
    "router_tick": 0.01,
    "router_probe_interval": 0.02,
    "router_drain_deadline": 0.3,
}


@pytest.fixture
def fresh_telemetry():
    prev = telemetry._active
    telemetry.reset()
    yield
    telemetry._active = prev


def _gateway(extra=None, **kw):
    o = dict(GW_OPTS)
    o.update(extra or {})
    return Gateway(o, **kw).start()


def _sockpair():
    a, b = socket.socketpair()
    a.settimeout(5.0)
    b.settimeout(5.0)
    return a, b


# -- wire protocol ---------------------------------------------------------

def test_protocol_roundtrip_over_socketpair():
    a, b = _sockpair()
    try:
        payload = os.urandom(1 << 12)
        n = P.write_message(a, {"kind": "request", "verb": "health",
                                "token": "t"}, payload)
        sizes = []
        hdr, got = P.read_message(b, on_bytes=sizes.append)
        assert hdr["verb"] == "health" and hdr["proto"] == P.PROTO_FORMAT
        assert got == payload
        assert sizes == [n]            # exact byte accounting
    finally:
        a.close(); b.close()


def test_protocol_clean_eof_vs_torn_frame():
    a, b = _sockpair()
    a.close()
    assert P.read_message(b) == (None, None)      # clean EOF
    b.close()
    a, b = _sockpair()
    try:
        data = P.pack_message({"kind": "request", "verb": "poll"})
        a.sendall(data[: len(data) // 2])
        a.close()                                  # EOF mid-message
        with pytest.raises(P.ProtocolError):
            P.read_message(b)
    finally:
        b.close()


@pytest.mark.parametrize("mutate", ["magic", "crc", "header"])
def test_protocol_rejects_corruption(mutate):
    data = bytearray(P.pack_message(
        {"kind": "request", "verb": "poll"}, b"payload-bytes"))
    if mutate == "magic":
        data[0] ^= 0xFF
    elif mutate == "crc":
        data[-1] ^= 0xFF
    else:
        data[len(P.MAGIC) + 4] ^= 0xFF             # first header byte
    a, b = _sockpair()
    try:
        a.sendall(bytes(data)); a.close()
        with pytest.raises(P.ProtocolError):
            P.read_message(b)
    finally:
        b.close()


def test_protocol_payload_cap_enforced():
    a, b = _sockpair()
    try:
        a.sendall(P.pack_message({"kind": "request", "verb": "submit"},
                                 b"x" * 4096))
        a.close()
        with pytest.raises(P.ProtocolError, match="exceeds cap"):
            P.read_message(b, max_payload=1024)
    finally:
        b.close()


def test_batch_codec_preserves_arrays_and_treedef():
    """decode(encode(batch)) is bit-exact AND treedef-identical to the
    fresh batch — aux metadata (stage_of, name tuples) must come back
    in canonical Python form or every jit cache downstream of a wire
    batch breaks on treedef comparison (the stage_of regression)."""
    import jax

    b = farmer.build_batch(3)
    rt = P.decode_batch(P.encode_batch(b))
    assert jax.tree_util.tree_structure((b,)) \
        == jax.tree_util.tree_structure((rt,))
    for l1, l2 in zip(jax.tree_util.tree_leaves(b),
                      jax.tree_util.tree_leaves(rt)):
        assert np.array_equal(np.asarray(l1), np.asarray(l2))
    assert rt.tree.stage_of == b.tree.stage_of
    assert isinstance(rt.tree.stage_of, tuple)


def test_result_codec_is_bitwise():
    res = {"status": "ok", "conv": 1.2345678901234567e-7,
           "eobj": -108390.0703125, "iterations": 9,
           "xbar": np.array([170.0, 80.0, 250.0]),
           "reason": None}
    hdr, payload = P.encode_result(res)
    out = P.decode_result(json.loads(json.dumps(hdr)), payload)
    assert out["conv"] == res["conv"]              # bitwise via repr
    assert out["eobj"] == res["eobj"]
    assert np.array_equal(out["xbar"], res["xbar"])
    assert out["status"] == "ok" and out["reason"] is None


def test_decode_batch_never_unpickles_hostile_payload(tmp_path):
    """REVIEW fix (high): the wire codec must never unpickle
    network-supplied bytes.  A crafted object array whose __reduce__
    has a side effect is a decode ERROR (allow_pickle=False), and the
    side effect never fires — at the protocol layer AND through a live
    gateway (mapped to bad_payload, connection stays usable)."""
    import io as _io
    import os as _os
    marker = tmp_path / "pwned"

    class Evil:
        def __reduce__(self):
            return (_os.mkdir, (str(marker),))

    buf = _io.BytesIO()
    np.savez(buf, c=np.array([Evil()], dtype=object))
    hostile = buf.getvalue()
    with pytest.raises(Exception):
        P.decode_batch(hostile)
    assert not marker.exists(), "pickle executed during decode!"

    gw = _gateway()
    try:
        with socket.create_connection(gw.address, timeout=5) as s:
            s.settimeout(10.0)
            P.write_message(s, {"kind": "request", "verb": "submit",
                                "token": ""}, hostile)
            hdr, _ = P.read_message(s)
            assert hdr["ok"] is False
            assert hdr["error_code"] == P.E_BAD_PAYLOAD
            # decode failed structurally; nothing executed
            assert not marker.exists()
            # the frame itself was well-formed: stream stays usable
            P.write_message(s, {"kind": "request", "verb": "health",
                                "token": ""})
            hdr, _ = P.read_message(s)
            assert hdr["ok"] is True
    finally:
        gw.shutdown()


def test_batch_codec_output_is_pickle_free():
    """Every array in an encoded batch payload loads under
    allow_pickle=False — including farmer's model_meta, whose tuple of
    index arrays rides the tagged-JSON sidecar, not a pickle."""
    import io as _io
    data = P.encode_batch(farmer.build_batch(3))
    z = np.load(_io.BytesIO(data), allow_pickle=False)
    for k in z.files:
        np.asarray(z[k])               # raises if pickle were needed


def test_encode_result_refuses_object_arrays():
    with pytest.raises(TypeError, match="object-dtype"):
        P.encode_result({"status": "ok",
                         "bad": np.array([{"a": 1}], dtype=object)})


def test_error_code_matrix_covers_protocol_and_router():
    for code in (P.E_BAD_FRAME, P.E_BAD_VERB, P.E_UNAUTHORIZED,
                 P.E_UNKNOWN_HANDLE, P.E_DRAINING, "over_quota",
                 "brownout_shed", "quarantined", "timeout"):
        assert code in P.ERROR_CODES


# -- layering guards (AST + fresh interpreter + package hygiene) ----------

def test_net_imports_jax_only_lazily():
    """serve/net/ must be embeddable in a client process that never
    initializes a backend: no module-level jax/mpmd/heavy imports."""
    net_dir = REPO / "mpisppy_tpu" / "serve" / "net"
    for fname in sorted(net_dir.glob("*.py")):
        mods = set()
        for node in ast.parse(fname.read_text()).body:
            if isinstance(node, ast.Import):
                mods.update(a.name for a in node.names)
            elif isinstance(node, ast.ImportFrom):
                mods.add(node.module or "")
        bad = {m for m in mods if m == "jax" or m.startswith("jax.")
               or "mpmd" in m or ".service" in m
               or ".compile_cache" in m or m.endswith("phbase")}
        assert not bad, f"{fname.name} module-level imports: {bad}"


def test_net_import_is_jax_free_in_fresh_process():
    code = ("import sys\n"
            "import mpisppy_tpu.serve.net\n"
            "import mpisppy_tpu.serve.net.gateway\n"
            "import mpisppy_tpu.serve.net.client\n"
            "import mpisppy_tpu.serve.net.protocol\n"
            "sys.exit(1 if 'jax' in sys.modules else 0)\n")
    r = subprocess.run([sys.executable, "-c", code],
                       capture_output=True, text=True, timeout=120)
    assert r.returncode == 0, r.stderr[-2000:]


def test_package_hygiene_no_orphan_modules():
    """Every mpisppy_tpu package directory has an __init__.py, and no
    __pycache__ holds a compiled module whose source .py is gone —
    orphaned .pyc files are shadow-importable and resurrect reverted
    code (the serve/net precedent this PR cleans up)."""
    root = REPO / "mpisppy_tpu"
    for d in sorted(p for p in root.rglob("*") if p.is_dir()):
        if d.name == "__pycache__":
            for pyc in d.glob("*.pyc"):
                src = d.parent / (pyc.name.split(".")[0] + ".py")
                assert src.exists(), (
                    f"orphaned compiled module {pyc} (no {src.name})")
        elif list(d.glob("*.py")):
            assert (d / "__init__.py").exists(), \
                f"package dir {d} lacks __init__.py"


# -- gateway: auth, error codes, counters ----------------------------------

def test_gateway_bearer_token_auth(fresh_telemetry):
    gw = _gateway({"gateway_tokens": {"sesame": "tenant-a"},
                   "telemetry": True})
    try:
        with Client(*gw.address, token="wrong") as c:
            with pytest.raises(ClientError) as exc:
                c.health()
            assert exc.value.code == P.E_UNAUTHORIZED
        with Client(*gw.address, token="sesame") as c:
            h = c.health()
            assert "counts" in h["gateway"]
        by_code = gw.counts["rejects_by_code"]
        assert by_code[P.E_UNAUTHORIZED] == 1
    finally:
        gw.shutdown()


def test_gateway_bad_verb_and_unknown_handle():
    gw = _gateway()
    try:
        with socket.create_connection(gw.address, timeout=5) as s:
            s.settimeout(5.0)
            P.write_message(s, {"kind": "request", "verb": "explode"})
            hdr, _ = P.read_message(s)
            assert hdr["ok"] is False
            assert hdr["error_code"] == P.E_BAD_VERB
        with Client(*gw.address) as c:
            from mpisppy_tpu.serve.net.client import NetHandle
            ghost = NetHandle(999999, "ghost")
            with pytest.raises(ClientError) as exc:
                c.poll(ghost)
            assert exc.value.code == P.E_UNKNOWN_HANDLE
            with pytest.raises(ClientError) as exc:
                c.result(ghost, timeout=1)
            assert exc.value.code == P.E_UNKNOWN_HANDLE
    finally:
        gw.shutdown()


def test_gateway_maps_router_reject_to_wire_code():
    """A structured router reject (over_quota via an empty token
    bucket) surfaces as the SAME code on the wire — one error-code
    namespace across both layers."""
    gw = _gateway({"router_tenant_rate": 0.001,
                   "router_tenant_burst": 1})
    try:
        with Client(*gw.address) as c:
            batch = farmer.build_batch(3)
            h1 = c.submit(batch, FAST_OPTS, model="farmer")
            h2 = c.submit(batch, FAST_OPTS, model="farmer")
            # bucket depth 1: the second submit is rejected at admission
            r2 = c.result(h2, timeout=10)
            assert r2["status"] == "rejected"
            assert r2["reason"] == "over_quota"
            r1 = c.result(h1, timeout=300)
            assert r1["status"] == "ok"
            assert "over_quota" in gw.counts["rejects_by_code"]
    finally:
        gw.shutdown()


def test_gateway_drain_rejects_new_admission():
    gw = _gateway()
    try:
        with Client(*gw.address) as c:
            out = c.drain(deadline=0.2)
            assert out["drained_open"] == 0
            with pytest.raises(ClientError) as exc:
                c.submit(farmer.build_batch(3), FAST_OPTS)
            assert exc.value.code == P.E_DRAINING
            # health keeps flowing while draining
            assert c.health()["gateway"]["draining"] is True
    finally:
        gw.shutdown()


def test_gateway_open_mode_requires_loopback():
    """REVIEW fix: open (unauthenticated) mode + a non-loopback bind
    would hand every LAN peer tenant "default" — refused at
    construction unless explicitly overridden or authenticated."""
    with pytest.raises(ValueError, match="non-loopback"):
        Gateway(dict(GW_OPTS), host="0.0.0.0")
    # authenticated, or explicitly overridden: constructible
    Gateway({**GW_OPTS, "gateway_tokens": {"t": "a"}}, host="0.0.0.0")
    Gateway({**GW_OPTS, "gateway_open_non_loopback": True},
            host="0.0.0.0")
    Gateway(dict(GW_OPTS), host="127.0.0.1")   # loopback: fine open


def test_gateway_admin_tokens_gate_drain_and_roll():
    """REVIEW fix: drain/roll are fleet-lifecycle verbs — a tenant
    bearer token must not drain admission or restart the fleet.  With
    gateway_admin_tokens set, only those tokens pass; a configured
    deployment WITHOUT an admin table refuses the verbs entirely."""
    gw = _gateway({"gateway_tokens": {"sesame": "tenant-a"},
                   "gateway_admin_tokens": ["root-tok"]})
    try:
        with Client(*gw.address, token="sesame") as c:
            for call in (lambda: c.drain(deadline=0.1),
                         lambda: c.roll(timeout=10)):
                with pytest.raises(ClientError) as exc:
                    call()
                assert exc.value.code == P.E_UNAUTHORIZED
        assert gw.counts.get("drains", 0) == 0
        assert gw.rolls == 0
        with Client(*gw.address, token="root-tok") as c:
            assert c.drain(deadline=0.1)["drained_open"] == 0
        assert gw.counts["drains"] == 1
    finally:
        gw.shutdown()
    # authenticated mode with NO admin table: no wire path to drain
    gw = _gateway({"gateway_tokens": {"sesame": "tenant-a"}})
    try:
        with Client(*gw.address, token="sesame") as c:
            with pytest.raises(ClientError) as exc:
                c.drain(deadline=0.1)
            assert exc.value.code == P.E_UNAUTHORIZED
    finally:
        gw.shutdown()


def test_gateway_bad_frame_counted_once_and_answered():
    """REVIEW fix: a torn frame is answered with ONE well-formed
    bad_frame error frame (packed, not a raw dict) and counted exactly
    once before the gateway closes the poisoned stream."""
    gw = _gateway()
    try:
        with socket.create_connection(gw.address, timeout=5) as s:
            s.settimeout(5.0)
            # exactly magic+len sized so the server consumes it all
            # (no unread bytes -> clean FIN, not RST, on close)
            s.sendall(b"GARBAGE!" + b"\x00" * 4)
            hdr, _ = P.read_message(s)
            assert hdr["ok"] is False
            assert hdr["error_code"] == P.E_BAD_FRAME
            assert P.read_message(s) == (None, None)   # then closed
        assert gw.counts["rejects_by_code"][P.E_BAD_FRAME] == 1
    finally:
        gw.shutdown()


def test_gateway_conn_threads_pruned():
    """REVIEW fix: finished connection handlers are pruned from the
    tracking list, so the gateway doesn't grow one Thread object per
    connection ever accepted."""
    gw = _gateway()
    try:
        for _ in range(5):
            with Client(*gw.address) as c:
                c.health()
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            with Client(*gw.address) as c:
                c.health()
            with gw._lock:
                n = len(gw._conn_threads)
            if n <= 2:
                break
            time.sleep(0.05)
        assert n <= 2, f"{n} connection threads still tracked"
    finally:
        gw.shutdown()


def test_gateway_counters_stable_keys(fresh_telemetry):
    """telemetry.gateway_counters() mirrors router_counters(): stable
    keys with telemetry off (zeros) and real values with it on."""
    cold = telemetry.gateway_counters()
    expected = {"gateway_requests", "gateway_bytes_in",
                "gateway_bytes_out", "gateway_rolls", "gateway_drains",
                "cache_aot_loads", "cache_aot_load_failures",
                "cache_aot_saves", "cache_aot_export_failures",
                "cache_aot_prewarm_hits", "cache_aot_evictions",
                "client_reconnects", "client_resends",
                "client_idle_reaped",
                "gateway_active_connections", "gateway_rejects_by_code"}
    assert set(cold) == expected
    assert all(v == 0 for k, v in cold.items()
               if k != "gateway_rejects_by_code")
    assert cold["gateway_rejects_by_code"] == {}

    telemetry.reset()
    gw = _gateway({"telemetry": True,
                   "gateway_tokens": {"good": "t"}})
    try:
        with Client(*gw.address, token="good") as c:
            c.health()
        with Client(*gw.address, token="bad") as c:
            with pytest.raises(ClientError):
                c.health()
        hot = telemetry.gateway_counters()
        assert hot["gateway_requests"] == 2
        assert hot["gateway_bytes_in"] > 0
        assert hot["gateway_bytes_out"] > 0
        assert hot["gateway_rejects_by_code"] == {P.E_UNAUTHORIZED: 1}
        assert set(hot) == expected
    finally:
        gw.shutdown()


def test_client_reconnects_with_capped_jitter_backoff():
    """Kill the connection under the client: the next request
    reconnects (counted) and succeeds; a dead gateway exhausts the
    reconnect budget with ConnectionError, in bounded time."""
    gw = _gateway()
    try:
        c = Client(*gw.address, reconnect_backoff=0.01,
                   reconnect_cap=0.05, max_reconnects=3)
        assert "counts" in c.health()["gateway"]
        c._sock.close()                 # torn transport under the hood
        assert "counts" in c.health()["gateway"]
        assert c.reconnects >= 1
        c.close()
    finally:
        gw.shutdown()
    t0 = time.monotonic()
    dead = Client(*gw.address, connect_timeout=0.2,
                  reconnect_backoff=0.01, reconnect_cap=0.05,
                  max_reconnects=2)
    with pytest.raises(ConnectionError):
        dead.health()
    assert time.monotonic() - t0 < 30.0


@pytest.mark.chaos
def test_client_timeout_none_survives_slow_solve():
    """REVIEW fix: with timeout=None the SERVER decides when to answer
    (up to gateway_result_cap), so the client stretches its socket
    wait to result_cap + grace.  A solve slower than request_timeout
    must complete on the ORIGINAL connection — not trip
    socket.timeout, tear the stream, and burn the reconnect budget on
    a healthy request (stranding gateway threads on dead sockets)."""
    gw = _gateway({"chaos": {"slow_replica": 1.0}})
    try:
        with Client(*gw.address, request_timeout=0.3, result_cap=60.0,
                    max_reconnects=2) as c:
            t0 = time.monotonic()
            res = c.solve(farmer.build_batch(3), FAST_OPTS,
                          model="farmer")          # timeout=None
            assert res["status"] == "ok"
            assert time.monotonic() - t0 > 0.3     # outlived the old cap
            assert c.reconnects == 0, \
                "slow solve misread as transport failure"
    finally:
        gw.shutdown()


# -- e2e over a real socket ------------------------------------------------

def test_client_solve_bitwise_equals_ph_main():
    """ISSUE 19 acceptance: a Client.solve batch=1 result over a real
    socket is bitwise-equal to PH.ph_main on farmer — npz arrays are
    lossless and JSON doubles round-trip via shortest repr, so the
    wire adds NOTHING to the serve parity guarantee."""
    names = [f"scen{i}" for i in range(3)]
    ph = PH(dict(GOLDEN_OPTS), names, batch=farmer.build_batch(3))
    conv, eobj, trivial = ph.ph_main()

    gw = _gateway()
    try:
        with Client(*gw.address) as c:
            res = c.solve(farmer.build_batch(3), GOLDEN_OPTS,
                          scenario_names=names, model="farmer",
                          timeout=300)
        assert res["status"] == "ok"
        assert res["conv"] == conv
        assert res["eobj"] == eobj
        assert res["trivial_bound"] == trivial
        assert np.array_equal(res["xbar"], np.asarray(ph.root_xbar()))
        # goldens (tests/test_ph_farmer.py values)
        assert abs(res["eobj"] - (-108390)) < 5
        assert np.allclose(res["xbar"], [170.0, 80.0, 250.0], atol=1.0)
    finally:
        gw.shutdown()


@pytest.mark.chaos
def test_eight_concurrent_clients_chaos_exactly_once():
    """ISSUE 19 acceptance: 8 concurrent socket clients against a
    2-replica set with replica_crash + slow_replica + poison_request
    armed.  Every request resolves exactly once per idempotency key
    (a duplicate submit returns the SAME handle id), the poison
    request quarantines without collateral, p99 stays finite."""
    names = [f"scen{i}" for i in range(3)]
    ph = PH(dict(FAST_OPTS), names, batch=farmer.build_batch(3))
    g_conv, g_eobj, g_trivial = ph.ph_main()

    gw = _gateway({
        "serve_replicas": 2,
        "router_hedge_threshold": 1.0,
        "router_breaker_backoff": 0.05,
        "router_breaker_backoff_cap": 0.5,
        "chaos": {"replica_crash": 1, "slow_replica": 0.02,
                  "poison_request": True, "chaos_replica": 0},
    })
    results, errors = {}, []
    lock = threading.Lock()

    def one_client(i):
        try:
            opts = dict(FAST_OPTS)
            if i == 3:
                opts["chaos_poison"] = True
            with Client(*gw.address, jitter_seed=i) as c:
                res = c.solve(farmer.build_batch(3), opts,
                              scenario_names=names, model="farmer",
                              idempotency_key=f"key{i}", timeout=300)
                # duplicate submit with the SAME key: the router's
                # idempotency table returns the original handle
                dup = c.submit(farmer.build_batch(3), opts,
                               scenario_names=names, model="farmer",
                               idempotency_key=f"key{i}")
                with lock:
                    results[i] = (res, dup.id)
        except Exception as exc:       # pragma: no cover - diagnostics
            with lock:
                errors.append((i, repr(exc)))

    try:
        threads = [threading.Thread(target=one_client, args=(i,))
                   for i in range(8)]
        t0 = time.monotonic()
        for t in threads:
            t.start()
        for t in threads:
            t.join(300)
        wall = time.monotonic() - t0
        assert not errors, errors
        assert len(results) == 8

        router = gw.router
        # exactly-once: 8 keys, 8 rids (dup submits resolved to the
        # SAME rid — nothing ran twice to completion)
        assert len(router._idempotency) == 8
        rids = {router._idempotency[f"key{i}"] for i in range(8)}
        assert len(rids) == 8
        for i, (res, dup_rid) in results.items():
            assert dup_rid == router._idempotency[f"key{i}"]
            if i == 3:
                assert res["status"] == "failed"
                assert "quarantined" in res["reason"]
            else:
                assert res["status"] == "ok", (i, res)
                assert res["conv"] == g_conv
                assert res["eobj"] == g_eobj
                assert res["trivial_bound"] == g_trivial

        # finite p99 under chaos; crash pruned only the targeted slot
        st = router.stats()
        assert st["p99"] is not None and np.isfinite(st["p99"])
        assert wall < 280.0
        assert st["counts"].get("quarantined", 0) == 1
        assert st["replica_restarts"] >= 1
    finally:
        gw.shutdown()


@pytest.mark.chaos
def test_roll_under_load_zero_failed_inflight(fresh_telemetry):
    """ISSUE 19 acceptance: Gateway.roll() under sustained client load
    replaces EVERY replica (each slot's incarnation advances) with
    zero failed in-flight requests, leaving a gateway.rolls counter
    and a per-slot roll_slot event trail."""
    telemetry.reset()
    gw = _gateway({"serve_replicas": 2, "telemetry": True})
    stop = threading.Event()
    outcomes, errors = [], []
    lock = threading.Lock()

    def load(i):
        try:
            with Client(*gw.address, jitter_seed=i) as c:
                k = 0
                while not stop.is_set():
                    res = c.solve(farmer.build_batch(3), FAST_OPTS,
                                  model="farmer",
                                  idempotency_key=f"load{i}-{k}",
                                  timeout=300)
                    with lock:
                        outcomes.append(res["status"])
                    k += 1
        except Exception as exc:       # pragma: no cover - diagnostics
            with lock:
                errors.append(repr(exc))

    try:
        threads = [threading.Thread(target=load, args=(i,))
                   for i in range(2)]
        for t in threads:
            t.start()
        # wait for traffic, then roll through both replicas
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            with lock:
                if outcomes:
                    break
            time.sleep(0.05)
        with Client(*gw.address) as c:
            rolled = c.roll(timeout=120)
        assert rolled == 2
        # keep load flowing a beat after the roll, then stop
        time.sleep(0.5)
        stop.set()
        for t in threads:
            t.join(120)
        assert not errors, errors
        assert outcomes and all(s == "ok" for s in outcomes), \
            [s for s in outcomes if s != "ok"]
        # every slot was replaced exactly once
        for slot in range(2):
            assert gw.router.replica_set[slot].incarnation == 1
        assert gw.rolls == 1
        assert gw.counts["rolls"] == 1
        assert telemetry.gateway_counters()["gateway_rolls"] == 1
        # the per-slot event trail
        ev = telemetry.get().registry.events("gateway.roll_slot")
        assert [e["slot"] for e in ev] == [0, 1]
        assert gw.router.counts.get("rolled_replicas") == 2
    finally:
        stop.set()
        gw.shutdown()


# -- AOT executable persistence --------------------------------------------

def _two_iter0_phs():
    phs = []
    for _ in range(2):
        ph = PH(dict(FAST_OPTS), ["s0", "s1", "s2"],
                batch=farmer.build_batch(3))
        ph.Iter0()
        phs.append(ph)
    return phs


def _run(exe, args):
    import jax
    out = exe(*args)
    jax.block_until_ready(out.conv)
    return out


def _leaves_equal(a, b):
    import jax
    la = jax.tree_util.tree_leaves(a)
    lb = jax.tree_util.tree_leaves(b)
    return len(la) == len(lb) and all(
        np.array_equal(np.asarray(x), np.asarray(y))
        for x, y in zip(la, lb))


def test_aot_persistence_warm_start_skips_trace(tmp_path, monkeypatch):
    """The tentpole measurement: first build traces + persists; a
    FRESH cache (a fresh replica / process restart stand-in) loads the
    artifact instead of re-tracing — counted, strictly faster, and
    bitwise identical."""
    from mpisppy_tpu.serve.service import stack_superstep_args

    monkeypatch.setenv("MPISPPY_TPU_COMPILE_CACHE_DIR", str(tmp_path))
    phs = _two_iter0_phs()
    args = stack_superstep_args(phs)

    cache1 = cc.CompileCache()
    t0 = time.monotonic()
    exe1 = cache1.get(phs[0].batch, FAST_OPTS,
                      model="farmer").batched_superstep(args)
    out1 = _run(exe1, args)
    trace_s = time.monotonic() - t0
    s1 = cache1.stats()
    assert s1["aot_saves"] == 1 and s1["aot_loads"] == 0
    files = list((tmp_path / "aot").glob("*" + cc._AOT_SUFFIX))
    assert len(files) == 1

    cache2 = cc.CompileCache()
    t0 = time.monotonic()
    exe2 = cache2.get(phs[0].batch, FAST_OPTS,
                      model="farmer").batched_superstep(args)
    out2 = _run(exe2, args)
    warm_s = time.monotonic() - t0
    s2 = cache2.stats()
    assert s2["aot_loads"] >= 1 and s2["aot_load_failures"] == 0
    assert s2["aot_saves"] == 0        # nothing re-persisted
    assert warm_s < trace_s            # cold start strictly below trace
    assert _leaves_equal(out1, out2)   # warm == traced, bitwise


def test_aot_corrupt_entry_falls_back_to_trace(tmp_path, monkeypatch):
    from mpisppy_tpu.serve.service import stack_superstep_args

    monkeypatch.setenv("MPISPPY_TPU_COMPILE_CACHE_DIR", str(tmp_path))
    phs = _two_iter0_phs()
    args = stack_superstep_args(phs)
    out1 = _run(cc.CompileCache().get(
        phs[0].batch, FAST_OPTS, model="farmer"
    ).batched_superstep(args), args)

    f = next((tmp_path / "aot").glob("*" + cc._AOT_SUFFIX))
    raw = bytearray(f.read_bytes())
    raw[len(raw) // 2] ^= 0xFF
    f.write_bytes(bytes(raw))

    cache = cc.CompileCache()
    out2 = _run(cache.get(phs[0].batch, FAST_OPTS,
                          model="farmer").batched_superstep(args), args)
    s = cache.stats()
    assert s["aot_load_failures"] == 1 and s["aot_loads"] == 0
    assert s["aot_saves"] == 1         # re-persisted a good artifact
    assert _leaves_equal(out1, out2)   # fallback result identical


def test_aot_fingerprint_mismatch_falls_back(tmp_path, monkeypatch):
    """A VALID file under the WRONG fingerprint (version/backend skew
    stand-in: the header fingerprint disagrees with the computed one)
    is rejected before deserialization — silent fallback, counted."""
    from mpisppy_tpu.serve.service import stack_superstep_args

    monkeypatch.setenv("MPISPPY_TPU_COMPILE_CACHE_DIR", str(tmp_path))
    phs = _two_iter0_phs()
    args = stack_superstep_args(phs)
    out1 = _run(cc.CompileCache().get(
        phs[0].batch, FAST_OPTS, model="farmer"
    ).batched_superstep(args), args)

    # rewrite the artifact with a foreign fingerprint in its header
    # (payload intact and CRC-consistent — ONLY the identity is wrong)
    f = next((tmp_path / "aot").glob("*" + cc._AOT_SUFFIX))
    payload = cc._aot_decode(f.read_bytes(),
                             f.name[: -len(cc._AOT_SUFFIX)])
    f.write_bytes(cc._aot_encode("0" * 64, 2, payload))

    cache = cc.CompileCache()
    out2 = _run(cache.get(phs[0].batch, FAST_OPTS,
                          model="farmer").batched_superstep(args), args)
    s = cache.stats()
    assert s["aot_load_failures"] == 1 and s["aot_loads"] == 0
    assert _leaves_equal(out1, out2)


def test_aot_disabled_without_cache_dir(tmp_path, monkeypatch):
    monkeypatch.delenv("MPISPPY_TPU_COMPILE_CACHE_DIR", raising=False)
    assert cc.aot_cache_dir() is None
    from mpisppy_tpu.serve.service import stack_superstep_args
    phs = _two_iter0_phs()
    args = stack_superstep_args(phs)
    cache = cc.CompileCache()
    _run(cache.get(phs[0].batch, FAST_OPTS,
                   model="farmer").batched_superstep(args), args)
    s = cache.stats()
    assert s["aot_saves"] == 0 and s["aot_loads"] == 0

"""gbd / usar / acopf3 model families (VERDICT r2 missing item 6):
lowering correctness against the scipy/HiGHS oracle + algorithm
smoke."""

import dataclasses
import sys
from pathlib import Path

import numpy as np
import pytest

sys.path.insert(0, str(Path(__file__).parent))
from efcheck import ef_linprog, ef_milp  # noqa: E402

from mpisppy_tpu.models import acopf3, gbd, usar  # noqa: E402
from mpisppy_tpu.opt.ef import ExtensiveForm  # noqa: E402
from mpisppy_tpu.opt.ph import PH  # noqa: E402

OPTS = {"pdhg_eps": 1e-7, "pdhg_max_iters": 200000}


def test_gbd_ef_matches_oracle():
    b = gbd.build_batch(5)
    ref, _ = ef_linprog(b, n_real=5)
    ef = ExtensiveForm(dict(OPTS), b.tree.scen_names, batch=b)
    ef.solve_extensive_form()
    assert ef.get_objective_value() == pytest.approx(ref, rel=2e-4)
    # reference protocol detail: demands drawn by RandomState(scennum)
    d0 = gbd.scenario_demand(0)
    assert d0.shape == (5,)
    assert all(d0[r] in gbd.DEMANDS_EXT[r] for r in range(5))


def test_gbd_ph_bounds_bracket():
    b = gbd.build_batch(6)
    ph = PH({"defaultPHrho": 1.0, "PHIterLimit": 40,
             "convthresh": 1e-6, **OPTS},
            list(b.tree.scen_names), batch=b)
    conv, eobj, triv = ph.ph_main()
    lag = ph.lagrangian_bound()
    inner, feas = ph.evaluate_xhat(ph.root_xbar())
    assert feas
    assert lag <= inner + 1e-3 * abs(inner)


def test_usar_lp_relaxation_matches_oracle():
    b = usar.build_batch(2, time_horizon=4, num_sites=3)
    ref, _ = ef_linprog(b, n_real=2)
    ef = ExtensiveForm(dict(OPTS), b.tree.scen_names, batch=b)
    ef.solve_extensive_form()
    assert ef.get_objective_value() == pytest.approx(
        ref, rel=2e-4, abs=1e-3)


def test_usar_mip_saves_lives():
    """Integer USAR via the LP dive: depots activate, teams deploy,
    and the incumbent matches the HiGHS branch-and-cut oracle."""
    from mpisppy_tpu.opt.mip import ExtensiveFormMIP
    b = usar.build_batch(2, time_horizon=4, num_sites=3)
    ref, _ = ef_milp(b, n_real=2, mip_rel_gap=1e-6)
    ef = ExtensiveFormMIP(dict(OPTS), b.tree.scen_names, batch=b)
    out = ef.solve_mip()
    assert out["incumbent"] <= 0.0          # lives saved (negated)
    assert out["incumbent"] == pytest.approx(ref, rel=5e-2, abs=0.51)
    act = out["x"][:, :2]
    assert np.allclose(act, np.round(act))


def test_acopf3_multistage_ef():
    b = acopf3.build_batch(branching_factors=(2, 2))
    assert b.tree.num_nodes > 1             # true multistage tree
    # LP part vs oracle (zero the quadratic cost; linprog can't QP)
    b_lp = dataclasses.replace(b, qdiag=np.zeros_like(np.asarray(b.c)))
    ref, _ = ef_linprog(b_lp, n_real=b.num_scens)
    ef = ExtensiveForm(dict(OPTS), b.tree.scen_names, batch=b_lp)
    ef.solve_extensive_form()
    assert ef.get_objective_value() == pytest.approx(ref, rel=3e-4)
    # QP path: quadratic generation cost can only increase the optimum
    efq = ExtensiveForm(dict(OPTS), b.tree.scen_names, batch=b)
    res = efq.solve_extensive_form()
    assert bool(np.all(np.asarray(res.converged)))
    assert efq.get_objective_value() >= ref - 1e-6 * abs(ref)


def test_acopf3_outage_forces_zero_flow():
    b = acopf3.build_batch(branching_factors=(7, 1), n_line=6)
    ef = ExtensiveForm(dict(OPTS), b.tree.scen_names, batch=b)
    res = ef.solve_extensive_form()
    x = np.asarray(res.x)
    # scenario with branch digit d>0 at stage 2 has line d-1 out: its
    # stage-2 flow must be ~0
    per = 3 + 5 + 6 + 2 * 5
    for s in range(b.num_scens):
        d = s % 7
        if d > 0 and d - 1 < 6:
            f = x[s, per + 3 + 5 + (d - 1)]
            assert abs(f) < 1e-4, (s, d, f)


def test_acopf3_ph_multistage_runs():
    b = acopf3.build_batch(branching_factors=(2, 2))
    ph = PH({"defaultPHrho": 5.0, "PHIterLimit": 25,
             "convthresh": 1e-6, **OPTS},
            list(b.tree.scen_names), batch=b)
    conv, eobj, triv = ph.ph_main()
    assert np.isfinite(eobj) and np.isfinite(triv)
    assert triv <= eobj + 1e-3 * abs(eobj)


# ---- aircond reference-parameter parity (round 4 deepening) ----------

def test_aircond_reference_parameters():
    """The reference parms table (aircond.py:15-34) is fully plumbed:
    salvage terminal inventory cost, quadratic shortage, random-walk
    demand clipping, parameter overrides."""
    from mpisppy_tpu.models import aircond
    b = aircond.build_batch(branching_factors=(2, 2))
    T = 3
    c = np.asarray(b.c)
    # terminal posInventory carries the NEGATIVE salvage coefficient
    ii_last = 4 * (T - 1) + 2
    assert np.allclose(c[:, ii_last], aircond.PARMS["LastInventoryCost"])
    assert c[0, ii_last] < 0
    # non-terminal stages carry the holding cost
    assert np.allclose(c[:, 2], aircond.PARMS["InventoryCost"])
    # random-walk demand honors [min_d, max_d]
    lo = np.asarray(b.row_lo)
    d_implied = -(lo[:, 1])                 # stage-2 balance rhs
    assert np.all(d_implied >= aircond.PARMS["min_d"] - 1e-9)
    assert np.all(d_implied <= aircond.PARMS["max_d"] + 1e-9)
    # QuadShortCoeff becomes native qdiag on the shortage columns
    b2 = aircond.build_batch(branching_factors=(2, 2),
                             QuadShortCoeff=0.3)
    q = np.asarray(b2.qdiag)
    assert np.allclose(q[:, 3], 0.6)        # 0.5*qdiag*x^2 convention
    assert np.allclose(q[:, 4 * (T - 1) + 3], 0.0)   # not at last stage
    # parameter override reaches the objective
    b3 = aircond.build_batch(branching_factors=(2, 2),
                             OvertimeProdCost=7.0)
    assert np.allclose(np.asarray(b3.c)[:, 1], 7.0)
    with pytest.raises(ValueError):
        aircond.build_batch(branching_factors=(2,), NoSuchParam=1)


def test_aircond_start_ups_integer_variant():
    """start_ups=True adds per-stage binaries with big-M forcing rows
    (reference aircond.py:142-144): producing anything requires the
    stage's StartUp to be on, and the MIP dive prices it."""
    from mpisppy_tpu.models import aircond
    from mpisppy_tpu.opt.mip import ExtensiveFormMIP
    b = aircond.build_batch(branching_factors=(2,), start_ups=True,
                            sigma_dev=20.0)
    assert bool(np.any(np.asarray(b.integer_mask)))
    T = 2
    assert b.num_vars == 4 * T + T
    assert b.num_nonants == 5 * (T - 1)
    ef = ExtensiveFormMIP({"pdhg_eps": 1e-6, "pdhg_max_iters": 100000},
                          list(b.tree.scen_names), batch=b)
    out = ef.solve_mip()
    live = np.asarray(ef.batch.prob) > 0      # out includes pad rows
    u = out["x"][live][:, 4 * T:]
    assert np.allclose(u, np.round(u))
    # demand is positive in every scenario, so something must produce:
    # at least one stage's start-up is on, and its cost is real
    assert np.all(u.sum(axis=1) >= 1 - 1e-9)
    assert out["bound"] <= out["incumbent"] + 1e-6


def test_aircond_xhat_generator():
    from mpisppy_tpu.models import aircond
    xh = aircond.xhat_generator_aircond(
        ["Scenario1", "Scenario2"], branching_factors=[2],
        start_seed=7)
    assert xh.shape == (4,)                 # stage-1 nonants
    assert np.all(np.isfinite(xh))


def test_acopf3_ieee14_case():
    """case='ieee14' builds the embedded IEEE 14-bus benchmark network
    (reference feeds egret matpower case files the same way,
    examples/acopf3/ccopf_multistage.py): 14 buses, 20 lines, 5 gens,
    259 MW total nominal load, and the nominal (no-outage) stage-1
    dispatch matches the closed-form economic dispatch — marginal
    costs equalize across the two cheap units with the expensive
    40-$/MW block idle."""
    b = acopf3.build_batch(branching_factors=(1,), case="ieee14")
    nB, nL, nG = 14, 20, 5
    per = nG + nB + nL + 2 * nB
    assert b.num_vars == 2 * per          # T=2 stages
    ef = ExtensiveForm(dict(OPTS), b.tree.scen_names, batch=b)
    res = ef.solve_extensive_form()
    assert bool(np.all(np.asarray(res.converged)))
    x = np.asarray(res.x)[0]
    g1 = x[:nG]
    # no load shed in the nominal network
    mp = x[nG + nB + nL:nG + nB + nL + nB]
    mn = x[nG + nB + nL + nB:per]
    assert np.abs(mp).max() < 1e-2 and np.abs(mn).max() < 1e-2
    total = sum(acopf3._IEEE14_LOAD)
    assert np.isclose(g1.sum(), total, atol=0.5)
    # closed-form ED on the two 20-$/MW units (DC, caps non-binding):
    # 2*c2_1*g1 = 2*c2_2*g2, g1+g2 = 259 ->
    # g1 = total * c2_2/(c2_1+c2_2), marginal < 40 so g3..g5 = 0
    c2a, c2b = acopf3._IEEE14_C2[0], acopf3._IEEE14_C2[1]
    g1_star = total * c2b / (c2a + c2b)
    assert np.isclose(g1[0], g1_star, rtol=2e-2), (g1, g1_star)
    assert g1[2:].max() < 1.0

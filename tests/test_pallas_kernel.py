"""Pallas fused-chunk kernel tests (interpret mode on CPU): the kernel
must match the jnp reference loop bit-for-tolerance, and a full PH
golden run through the pallas path must land on the farmer optimum."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from mpisppy_tpu.models import farmer
from mpisppy_tpu.ops.pallas_pdhg import fused_chunk
from mpisppy_tpu.ops.pdhg import PDHGSolver, prepare_batch


def _ref_steps(A, cs, qs, lbs, ubs, rlo, rhi, x, y, tau, sigma, n):
    xs = jnp.zeros_like(x)
    ys = jnp.zeros_like(y)
    for _ in range(n):
        grad = cs + qs * x + jnp.einsum("smn,sm->sn", A, y)
        xn = jnp.clip(x - tau[:, None] * grad, lbs, ubs)
        xt = 2.0 * xn - x
        v = y + sigma[:, None] * jnp.einsum("smn,sn->sm", A, xt)
        zc = jnp.clip(v / sigma[:, None], rlo, rhi)
        yn = v - sigma[:, None] * zc
        x, y = xn, yn
        xs = xs + xn
        ys = ys + yn
    return x, y, xs, ys


def test_fused_chunk_matches_reference():
    rng = np.random.RandomState(0)
    S, M, N = 4, 5, 7
    A = jnp.asarray(rng.randn(S, M, N))
    cs = jnp.asarray(rng.randn(S, N))
    qs = jnp.asarray(np.abs(rng.randn(S, N)) * 0.1)
    lbs = jnp.zeros((S, N))
    ubs = jnp.full((S, N), 10.0)
    rlo = jnp.asarray(np.where(rng.rand(S, M) < 0.5, -np.inf,
                               -rng.rand(S, M)))
    rhi = jnp.asarray(rng.rand(S, M) + 1.0)
    x = jnp.asarray(rng.rand(S, N))
    y = jnp.asarray(rng.randn(S, M) * 0.1)
    tau = jnp.asarray(0.1 + 0.05 * rng.rand(S))
    sigma = jnp.asarray(0.1 + 0.05 * rng.rand(S))

    ref = _ref_steps(A, cs, qs, lbs, ubs, rlo, rhi, x, y, tau, sigma, 7)
    got = fused_chunk(A, cs, qs, lbs, ubs, rlo, rhi, x, y, tau, sigma,
                      7, tile_s=2, interpret=True)
    for r, g in zip(ref, got):
        assert np.allclose(np.asarray(r), np.asarray(g), atol=1e-10)


def test_fused_chunk_odd_batch_falls_back_to_tile1():
    rng = np.random.RandomState(1)
    S, M, N = 3, 4, 5
    args = (jnp.asarray(rng.randn(S, M, N)), jnp.asarray(rng.randn(S, N)),
            jnp.zeros((S, N)), jnp.zeros((S, N)),
            jnp.full((S, N), 5.0), jnp.full((S, M), -1.0),
            jnp.ones((S, M)), jnp.asarray(rng.rand(S, N)),
            jnp.zeros((S, M)), jnp.full((S,), 0.1), jnp.full((S,), 0.1))
    out = fused_chunk(*args, 3, tile_s=8, interpret=True)
    ref = _ref_steps(args[0], args[1], args[2], args[3], args[4],
                     args[5], args[6], args[7], args[8], args[9],
                     args[10], 3)
    assert np.allclose(np.asarray(out[0]), np.asarray(ref[0]), atol=1e-10)


def test_pdhg_solver_pallas_path_farmer():
    b = farmer.build_batch(8)
    prep = prepare_batch(b.A, b.row_lo, b.row_hi)
    solver = PDHGSolver(max_iters=20000, eps=1e-7, use_pallas=True,
                        pallas_tile=4, pallas_interpret=True)
    res = solver.solve(prep, b.c, b.qdiag, b.lb, b.ub,
                       obj_const=b.obj_const)
    assert bool(np.asarray(res.converged).all())
    # wait-and-see bound of 8-scenario farmer: E[obj] finite, below 0
    solver2 = PDHGSolver(max_iters=20000, eps=1e-7, use_pallas=False)
    res2 = solver2.solve(prep, b.c, b.qdiag, b.lb, b.ub,
                         obj_const=b.obj_const)
    assert np.allclose(np.asarray(res.obj), np.asarray(res2.obj),
                       rtol=1e-5, atol=1e-3)

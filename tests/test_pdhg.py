"""Solver-kernel correctness vs scipy.linprog — the analog of the
reference's reliance on commercial-solver correctness (there is no
solver test in the reference; we must test ours).
"""

import numpy as np
import pytest
from scipy.optimize import linprog

from mpisppy_tpu.models import farmer
from mpisppy_tpu.ops import PDHGSolver, prepare_batch


def scipy_solve(b, s):
    A = np.array(b.A[s])
    lo, hi = np.array(b.row_lo[s]), np.array(b.row_hi[s])
    A_ub, b_ub = [], []
    for r in range(A.shape[0]):
        if np.isfinite(hi[r]):
            A_ub.append(A[r]); b_ub.append(hi[r])
        if np.isfinite(lo[r]):
            A_ub.append(-A[r]); b_ub.append(-lo[r])
    bounds = [(l, u if np.isfinite(u) else None)
              for l, u in zip(np.array(b.lb[s]), np.array(b.ub[s]))]
    return linprog(np.array(b.c[s]), A_ub=np.array(A_ub),
                   b_ub=np.array(b_ub), bounds=bounds, method="highs")


@pytest.fixture(scope="module")
def farmer3():
    return farmer.build_batch(3)


def test_farmer_lp_matches_scipy(farmer3):
    b = farmer3
    prep = prepare_batch(b.A, b.row_lo, b.row_hi)
    solver = PDHGSolver(max_iters=20000, eps=1e-8)
    res = solver.solve(prep, b.c, b.qdiag, b.lb, b.ub,
                       obj_const=b.obj_const)
    assert bool(np.all(np.asarray(res.converged)))
    for s in range(3):
        ref = scipy_solve(b, s)
        assert abs(float(res.obj[s]) - ref.fun) < 1e-5 * (1 + abs(ref.fun))
        # dual objective is a valid lower bound (within tolerance)
        assert float(res.dual_obj[s]) <= ref.fun + 1e-4 * (1 + abs(ref.fun))


def test_qp_prox_term(farmer3):
    """Diagonal QP: adding rho/2||x - t||^2 on the acreage vars must
    match scipy solving the same QP via KKT sweep (small rho keeps the
    LP active set; we check optimality conditions instead of an exact
    reference)."""
    b = farmer3
    prep = prepare_batch(b.A, b.row_lo, b.row_hi)
    solver = PDHGSolver(max_iters=30000, eps=1e-8)
    rho = 10.0
    t = np.array([100.0, 100.0, 300.0])
    q = np.array(b.qdiag)
    q[:, :3] += rho
    c = np.array(b.c)
    c[:, :3] -= rho * t
    res = solver.solve(prep, c, q, b.lb, b.ub, obj_const=b.obj_const)
    assert bool(np.all(np.asarray(res.converged)))
    # strong duality for convex QP: gap ~ 0
    assert np.all(np.asarray(res.gap) < 1e-6)


def test_warm_start_speeds_up(farmer3):
    b = farmer3
    prep = prepare_batch(b.A, b.row_lo, b.row_hi)
    solver = PDHGSolver(max_iters=20000, eps=1e-7)
    r1 = solver.solve(prep, b.c, b.qdiag, b.lb, b.ub)
    r2 = solver.solve(prep, b.c, b.qdiag, b.lb, b.ub, x0=r1.x, y0=r1.y)
    assert int(r2.iters) <= int(r1.iters)


def test_infeasible_detected():
    """x >= 5 with ub = 1: no feasible point; kernel must NOT report
    convergence with a small primal residual (reference classifies
    infeasibility from solver status, spopt.py:175-194)."""
    import jax.numpy as jnp
    A = jnp.ones((1, 1, 1))
    prep = prepare_batch(A, jnp.full((1, 1), 5.0), jnp.full((1, 1), np.inf))
    solver = PDHGSolver(max_iters=3000, eps=1e-8)
    res = solver.solve(prep, jnp.ones((1, 1)), jnp.zeros((1, 1)),
                       jnp.zeros((1, 1)), jnp.ones((1, 1)))
    assert float(res.pres[0]) > 1e-3
    assert not bool(res.converged[0])


def test_iters_cap_bounds_spend(farmer3):
    """The traced screening cap (ops/pdhg._solve_impl iters_cap) stops
    the solve after ~cap iterations when the uncapped solve needs
    more, and different cap values reuse one trace (the cap is a
    traced arg, so there is no recompile per budget value)."""
    b = farmer3
    prep = prepare_batch(b.A, b.row_lo, b.row_hi)
    solver = PDHGSolver(max_iters=20000, eps=1e-11, check_every=40)
    import jax.numpy as jnp
    zargs = (jnp.zeros(b.c.shape[:1], b.c.dtype), jnp.zeros_like(b.c),
             jnp.zeros_like(b.row_lo))
    base = solver._solve_jit(prep, b.c, b.qdiag, b.lb, b.ub, *zargs,
                             None, None, None)
    n_base = int(base.iters)
    if n_base < 200:
        import pytest
        pytest.skip("instance converges too fast to exercise the cap")
    cap = max(80, n_base // 4)
    capped = solver._solve_jit(prep, b.c, b.qdiag, b.lb, b.ub, *zargs,
                               None, None, jnp.asarray(cap, jnp.int32))
    assert int(capped.iters) <= cap + solver.check_every
    assert int(capped.iters) < n_base
    # different cap values must reuse the same trace
    n_traces = solver._solve_jit._cache_size()
    capped2 = solver._solve_jit(prep, b.c, b.qdiag, b.lb, b.ub, *zargs,
                                None, None,
                                jnp.asarray(2 * cap, jnp.int32))
    assert solver._solve_jit._cache_size() == n_traces
    assert int(capped2.iters) <= 2 * cap + solver.check_every

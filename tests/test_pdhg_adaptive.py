"""Adaptive-work PDHG tests: the KKT-triggered restart policy, the
converged-scenario compaction driver, the option plumbing for the new
knobs, and the AST trace-safety guard on the solver's hot loop.

Measured headline (f64 model corpus, eps=1e-6): adaptive restarts cut
total inner iterations 33% vs the fixed cadence (farmer 0.50x, netdes
0.37x, uc 0.44x, apl1p 0.55x; sizes/sslp within noise) — the tier-1
subset below asserts the >=20% aggregate on its three fastest members.
"""

import ast
import os

import numpy as np
import pytest

import jax.numpy as jnp

from mpisppy_tpu.models import apl1p, farmer, netdes
from mpisppy_tpu.ops.pdhg import PDHGSolver, _gather_prep, prepare_batch
from mpisppy_tpu.serve.compile_cache import width_bucket

pytestmark = pytest.mark.pdhg


# --------------------------------------------------------------------------
# knob plumbing
# --------------------------------------------------------------------------

def test_width_bucket():
    assert [width_bucket(n) for n in (1, 2, 3, 4, 5, 8, 9, 1000)] \
        == [1, 2, 4, 4, 8, 8, 16, 1024]
    assert width_bucket(3, floor=8) == 8
    assert width_bucket(0) == 1


def test_from_options_maps_adaptive_knobs():
    s = PDHGSolver.from_options({
        "pdhg_restart_mode": "fixed",
        "pdhg_restart_beta_sufficient": 0.1,
        "pdhg_restart_beta_necessary": 0.9,
        "pdhg_compact_threshold": 0.25})
    assert s.restart_mode == "fixed"
    assert s.restart_beta_sufficient == 0.1
    assert s.restart_beta_necessary == 0.9
    assert s.compact_threshold == 0.25
    # defaults: adaptive on, compaction off
    d = PDHGSolver.from_options({})
    assert d.restart_mode == "adaptive"
    assert d.compact_threshold == 0.0


def test_env_overlay_wins(monkeypatch):
    monkeypatch.setenv(
        "MPISPPY_TPU_PDHG",
        "restart_mode=fixed pdhg_compact_threshold=0.5")
    s = PDHGSolver.from_options({"pdhg_restart_mode": "adaptive",
                                 "pdhg_compact_threshold": 0.0})
    assert s.restart_mode == "fixed"       # env wins over the dict
    assert s.compact_threshold == 0.5      # prefixed key accepted too


def test_bad_restart_mode_rejected():
    with pytest.raises(ValueError):
        PDHGSolver(restart_mode="sometimes")


def test_clone_and_config_key():
    s = PDHGSolver(eps=1e-7, restart_beta_sufficient=0.3,
                   compact_threshold=0.5)
    c = s.clone(max_iters=123)
    assert c.max_iters == 123
    assert c.restart_beta_sufficient == 0.3
    assert c.compact_threshold == 0.5
    # config_key covers every knob: only the overridden field differs
    ka, kb = s.config_key(), c.config_key()
    assert ka != kb
    assert [a for a, b in zip(ka, kb) if a != b] == [s.max_iters]
    # the new knobs are IN the key (configs must never alias in caches)
    assert s.config_key() != s.clone(restart_mode="fixed").config_key()
    assert s.config_key() != \
        s.clone(compact_threshold=0.25).config_key()


# --------------------------------------------------------------------------
# adaptive vs fixed on the model corpus
# --------------------------------------------------------------------------

def _corpus():
    return [farmer.build_batch(8), netdes.build_batch(4),
            apl1p.build_batch()]


def test_adaptive_and_fixed_reach_reference_verdicts():
    """Both restart policies must reach the SAME certified KKT verdicts
    (all-converged) and the same objectives on the corpus, and the
    adaptive policy must spend >=20% fewer total inner iterations (the
    measured aggregate on the full corpus is 33%)."""
    tot = {"adaptive": 0, "fixed": 0}
    for b in _corpus():
        prep = prepare_batch(b.A, b.row_lo, b.row_hi)
        objs = {}
        for mode in ("adaptive", "fixed"):
            s = PDHGSolver(max_iters=100000, eps=1e-6, restart_mode=mode)
            res = s.solve(prep, b.c, b.qdiag, b.lb, b.ub,
                          obj_const=b.obj_const)
            assert bool(np.all(np.asarray(res.converged))), mode
            assert np.all(np.asarray(res.pres) < 1e-6)
            tot[mode] += int(res.iters)
            objs[mode] = np.asarray(res.obj)
            # restart accounting: per-scenario counts ride in the result
            assert np.asarray(res.restarts).shape == (b.num_scens,)
        assert np.allclose(objs["adaptive"], objs["fixed"], rtol=1e-4,
                           atol=1e-4)
    assert tot["adaptive"] <= 0.8 * tot["fixed"], tot


def test_adaptive_restarts_before_forced_cap():
    """On farmer the trigger must fire well before the every-16 forced
    cap (that is where the iteration savings come from): more restart
    events than the fixed cadence takes in the same iteration count."""
    b = farmer.build_batch(8)
    prep = prepare_batch(b.A, b.row_lo, b.row_hi)
    s = PDHGSolver(max_iters=100000, eps=1e-6)
    res = s.solve(prep, b.c, b.qdiag, b.lb, b.ub, obj_const=b.obj_const)
    n_checks = int(res.iters) // s.check_every
    forced_cadence_events = (n_checks // s.restart_every) * b.num_scens
    assert int(np.sum(np.asarray(res.restarts))) > forced_cadence_events


# --------------------------------------------------------------------------
# compaction
# --------------------------------------------------------------------------

def _split_difficulty_batch():
    """farmer-8 with a strong prox term (center far from the LP
    optimum) on scenarios 0-3: the inflated objective scale makes those
    need ~4x the iterations of the plain LPs 4-7 (measured: LPs
    converge by 2560 inner iterations, prox scenarios by 9120) — a
    clean early/late split, the shape compaction exists for."""
    b = farmer.build_batch(8)
    q = np.array(b.qdiag)
    c = np.array(b.c)
    q[:4] += 100.0
    c[:4, :3] -= 100.0 * 150.0   # prox center at 150 acres
    return b, jnp.asarray(c), jnp.asarray(q)


def test_compaction_parity_frozen_bit_identical():
    b, c, q = _split_difficulty_batch()
    prep = prepare_batch(b.A, b.row_lo, b.row_hi)
    seg = 2560
    sc = PDHGSolver(max_iters=20000, eps=1e-7, compact_threshold=0.9)
    su = sc.clone(compact_threshold=0.0)

    traj = []
    res_c = sc.solve_compacted(prep, c, q, b.lb, b.ub,
                               obj_const=b.obj_const,
                               segment_iters=seg, on_segment=traj.append)
    res_u = su.solve(prep, c, q, b.lb, b.ub, obj_const=b.obj_const)
    assert bool(np.all(np.asarray(res_c.converged)))
    assert bool(np.all(np.asarray(res_u.converged)))

    # compaction must actually have happened and widths never grow
    widths = [t["width"] for t in traj]
    assert widths[-1] < b.num_scens
    assert widths == sorted(widths, reverse=True)
    assert all(w == width_bucket(w) for w in widths)

    # scenarios frozen in segment 1 (before any compaction) are
    # BIT-identical to the uncompacted solve: they converged at the
    # same KKT check, with x_best pinned from the same iterate
    probe = su.solve(prep, c, q, b.lb, b.ub, obj_const=b.obj_const,
                     iters_cap=jnp.asarray(seg, jnp.int32))
    frozen = np.asarray(probe.converged)
    assert frozen[4:].all()      # the plain-LP half converges early
    assert not frozen.all()      # ...and the prox-heavy half survives
    for f in ("x", "y", "obj", "pres", "dres", "gap"):
        a = np.asarray(getattr(res_c, f))[frozen]
        u = np.asarray(getattr(res_u, f))[frozen]
        assert np.array_equal(a, u), f
    # survivors agree within the KKT tolerance (restart average and
    # omega re-seed each segment, so bitwise equality is not expected)
    assert np.allclose(np.asarray(res_c.obj), np.asarray(res_u.obj),
                       rtol=1e-5, atol=1e-5)


def test_compaction_disabled_is_plain_solve():
    b = farmer.build_batch(4)
    prep = prepare_batch(b.A, b.row_lo, b.row_hi)
    s = PDHGSolver(max_iters=20000, eps=1e-7)   # compact_threshold=0
    ra = s.solve_compacted(prep, b.c, b.qdiag, b.lb, b.ub,
                           obj_const=b.obj_const)
    rb = s.solve(prep, b.c, b.qdiag, b.lb, b.ub, obj_const=b.obj_const)
    assert np.array_equal(np.asarray(ra.x), np.asarray(rb.x))


def test_compaction_skips_padding_scenarios():
    """prob=0 padding rows (ir.pad_scenarios) never count as active:
    a batch whose real rows all converge ends without spinning on the
    padding."""
    b, c, q = _split_difficulty_batch()
    prep = prepare_batch(b.A, b.row_lo, b.row_hi)
    probs = np.array([0.25, 0.25, 0.25, 0.25, 0.0, 0.0, 0.0, 0.0])
    s = PDHGSolver(max_iters=20000, eps=1e-7, compact_threshold=0.9)
    traj = []
    res = s.solve_compacted(prep, c, q, b.lb, b.ub, obj_const=b.obj_const,
                            probs=probs, segment_iters=640,
                            on_segment=traj.append)
    # real rows (the prox-heavy half) all converged...
    assert bool(np.all(np.asarray(res.converged)[:4]))
    # ...and the driver stopped on active==0 without burning max_iters
    # on the prob-0 LPs
    assert traj[-1]["active"] == 0
    assert int(res.iters) < s.max_iters


def test_gather_prep_keeps_shared_leaves():
    """Shared-A preps broadcast with leading dim 1; _gather_prep must
    gather only per-scenario leaves (the take() rule)."""
    b = farmer.build_batch(6)
    prep = prepare_batch(b.A, b.row_lo, b.row_hi)
    ii = jnp.asarray([1, 4], jnp.int32)
    g = _gather_prep(prep, ii)
    assert g.A.shape[0] == 2 and g.anorm.shape == (2,)
    shared = prep.__class__(
        A=prep.A, row_lo=prep.row_lo, row_hi=prep.row_hi,
        d_row=prep.d_row[:1], d_col=prep.d_col[:1], anorm=prep.anorm)
    g2 = _gather_prep(shared, ii)
    assert g2.d_row.shape[0] == 1        # untouched broadcast leaf
    assert g2.row_lo.shape[0] == 2


def test_pallas_kernel_on_compacted_slab():
    """The Pallas fused-chunk path (interpret mode) must match the jnp
    path on a gathered, non-pow2-tile slab — the shape compaction
    produces (width 4 slab under the default tile_s=8 forces the
    even-divisor tiling fallback)."""
    b = farmer.build_batch(8)
    prep = prepare_batch(b.A, b.row_lo, b.row_hi)
    ii = jnp.asarray([0, 2, 5, 6], jnp.int32)
    gp = _gather_prep(prep, ii)
    args = (b.c[ii], b.qdiag[ii], b.lb[ii], b.ub[ii])
    kw = {"obj_const": b.obj_const[ii]}
    sp = PDHGSolver(max_iters=20000, eps=1e-7, use_pallas=True,
                    pallas_tile=8, pallas_interpret=True)
    sj = sp.clone(use_pallas=False)
    rp = sp.solve(gp, *args, **kw)
    rj = sj.solve(gp, *args, **kw)
    assert bool(np.all(np.asarray(rp.converged)))
    assert np.allclose(np.asarray(rp.obj), np.asarray(rj.obj),
                       rtol=1e-5, atol=1e-3)


# --------------------------------------------------------------------------
# AST trace-safety guard
# --------------------------------------------------------------------------

def _is_static_expr(node):
    """Expression whose value is fixed at TRACE time: constants,
    self.* config attributes, isinstance() checks, and .shape reads."""
    if isinstance(node, ast.Constant):
        return True
    if isinstance(node, ast.Name):
        return node.id in ("self", "SplitA")
    if isinstance(node, ast.Attribute):
        return node.attr == "shape" or _is_static_expr(node.value)
    if isinstance(node, ast.Subscript):
        return _is_static_expr(node.value)
    if isinstance(node, ast.Call):
        return (isinstance(node.func, ast.Name)
                and node.func.id in ("isinstance", "len", "getattr",
                                     "int", "max"))
    return False


def _is_static_test(node):
    if isinstance(node, ast.BoolOp):
        return all(_is_static_test(v) for v in node.values)
    if isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.Not):
        return _is_static_test(node.operand)
    if isinstance(node, ast.Compare):
        # identity tests (x is None) are Python-level, never traced
        if all(isinstance(op, (ast.Is, ast.IsNot)) for op in node.ops):
            return True
        return (_is_static_expr(node.left)
                and all(_is_static_expr(c) for c in node.comparators))
    return _is_static_expr(node)


def test_solve_impl_loop_body_is_trace_safe():
    """Guard: every Python `if` inside PDHGSolver._solve_impl branches
    on trace-time-static state only (config attributes, None-ness of
    optional args, shapes/types) — a Python `if` on a traced value
    would raise TracerBoolConversionError at best and silently bake in
    one branch at worst.  Traced branching must use jnp.where /
    lax.cond / lax.switch."""
    import mpisppy_tpu.ops.pdhg as mod

    src = open(mod.__file__).read()
    tree = ast.parse(src)
    impl = None
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef) and node.name == "PDHGSolver":
            for f in node.body:
                if isinstance(f, ast.FunctionDef) \
                        and f.name == "_solve_impl":
                    impl = f
    assert impl is not None, "PDHGSolver._solve_impl not found"
    bad = [n.lineno for n in ast.walk(impl)
           if isinstance(n, ast.If) and not _is_static_test(n.test)]
    assert not bad, (
        f"Python `if` on possibly-traced values in _solve_impl at "
        f"lines {bad} of {mod.__file__}; use jnp.where/lax.cond")
    # the checker itself must reject a traced-value branch
    neg = ast.parse("if score_cand > 1.0:\n    pass").body[0]
    assert not _is_static_test(neg.test)

"""PH end-to-end golden tests on farmer — the analog of the reference's
workhorse test_ef_ph.py (golden values at low precision,
tests/utils.py:30 round_pos_sig).

Golden numbers: classic 3-scenario farmer optimum is -108390
(Birge & Louveaux), trivial bound -115405.55 (wait-and-see).
"""

import numpy as np
import pytest

from mpisppy_tpu.models import farmer
from mpisppy_tpu.opt.ph import PH
from mpisppy_tpu.utils.xhat_eval import Xhat_Eval


def round_pos_sig(x, sig=1):
    """Reference: mpisppy/tests/utils.py:30."""
    import math
    return round(x, -int(math.floor(math.log10(abs(x)))) + (sig - 1))


@pytest.fixture(scope="module")
def ph3():
    b = farmer.build_batch(3)
    opts = {"defaultPHrho": 1.0, "PHIterLimit": 200,
            "convthresh": 1e-5, "pdhg_eps": 1e-7}
    ph = PH(opts, [f"scen{i}" for i in range(3)], batch=b)
    ph.ph_main()
    return ph


def test_trivial_bound(ph3):
    # wait-and-see bound for classic farmer: -115405.55
    assert round_pos_sig(ph3.trivial_bound, 5) == -115410.0 or \
        abs(ph3.trivial_bound - -115405.55) < 5.0


def test_ph_converges_to_ef_objective(ph3):
    eobj = float(ph3.Eobjective(ph3.state.obj))
    assert abs(eobj - -108390.0) < 20.0


def test_xbar_solution(ph3):
    xbar = np.asarray(ph3.root_xbar())
    assert np.allclose(xbar, [170.0, 80.0, 250.0], atol=0.5)


def test_lagrangian_bound_valid(ph3):
    lb = ph3.lagrangian_bound()
    # must be a valid lower bound on -108390, and tighter than trivial
    assert lb <= -108389.0
    assert lb >= ph3.trivial_bound - 1.0


def test_xhat_eval_inner_bound(ph3):
    ev = Xhat_Eval(dict(ph3.options), ph3.all_scenario_names,
                   batch=farmer.build_batch(3))
    eobj, feas = ev.evaluate(np.asarray(ph3.root_xbar()))
    assert feas
    # fixing to the optimal xbar recovers the EF objective
    assert abs(eobj - -108390.0) < 20.0
    # a deliberately bad candidate is worse
    bad, feas2 = ev.evaluate(np.array([0.0, 0.0, 0.0]))
    assert feas2
    assert bad > eobj + 1000


def test_scenario_denouement_contract():
    """Denouements receive (rank, name, scenario) with THAT scenario's
    data — a ScenarioView slice, not the global state (reference
    spbase.py:505-522 contract; VERDICT r3 item 7)."""
    b = farmer.build_batch(3)
    seen = {}

    def denouement(rank, name, scen):
        assert rank == 0
        assert scen.name == name
        # per-scenario arrays, not the (S, N) global state
        assert scen.x.ndim == 1 and scen.x.shape[0] == b.num_vars
        assert scen.nonants.shape == (b.num_nonants,)
        seen[name] = (scen.obj, scen.prob, scen.nonants.copy())

    opts = {"defaultPHrho": 1.0, "PHIterLimit": 50,
            "convthresh": 1e-4, "pdhg_eps": 1e-6}
    ph = PH(opts, [f"scen{i}" for i in range(3)], batch=b,
            scenario_denouement=denouement)
    ph.ph_main()
    assert set(seen) == {"scen0", "scen1", "scen2"}
    probs = [p for (_, p, _) in seen.values()]
    assert abs(sum(probs) - 1.0) < 1e-9
    # per-scenario objectives differ (different yields) and their
    # probability-weighted sum is the expected objective
    objs = [seen[f"scen{i}"][0] for i in range(3)]
    eobj = float(ph.Eobjective(ph.state.obj))
    assert abs(sum(p * o for p, o in zip(probs, objs)) - eobj) < 1e-6
    # converged PH: every scenario's nonants agree with xbar
    xbar = np.asarray(ph.root_xbar())
    for _, _, na in seen.values():
        assert np.allclose(na, xbar, atol=2.0)


def test_ph_sharded_multi_device():
    """8 virtual CPU devices (conftest): same answer, sharded batch.
    Analog of the reference's mpiexec smoke tier (straight_tests.py)."""
    import jax
    assert len(jax.devices()) == 8
    b = farmer.build_batch(16)  # 2 scenarios per device
    opts = {"defaultPHrho": 2.0, "PHIterLimit": 40,
            "convthresh": 1e-4, "pdhg_eps": 1e-6}
    ph = PH(opts, [f"scen{i}" for i in range(16)], batch=b)
    conv, eobj, triv = ph.ph_main()
    assert conv < 2.0  # started ~30; must be well into consensus
    assert eobj >= triv - 1.0  # trivial bound stays a lower bound
    # serial re-run on 1 device mesh gives the same trajectory
    from mpisppy_tpu.parallel.mesh import ScenarioMesh
    mesh1 = ScenarioMesh(devices=jax.devices()[:1])
    ph1 = PH(opts, [f"scen{i}" for i in range(16)],
             batch=farmer.build_batch(16), mesh=mesh1)
    conv1, eobj1, triv1 = ph1.ph_main()
    assert abs(triv - triv1) < 1e-3 * abs(triv)
    assert abs(eobj - eobj1) < 1e-3 * abs(eobj)


def test_iter0_certify_off_and_certify_budget(monkeypatch):
    """options['iter0_certify']=False must keep Iter0 off the f64
    straggler-rescue path entirely (the UC-on-TPU wall-clock guard),
    and options['certify_max_iters'] must bound the f64 fallback
    solver's budget."""
    b = farmer.build_batch(3)
    ph = PH({"defaultPHrho": 1.0, "PHIterLimit": 2, "convthresh": 0.0,
             "pdhg_eps": 1e-7, "iter0_certify": False,
             "certify_max_iters": 1234},
            [f"s{i}" for i in range(3)], batch=b)
    calls = []
    orig = ph._certified_resolve

    def spy(res, *a, **kw):
        calls.append((a, kw))
        return orig(res, *a, **kw)
    monkeypatch.setattr(ph, "_certified_resolve", spy)
    ph.Iter0()
    assert calls == []          # no rescue attempted at Iter0
    assert np.isfinite(ph.trivial_bound)
    # force the refine path explicitly (a tiny LP can converge to
    # machine-zero residuals, so an "unreachable eps" is not reliably
    # a straggler); the lazily-built f64 solver must carry the budget
    res = ph.solve_loop()
    ph._certified_resolve(
        res, None, None, None, None,
        select=np.ones(ph.batch.num_scens, bool))
    assert ph._solver64 is not None
    assert ph._solver64.max_iters == 1234


def test_farmer_4096_scenarios_sharded_gap():
    """farmer-10k tier (BASELINE.md target row 'farmer, 10,000 scen')
    at test scale: S=4096 sharded over the 8-virtual-device mesh, PH
    to a VERIFIED <=1% outer/inner gap — the same protocol the
    BENCH_SCENS=10000 artifact runs on the TPU."""
    S = 4096
    b = farmer.build_batch(S)
    ph = PH({"defaultPHrho": 1.0, "PHIterLimit": 60, "convthresh": 0.0,
             "pdhg_eps": 1e-6, "superstep_eps": 1e-4,
             "lagrangian_eps": 1e-4},
            [f"scen{i}" for i in range(S)], batch=b)
    assert ph.batch.num_scens == S          # 4096 = 8 * 512, no pad
    ph.Iter0()
    outer = ph.trivial_bound
    gap = np.inf
    for k in range(60):
        ph.ph_iteration()
        if (k + 1) % 4 == 0:
            inner, feas = ph.evaluate_xhat(ph.root_xbar())
            outer = max(outer, ph.lagrangian_bound())
            if feas:
                gap = abs(inner - outer) / max(abs(inner), 1e-9)
            if gap <= 0.01:
                break
    assert gap <= 0.01

"""Mixed-precision PDHG tests (hot_dtype / promotion / SparseSplitA /
dtype-aware MFU — the PR 6 tentpole).

Covers: knob plumbing (from_options, MPISPPY_TPU_PDHG overlay, clone /
config_key non-aliasing), the eps-floor promotion rule and its
monotonicity, f32-vs-f64 verdict parity on the model corpus, BCOO
matvec parity against the dense SplitA path at several densities, the
SPOpt/PH promotion driver (accounting, prep dtypes, checkpointed
`promoted` flag with pre-PR-6 back-compat), the AST guard that pins
every certified/EF/MIP-dive solver clone to hot_dtype=None, serve
bucket-key non-aliasing, Pallas bf16-storage/f32-accumulate parity in
interpret mode, and the never-None dtype-aware peak-FLOP model.

Timing waiver: the ISSUE-6 >=1.5x hot-loop speedup is asserted on
accelerators only.  On CPU, f32 storage does not reliably beat the
x64 pipeline (XLA:CPU vectorizes both; memory traffic, not flops,
dominates at corpus sizes), so the CPU measurement is informational —
see doc/src/pdhg.md "Mixed-precision hot loop".
"""

import ast

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from mpisppy_tpu.ir import (SparseSplitA, SplitA, bmatvec, bmatvec_t,
                            shared_density, sparsify_split)
from mpisppy_tpu.models import apl1p, farmer, netdes
from mpisppy_tpu.ops.pdhg import HOT_DTYPES, PDHGSolver, eps_floor, \
    prepare_batch
from mpisppy_tpu.opt.ph import PH
from mpisppy_tpu.resilience.checkpoint import (load_run_checkpoint,
                                               save_run_checkpoint)
from mpisppy_tpu.utils import mfu as mfu_mod

pytestmark = pytest.mark.precision

F32_FLOOR = 100.0 * float(jnp.finfo(jnp.float32).eps)


# --------------------------------------------------------------------------
# knob plumbing
# --------------------------------------------------------------------------

def test_from_options_maps_precision_knobs():
    s = PDHGSolver.from_options({"pdhg_hot_dtype": "f32",
                                 "pdhg_sparse_threshold": 0.3})
    assert s.hot_dtype == "f32"
    assert s.sparse_threshold == 0.3
    # defaults: full precision, always-dense
    d = PDHGSolver.from_options({})
    assert d.hot_dtype is None
    assert d.sparse_threshold == 0.0


def test_hot_dtype_normalization_and_rejection():
    # every "off" spelling lands on None (the historical behavior)
    for off in (None, "", "none", "off", "f64", "float64"):
        assert PDHGSolver(hot_dtype=off).hot_dtype is None
    assert PDHGSolver(hot_dtype="bf16x").hot_dtype == "bf16x"
    with pytest.raises(ValueError, match="hot_dtype"):
        PDHGSolver(hot_dtype="f16")


def test_env_overlay_wins_precision(monkeypatch):
    monkeypatch.setenv("MPISPPY_TPU_PDHG",
                       "hot_dtype=f32 pdhg_sparse_threshold=0.25")
    s = PDHGSolver.from_options({"pdhg_hot_dtype": "off",
                                 "pdhg_sparse_threshold": 0.0})
    assert s.hot_dtype == "f32"          # env wins over the dict
    assert s.sparse_threshold == 0.25    # prefixed key accepted too


def test_clone_and_config_key_cover_precision_knobs():
    s = PDHGSolver(hot_dtype="f32", sparse_threshold=0.3)
    c = s.clone(max_iters=77)
    assert c.hot_dtype == "f32" and c.sparse_threshold == 0.3
    # the new knobs are IN the key (configs must never alias in caches)
    assert s.config_key() != s.clone(hot_dtype=None).config_key()
    assert s.config_key() != s.clone(hot_dtype="bf16x").config_key()
    assert s.config_key() != s.clone(sparse_threshold=0.0).config_key()
    # the certified/dive clone idiom drops ONLY the hot dtype
    f = s.clone(hot_dtype=None)
    assert f.hot_dtype is None and f.sparse_threshold == 0.3


# --------------------------------------------------------------------------
# eps floor + promotion rule
# --------------------------------------------------------------------------

def test_eps_floor_and_promotion_monotone():
    s = PDHGSolver(hot_dtype="f32")
    assert s.hot_eps_floor() == pytest.approx(F32_FLOOR)
    assert eps_floor("float32") == pytest.approx(F32_FLOOR)
    assert not s.wants_promotion(1e-4)
    assert s.wants_promotion(1e-6)
    # bf16x ACCUMULATES in f32, so its floor is f32's, not bf16's
    assert PDHGSolver(hot_dtype="bf16x").hot_eps_floor() \
        == pytest.approx(F32_FLOOR)
    full = PDHGSolver()
    assert full.hot_eps_floor() == 0.0
    assert not full.wants_promotion(1e-12)
    # monotone along the eps ladder: once True, tighter eps stays True
    wants = [s.wants_promotion(e)
             for e in (1e-3, 1e-4, 1e-5, 1e-6, 1e-8)]
    assert wants == sorted(wants)
    assert wants[-1]


def test_hot_pair_never_upcasts():
    s = PDHGSolver(hot_dtype="f32")
    assert s._hot_pair(jnp.float64) == (jnp.dtype("float32"),) * 2
    assert s._hot_pair(jnp.float32) is None      # no-op downcast
    b = PDHGSolver(hot_dtype="bf16x")
    assert b._hot_pair(jnp.float32) \
        == (jnp.dtype(jnp.bfloat16), jnp.dtype("float32"))
    assert PDHGSolver()._hot_pair(jnp.float64) is None


# --------------------------------------------------------------------------
# f32-vs-f64 verdict parity on the model corpus
# --------------------------------------------------------------------------

def _corpus():
    return [farmer.build_batch(8), netdes.build_batch(4),
            apl1p.build_batch()]


def test_hot_f32_matches_f64_verdicts_on_corpus():
    """At a tolerance above the f32 floor the hot loop must reach the
    SAME convergence verdicts as the f64 loop, matching objectives,
    with the result still in the caller's dtype."""
    for b in _corpus():
        prep = prepare_batch(b.A, b.row_lo, b.row_hi)
        base = PDHGSolver(max_iters=100000, eps=1e-4)
        hot = base.clone(hot_dtype="f32")
        r64 = base.solve(prep, b.c, b.qdiag, b.lb, b.ub,
                         obj_const=b.obj_const)
        r32 = hot.solve(prep, b.c, b.qdiag, b.lb, b.ub,
                        obj_const=b.obj_const)
        v64 = np.asarray(r64.converged)
        v32 = np.asarray(r32.converged)
        assert bool(np.all(v64)) and bool(np.all(v32))
        np.testing.assert_array_equal(v32, v64)
        # residuals certified against FULL-precision data in the
        # caller's dtype (the final KKT recheck in _solve_impl)
        assert np.all(np.asarray(r32.pres) < 1e-4)
        assert np.asarray(r32.x).dtype == np.asarray(r64.x).dtype
        np.testing.assert_allclose(np.asarray(r32.obj),
                                   np.asarray(r64.obj),
                                   rtol=1e-3, atol=1e-3)


def test_hot_loop_speedup_or_cpu_waiver():
    """ISSUE-6 acceptance: >=1.5x fewer hot-loop seconds under hot f32,
    asserted on accelerators.  CPU runs measure but do not assert (see
    module docstring + doc/src/pdhg.md for the documented waiver)."""
    import time

    b = farmer.build_batch(64)
    prep = prepare_batch(b.A, b.row_lo, b.row_hi)
    args = (prep, b.c, b.qdiag, b.lb, b.ub)
    kw = {"obj_const": b.obj_const}
    secs = {}
    for tag, s in (("f64", PDHGSolver(max_iters=100000, eps=1e-4)),
                   ("f32", PDHGSolver(max_iters=100000, eps=1e-4,
                                      hot_dtype="f32"))):
        r = s.solve(*args, **kw)               # compile warmup
        jax.block_until_ready(r.x)
        t0 = time.perf_counter()
        r = s.solve(*args, **kw)
        jax.block_until_ready(r.x)
        secs[tag] = time.perf_counter() - t0
        assert bool(np.all(np.asarray(r.converged))), tag
    if jax.default_backend() != "cpu":
        assert secs["f64"] / secs["f32"] >= 1.5, secs


# --------------------------------------------------------------------------
# SparseSplitA parity vs the dense SplitA path
# --------------------------------------------------------------------------

def _random_split(S=3, M=24, N=16, density=0.1, seed=0):
    rng = np.random.default_rng(seed)
    sh = rng.normal(size=(M, N)) * (rng.random((M, N)) < density)
    nnz = 5
    rows = rng.integers(0, M, nnz).astype(np.int32)
    cols = rng.integers(0, N, nnz).astype(np.int32)
    sh[rows, cols] = 0.0        # SplitA contract: shared 0 at deltas
    vals = rng.normal(size=(S, nnz))
    return SplitA(shared=jnp.asarray(sh), rows=jnp.asarray(rows),
                  cols=jnp.asarray(cols), vals=jnp.asarray(vals))


@pytest.mark.parametrize("density", [0.01, 0.1, 0.3])
def test_sparse_split_matvec_parity(density):
    Ad = _random_split(density=density)
    As = sparsify_split(Ad, threshold=0.99)
    assert isinstance(As, SparseSplitA)
    S, M, N = Ad.shape
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.normal(size=(S, N)))
    y = jnp.asarray(rng.normal(size=(S, M)))
    np.testing.assert_allclose(np.asarray(bmatvec(As, x)),
                               np.asarray(bmatvec(Ad, x)),
                               rtol=1e-12, atol=1e-12)
    np.testing.assert_allclose(np.asarray(bmatvec_t(As, y)),
                               np.asarray(bmatvec_t(Ad, y)),
                               rtol=1e-12, atol=1e-12)
    np.testing.assert_allclose(np.asarray(As.to_dense()),
                               np.asarray(Ad.to_dense()),
                               rtol=0, atol=0)
    assert As.shared_nnz_frac == pytest.approx(shared_density(Ad))


def test_sparsify_split_gating_and_astype():
    Ad = _random_split(density=0.5)
    assert sparsify_split(Ad, 0.0) is Ad       # knob off
    assert sparsify_split(Ad, None) is Ad
    assert sparsify_split(Ad, 0.2) is Ad       # density above threshold
    dense = jnp.ones((2, 3, 4))
    assert sparsify_split(dense, 0.9) is dense  # not a SplitA
    As = sparsify_split(_random_split(density=0.1), 0.99)
    assert sparsify_split(As, 0.99) is As      # already sparse
    # astype preserves the subclass AND the coordinate structure (this
    # is what lets the mixed-precision storage cast ride through)
    A32 = As.astype(jnp.float32)
    assert isinstance(A32, SparseSplitA)
    assert A32.shared.data.dtype == jnp.float32
    assert A32.vals.dtype == jnp.float32
    np.testing.assert_allclose(np.asarray(A32.to_dense()),
                               np.asarray(As.to_dense()), rtol=1e-6)


# --------------------------------------------------------------------------
# SPOpt/PH promotion driver
# --------------------------------------------------------------------------

OPTS = {"defaultPHrho": 1.0, "PHIterLimit": 6, "convthresh": 1e-6}


def _ph(extra):
    return PH(dict(OPTS, **extra), [f"s{i}" for i in range(4)],
              batch=farmer.build_batch(4))


def test_active_solver_prep_promotes_below_floor():
    ph = _ph({"pdhg_hot_dtype": "f32", "pdhg_eps": 1e-4})
    # hot prep carries low-precision data; farmer's is split
    assert str(ph.prep.A.dtype) == "float32"
    s0, p0 = ph.active_solver_prep(1e-4)
    assert s0 is ph.solver and p0 is ph.prep
    assert ph.pdhg_stats()["promotions_total"] == 0
    s1, p1 = ph.active_solver_prep(1e-6)
    assert s1 is not ph.solver and s1.hot_dtype is None
    assert str(p1.A.dtype) == "float64"
    assert ph.pdhg_stats()["promotions_total"] == 1
    # the pair is cached; each promoted SOLVE is counted
    s2, p2 = ph.active_solver_prep(1e-6)
    assert s2 is s1 and p2 is p1
    assert ph.pdhg_stats()["promotions_total"] == 2
    # probes (count=False) never skew the accounting
    ph.active_solver_prep(1e-6, count=False)
    assert ph.pdhg_stats()["promotions_total"] == 2
    ph.reset_solve_stats()
    assert ph.pdhg_stats()["promotions_total"] == 0


def test_ph_hot_run_stays_hot_above_floor():
    """Supersteps at eps above the f32 floor never promote, the
    objective matches the f64 run, and solve_stats reports a non-null
    dtype-aware MFU (CPU included — the satellite that fixed the null
    mfu gauge)."""
    ph_h = _ph({"pdhg_hot_dtype": "f32", "pdhg_eps": 1e-4})
    conv_h, eobj_h, _ = ph_h.ph_main()
    ph_f = _ph({"pdhg_eps": 1e-4})
    conv_f, eobj_f, _ = ph_f.ph_main()
    assert eobj_h == pytest.approx(eobj_f, rel=1e-3)
    st = ph_h.pdhg_stats()
    assert st["hot_dtype"] == "f32"
    assert st["promotions_total"] == 0
    assert int(ph_h.state.promoted) == 0
    stats = ph_h.solve_stats()
    assert stats["mfu"] is not None and stats["mfu"] > 0
    assert stats["dtype"] == "float32"
    # the full-precision run reports its own dtype and a non-null mfu
    assert ph_f.solve_stats()["mfu"] is not None
    assert ph_f.solve_stats()["dtype"] == "float64"


def test_ph_hot_run_promotes_below_floor():
    """A superstep tolerance below the f32 floor routes every solve to
    the promoted full-precision pair: the state records it, the
    accounting counts it, and the objective matches full precision."""
    ph_h = _ph({"pdhg_hot_dtype": "f32", "superstep_eps": 1e-6,
                "pdhg_eps": 1e-6, "PHIterLimit": 4})
    conv_h, eobj_h, _ = ph_h.ph_main()
    assert int(ph_h.state.promoted) == 1
    assert ph_h.pdhg_stats()["promotions_total"] >= 1
    ph_f = _ph({"superstep_eps": 1e-6, "pdhg_eps": 1e-6,
                "PHIterLimit": 4})
    conv_f, eobj_f, _ = ph_f.ph_main()
    assert eobj_h == pytest.approx(eobj_f, rel=1e-9)


def test_spopt_sparse_prep_counts_matvecs():
    # farmer's shared block density (~0.21) sits under the threshold
    ph = _ph({"pdhg_sparse_threshold": 0.3, "pdhg_eps": 1e-5})
    assert isinstance(ph.prep.A, SparseSplitA)
    st = ph.pdhg_stats()
    assert st["shared_nnz_frac"] == pytest.approx(
        float(ph.prep.A.shared_nnz_frac))
    conv, eobj, _ = ph.ph_main()
    assert ph.pdhg_stats()["sparse_matvecs"] > 0
    # dense reference: same objective, zero sparse matvecs
    ph_d = _ph({"pdhg_eps": 1e-5})
    conv_d, eobj_d, _ = ph_d.ph_main()
    assert eobj == pytest.approx(eobj_d, rel=1e-6)
    assert ph_d.pdhg_stats()["sparse_matvecs"] == 0


# --------------------------------------------------------------------------
# checkpoint: promoted flag + pre-PR-6 back-compat
# --------------------------------------------------------------------------

def test_checkpoint_promoted_roundtrip_and_pre_pr6_backcompat(tmp_path):
    ph = _ph({"pdhg_hot_dtype": "f32", "superstep_eps": 1e-6,
              "pdhg_eps": 1e-6, "PHIterLimit": 2})
    ph.ph_main(finalize=False)
    assert int(ph.state.promoted) == 1
    real = save_run_checkpoint(str(tmp_path / "prec.ckpt"), ph)
    fresh = _ph({"pdhg_hot_dtype": "f32", "superstep_eps": 1e-6,
                 "pdhg_eps": 1e-6, "PHIterLimit": 2})
    fresh.Iter0()
    load_run_checkpoint(real, fresh)
    assert int(fresh.state.promoted) == 1
    # pre-PR-6 checkpoint: strip the precision fields entirely — loads
    # must default to the f64-era values (promoted=0), not KeyError
    z = dict(np.load(real, allow_pickle=True))
    for k in ("promoted", "ladder_eps"):
        z.pop(k)
    old = str(tmp_path / "old_format.npz")
    with open(old, "wb") as f:
        np.savez(f, **z)
    older = _ph({"pdhg_hot_dtype": "f32", "superstep_eps": 1e-6,
                 "pdhg_eps": 1e-6, "PHIterLimit": 2})
    older.Iter0()
    load_run_checkpoint(old, older)
    assert int(older.state.promoted) == 0
    # the rest of the state restored identically either way
    np.testing.assert_allclose(np.asarray(older.state.W),
                               np.asarray(fresh.state.W))


# --------------------------------------------------------------------------
# AST guard: certified/EF/MIP-dive paths pin hot_dtype=None
# --------------------------------------------------------------------------

def _clone_calls(modname, funcname):
    import importlib
    mod = importlib.import_module(modname)
    tree = ast.parse(open(mod.__file__).read())
    fn = None
    for node in ast.walk(tree):
        if isinstance(node, ast.FunctionDef) and node.name == funcname:
            fn = node
    assert fn is not None, f"{funcname} not found in {modname}"
    calls = [n for n in ast.walk(fn) if isinstance(n, ast.Call)
             and isinstance(n.func, ast.Attribute)
             and n.func.attr == "clone"]
    assert calls, f"no solver.clone() call in {modname}.{funcname}"
    return calls


@pytest.mark.parametrize("modname,funcname", [
    ("mpisppy_tpu.spopt", "_certified_resolve"),
    ("mpisppy_tpu.spopt", "_promoted_pair"),
    ("mpisppy_tpu.opt.ef", "_certified_ef_resolve"),
    ("mpisppy_tpu.opt.mip", "_dive_solver"),
])
def test_certified_paths_pin_full_precision(modname, funcname):
    """Guard: every solver clone on a bound-certifying path (certified
    KKT re-solve, EF authority solve, MIP dive probes) carries an
    explicit hot_dtype=None keyword — these solves feed verdicts and
    bound decisions and must NEVER run sub-f64, no matter what hot
    dtype the parent solver was configured with."""
    for call in _clone_calls(modname, funcname):
        kw = {k.arg: k.value for k in call.keywords}
        assert "hot_dtype" in kw, (
            f"{modname}.{funcname}: clone() without explicit "
            f"hot_dtype at line {call.lineno}")
        node = kw["hot_dtype"]
        assert isinstance(node, ast.Constant) and node.value is None, (
            f"{modname}.{funcname}: clone(hot_dtype=...) must be the "
            f"literal None at line {call.lineno}")


# --------------------------------------------------------------------------
# serve: precision knobs must split compile-cache buckets
# --------------------------------------------------------------------------

def test_bucket_key_distinguishes_precision_configs():
    """serve builds ONE canonical solver per bucket from the request
    options and never routes through active_solver_prep, so promotion
    cannot thrash buckets — but two configs that differ only in the
    precision knobs must land in different buckets."""
    from mpisppy_tpu.serve.compile_cache import bucket_key

    b = farmer.build_batch(4)
    k0 = bucket_key(b, options={})
    kh = bucket_key(b, options={"pdhg_hot_dtype": "f32"})
    kb = bucket_key(b, options={"pdhg_hot_dtype": "bf16x"})
    ks = bucket_key(b, options={"pdhg_sparse_threshold": 0.3})
    assert len({k0, kh, kb, ks}) == 4


# --------------------------------------------------------------------------
# Pallas: bf16 storage, f32 accumulation (interpret mode)
# --------------------------------------------------------------------------

def _ref_chunk(A, cs, qs, lb, ub, rlo, rhi, x, y, tau, sigma, n_steps):
    """jnp replica of pallas_pdhg._chunk_kernel's body (A already in
    the compute dtype)."""
    t2, s2 = tau[:, None], sigma[:, None]
    xs, ys = jnp.zeros_like(x), jnp.zeros_like(y)
    for _ in range(n_steps):
        aty = jnp.sum(A * y[:, :, None], axis=1)
        grad = cs + qs * x + aty
        xn = jnp.clip(x - t2 * grad, lb, ub)
        xt = 2.0 * xn - x
        ax = jnp.sum(A * xt[:, None, :], axis=2)
        v = y + s2 * ax
        zc = jnp.clip(v / s2, rlo, rhi)
        yn = v - s2 * zc
        x, y, xs, ys = xn, yn, xs + xn, ys + yn
    return x, y, xs, ys


def test_pallas_chunk_bf16_storage_f32_accumulate():
    from mpisppy_tpu.ops.pallas_pdhg import fused_chunk

    rng = np.random.default_rng(7)
    S, M, N = 4, 8, 8
    f32 = jnp.float32
    A = jnp.asarray(rng.normal(size=(S, M, N)), f32)
    cs = jnp.asarray(rng.normal(size=(S, N)), f32)
    qs = jnp.asarray(rng.random((S, N)), f32)
    lb = jnp.full((S, N), -1.0, f32)
    ub = jnp.full((S, N), 1.0, f32)
    rlo = jnp.full((S, M), -0.5, f32)
    rhi = jnp.full((S, M), 0.5, f32)
    x = jnp.zeros((S, N), f32)
    y = jnp.zeros((S, M), f32)
    tau = jnp.full((S,), 0.05, f32)
    sigma = jnp.full((S,), 0.05, f32)
    A_bf = A.astype(jnp.bfloat16)

    out_bf = fused_chunk(A_bf, cs, qs, lb, ub, rlo, rhi, x, y, tau,
                         sigma, n_steps=5, interpret=True)
    # outputs stay in the COMPUTE dtype even with bf16 storage
    assert all(o.dtype == f32 for o in out_bf)
    # exact parity vs the jnp replica running the same upcast — the
    # kernel casts the tile ONCE and accumulates in f32
    ref = _ref_chunk(A_bf.astype(f32), cs, qs, lb, ub, rlo, rhi, x, y,
                     tau, sigma, 5)
    for got, want in zip(out_bf, ref):
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-5, atol=1e-6)
    # bf16 storage vs f32 storage: close at bf16 resolution
    out_f = fused_chunk(A, cs, qs, lb, ub, rlo, rhi, x, y, tau, sigma,
                        n_steps=5, interpret=True)
    for got, want in zip(out_bf, out_f):
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=0.05, atol=0.05)


# --------------------------------------------------------------------------
# dtype-aware MFU model
# --------------------------------------------------------------------------

def test_peak_flops_dtype_aware_and_never_none(monkeypatch):
    monkeypatch.delenv("TPU_PEAK_FLOPS", raising=False)
    monkeypatch.delenv("CPU_PEAK_FLOPS", raising=False)
    dev = jax.devices()[0]
    peaks = {dt: mfu_mod.device_peak_flops(dev, dtype=dt)
             for dt in ("float32", "float64", "bfloat16")}
    for dt, p in peaks.items():
        assert p is not None and p > 0, dt
    # f64 runs on a slower datapath on every backend we model
    assert peaks["float64"] < peaks["float32"]
    # CPU estimate is overridable without code changes
    monkeypatch.setenv("CPU_PEAK_FLOPS", "1e11")
    assert mfu_mod.cpu_peak_flops("float64") == 1e11
    # TPU_PEAK_FLOPS wins on EVERY backend (telemetry tests pin mfu
    # values on CPU through it)
    monkeypatch.setenv("TPU_PEAK_FLOPS", "2e12")
    assert mfu_mod.device_peak_flops(dev, dtype="float32") == 2e12


def test_pdhg_flops_density_debit_and_mfu_non_null():
    full = mfu_mod.pdhg_flops(100, 8, 24, 16)
    half = mfu_mod.pdhg_flops(100, 8, 24, 16, density=0.5)
    assert full > 0
    assert half == pytest.approx(0.5 * full)
    u = mfu_mod.mfu(full, 1.0, jax.devices()[0], dtype="float32")
    assert u is not None and u > 0
    # degenerate wall time is the ONLY None case
    assert mfu_mod.mfu(full, 0.0) is None

"""Process-replica fleet tests (serve/procpool.py + serve/procworker.py)
plus the satellites that ride with them: DRR cross-bucket dispatch
fairness, AOT prewarm/eviction lifecycle, and the pooled pipelined
wire client.

CPU-safe small process counts throughout (1-2 workers per test); every
worker inherits the conftest's 8-virtual-device XLA_FLAGS topology and
the parent's x64 flag, so batch=1 results stay bitwise-comparable to
an in-process `PH.ph_main` across the process boundary.
"""

import ast
import os
import pathlib
import signal
import socket
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from mpisppy_tpu import telemetry
from mpisppy_tpu.models import farmer
from mpisppy_tpu.opt.ph import PH
from mpisppy_tpu.serve import compile_cache as cc
from mpisppy_tpu.serve.net import protocol as P
from mpisppy_tpu.serve.net.client import PooledClient
from mpisppy_tpu.serve.router import Router
from mpisppy_tpu.serve.service import SolverService

pytestmark = pytest.mark.procserve

REPO = pathlib.Path(__file__).resolve().parents[1]

GOLDEN_OPTS = {"defaultPHrho": 1.0, "PHIterLimit": 200,
               "convthresh": 1e-5, "pdhg_eps": 1e-7}
FAST_OPTS = {"defaultPHrho": 1.0, "PHIterLimit": 4, "convthresh": 1e-4,
             "pdhg_eps": 1e-7, "superstep_eps": 1e-5}
# convthresh=0 never converges early: a deterministic fixed-length run
# that stays in flight long enough to be killed mid-batch
LONG_OPTS = {"defaultPHrho": 1.0, "PHIterLimit": 60, "convthresh": 0.0,
             "pdhg_eps": 1e-7}


@pytest.fixture
def fresh_telemetry():
    prev = telemetry._active
    telemetry.reset()
    yield
    telemetry._active = prev


# -- import contract (CI/tooling satellite) -------------------------------

def _module_level_imports(path):
    mods = set()
    for node in ast.parse(path.read_text()).body:
        if isinstance(node, ast.Import):
            mods.update(a.name for a in node.names)
        elif isinstance(node, ast.ImportFrom):
            mods.add(node.module or "")
    return mods


def test_procserve_modules_import_jax_only_lazily():
    """procworker.py (the worker entrypoint) and procpool.py (the
    parent fleet) must stay jax-lazy at module level: the worker pins
    JAX_ENABLE_X64 BEFORE jax loads, which only works if importing the
    module didn't already load it; the parent never needs jax at all to
    run a process fleet."""
    serve_dir = REPO / "mpisppy_tpu" / "serve"
    for fname in ("procworker.py", "procpool.py"):
        mods = _module_level_imports(serve_dir / fname)
        bad = {m for m in mods if m == "jax" or m.startswith("jax.")}
        assert not bad, f"{fname} imports jax at module level: {bad}"
        heavy = {m for m in mods if ".service" in m or ".compile_cache"
                 in m or m.endswith("phbase") or m.endswith("spopt")}
        assert not heavy, f"{fname} imports {heavy} at module level"


def test_procserve_import_is_jax_free_in_fresh_process():
    code = ("import sys\n"
            "import mpisppy_tpu.serve.procworker\n"
            "import mpisppy_tpu.serve.procpool\n"
            "sys.exit(1 if 'jax' in sys.modules else 0)\n")
    r = subprocess.run([sys.executable, "-c", code],
                       capture_output=True, text=True, timeout=120)
    assert r.returncode == 0, r.stderr[-2000:]


# -- pooled pipelined client (serve/net/client.py satellite) ---------------

class _MiniServer:
    """A protocol-speaking loopback peer with fault knobs: `hold` the
    first connection's first N responses back until all N requests have
    arrived (proves the client pipelines), or `drop_first` — tear the
    first connection down after reading one request without answering
    (proves reconnect-with-resend)."""

    def __init__(self, hold=0, drop_first=False):
        self.hold = hold
        self.drop_first = drop_first
        self.accepted = 0
        self.sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self.sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self.sock.bind(("127.0.0.1", 0))
        self.sock.listen(8)
        self.port = self.sock.getsockname()[1]
        self._stopped = False
        threading.Thread(target=self._accept, daemon=True).start()

    def _accept(self):
        while not self._stopped:
            try:
                conn, _ = self.sock.accept()
            except OSError:
                return
            self.accepted += 1
            threading.Thread(target=self._serve,
                             args=(conn, self.accepted),
                             daemon=True).start()

    def _serve(self, conn, conn_no):
        held = self.hold if conn_no == 1 else 0
        batch = []
        try:
            while True:
                hdr, _payload = P.read_message(conn)
                if hdr is None:
                    return
                if self.drop_first and conn_no == 1:
                    return             # vanish without answering
                resp = {"kind": "response", "ok": True,
                        "verb": hdr.get("verb"), "error_code": None,
                        "result": {"echo": hdr.get("x")}}
                if "seq" in hdr:
                    resp["seq"] = hdr["seq"]
                if held > 0:
                    batch.append(resp)
                    if len(batch) >= held:
                        for r in batch:
                            conn.sendall(P.pack_message(r))
                        batch, held = [], 0
                    continue
                conn.sendall(P.pack_message(resp))
        except (P.ProtocolError, ConnectionError, OSError):
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def close(self):
        self._stopped = True
        try:
            self.sock.close()
        except OSError:
            pass


def test_pooled_client_pipelines_on_one_connection():
    """Three concurrent calls through a pool of ONE connection, against
    a server that answers nothing until all three requests arrived: a
    request-response-lockstep client would deadlock here; the pipelined
    client has all three frames in flight at once."""
    srv = _MiniServer(hold=3)
    client = PooledClient("127.0.0.1", srv.port, pool_size=1,
                          request_timeout=20.0)
    results, errors = {}, []

    def call(i):
        try:
            resp, _ = client.call("health", x=i)
            results[i] = resp["result"]["echo"]
        except Exception as exc:       # pragma: no cover - diagnostics
            errors.append(repr(exc))

    try:
        threads = [threading.Thread(target=call, args=(i,))
                   for i in range(3)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(30)
        assert not errors, errors
        assert results == {0: 0, 1: 1, 2: 2}   # seq echo matched FIFO
        assert srv.accepted == 1               # one socket carried all
        assert client.reconnects == 0
    finally:
        client.close()
        srv.close()


def test_pooled_client_reconnects_and_resends(fresh_telemetry):
    """A peer that tears the connection down mid-request: the client
    redials and resends (idempotency keys upstream make that safe), and
    both the plain-int stats and the telemetry counters record it."""
    telemetry.configure(True)
    srv = _MiniServer(drop_first=True)
    client = PooledClient("127.0.0.1", srv.port, pool_size=1,
                          request_timeout=20.0, jitter_seed=7)
    try:
        resp, _ = client.call("health", x="again")
        assert resp["result"]["echo"] == "again"
        assert client.reconnects >= 1
        assert client.resends >= 1
        assert srv.accepted == 2
        counters = telemetry.gateway_counters()
        assert counters["client_reconnects"] >= 1
        assert counters["client_resends"] >= 1
    finally:
        client.close()
        srv.close()


def test_pooled_client_reaps_idle_connections():
    srv = _MiniServer()
    client = PooledClient("127.0.0.1", srv.port, pool_size=2,
                          idle_timeout=0.05, request_timeout=20.0)
    try:
        client.call("health", x=1)
        time.sleep(0.2)                # idle past the reap horizon
        client.call("health", x=2)
        assert client.idle_reaped == 1
        assert srv.accepted == 2       # second call dialed fresh
    finally:
        client.close()
        srv.close()


# -- DRR cross-bucket dispatch fairness (service satellite) ----------------

def test_drr_bucket_fairness_no_starvation():
    """A hot bucket streaming same-shape requests cannot starve an
    interleaved cold one: with queue [A x6, B x2] and max_batch=4 the
    DRR ring serves [4xA, 2xB, 2xA] — B jumps the queue head exactly
    once, counted in bucket_starvation and surfaced via health()."""
    svc = SolverService({"serve_max_batch": 4,
                         "serve_max_inflight": 16})
    ba = farmer.build_batch(3)
    bb = farmer.build_batch(4)         # different scenario count: new bucket
    for _ in range(6):
        svc.submit(ba, FAST_OPTS, model="farmer")
    for _ in range(2):
        svc.submit(bb, FAST_OPTS, model="farmer")

    groups = [svc._next_group() for _ in range(3)]
    sizes = [len(g) for g in groups]
    scens = [g[0].batch.num_scens for g in groups]
    assert sizes == [4, 2, 2]
    assert scens == [3, 4, 3]          # A, then B's turn, then A again
    assert svc.bucket_starvation == 1
    assert svc.health()["bucket_starvation"] == 1


# -- AOT artifact lifecycle (compile_cache satellite) ----------------------

def _fake_artifact(d, name, size, age_s):
    p = d / (name + cc._AOT_SUFFIX)
    p.write_bytes(b"x" * size)
    old = time.time() - age_s
    os.utime(p, (old, old))
    return p


def test_prune_aot_dir_by_age_and_size(tmp_path, fresh_telemetry):
    telemetry.configure(True)
    d = tmp_path / "aot"
    d.mkdir()
    _fake_artifact(d, "ancient", 100, age_s=1000)
    _fake_artifact(d, "old", 100, age_s=500)
    _fake_artifact(d, "young1", 100, age_s=50)
    _fake_artifact(d, "young2", 100, age_s=10)
    (d / "not_an_artifact.txt").write_bytes(b"ignore me")

    # age eviction: everything older than 200s goes
    assert cc.prune_aot_dir(max_age_s=200, directory=str(d)) == 2
    left = sorted(f.name for f in d.glob("*" + cc._AOT_SUFFIX))
    assert left == ["young1" + cc._AOT_SUFFIX,
                    "young2" + cc._AOT_SUFFIX]

    # size eviction: cap below the survivors' total drops oldest-first
    assert cc.prune_aot_dir(max_total_bytes=150, directory=str(d)) == 1
    left = [f.name for f in d.glob("*" + cc._AOT_SUFFIX)]
    assert left == ["young2" + cc._AOT_SUFFIX]

    # both limits None / empty dir: no-ops
    assert cc.prune_aot_dir(directory=str(d)) == 0
    assert cc.prune_aot_dir(max_age_s=1, directory=str(tmp_path / "no")) == 0
    assert (d / "not_an_artifact.txt").exists()
    counters = telemetry.gateway_counters()
    assert counters["cache_aot_evictions"] == 3


def _persist_one_artifact(tmp_path):
    """Trace + persist one real batched executable into tmp_path/aot
    (the test_net_gateway recipe)."""
    from mpisppy_tpu.serve.service import stack_superstep_args
    phs = []
    for _ in range(2):
        ph = PH(dict(FAST_OPTS), ["s0", "s1", "s2"],
                batch=farmer.build_batch(3))
        ph.Iter0()
        phs.append(ph)
    args = stack_superstep_args(phs)
    cache = cc.CompileCache()
    exe = cache.get(phs[0].batch, FAST_OPTS,
                    model="farmer").batched_superstep(args)
    assert cache.stats()["aot_saves"] == 1
    # NOTE: phs[0].batch, not a fresh build_batch(3) — PH pads the
    # batch to the device count, so a fresh unpadded batch is a
    # DIFFERENT bucket (and a different artifact fingerprint)
    return args, exe, phs[0].batch


def test_prewarm_loads_artifacts_and_serves_hits(tmp_path, monkeypatch):
    """prewarm() makes the full artifact set resident; a fresh cache's
    next build is served from the registry (counted as a prewarm hit
    AND a load) without touching the disk file."""
    monkeypatch.setenv("MPISPPY_TPU_COMPILE_CACHE_DIR", str(tmp_path))
    args, _, batch = _persist_one_artifact(tmp_path)
    (tmp_path / "aot" / ("junk" + cc._AOT_SUFFIX)).write_bytes(b"torn")
    cc.clear_prewarmed()
    try:
        assert cc.prewarm() == 1       # junk rejected, artifact resident
        cache = cc.CompileCache()
        exe = cache.get(batch, FAST_OPTS,
                        model="farmer").batched_superstep(args)
        s = cache.stats()
        assert s["aot_prewarm_hits"] == 1
        assert s["aot_loads"] == 1
        assert s["aot_saves"] == 0
        out = exe(*args)
        assert np.asarray(out.conv).shape[0] == 2
        # idempotent: a second sweep re-reads nothing new
        assert cc.prewarm() == 1
    finally:
        cc.clear_prewarmed()


# -- process-replica fleet (tentpole) --------------------------------------

def _proc_router(n, **extra):
    o = {"serve_replicas": n, "serve_replica_mode": "process",
         "serve_max_batch": 4, "router_hedge_threshold": None,
         "router_drain_deadline": 0.5, "telemetry": True}
    o.update(extra)
    return Router(o)


def test_process_mode_batch1_bitwise_equals_ph_main():
    """The acceptance bar: a batch=1 solve through a PROCESS replica —
    config JSON out, batch npz over the wire, an independent jax
    runtime in the worker, result npz back — returns bit-for-bit what
    an in-process PH.ph_main produces."""
    names = ["s0", "s1", "s2"]
    ph = PH(dict(GOLDEN_OPTS), names, batch=farmer.build_batch(3))
    conv, eobj, trivial = ph.ph_main()

    router = _proc_router(1).start()
    try:
        res = router.solve(farmer.build_batch(3), GOLDEN_OPTS,
                           scenario_names=names, model="farmer",
                           timeout=300)
        assert res["status"] == "ok"
        assert res["conv"] == conv
        assert res["eobj"] == eobj
        assert res["trivial_bound"] == trivial
        assert np.array_equal(res["xbar"], np.asarray(ph.root_xbar()))
        st = router.stats()
        assert st["replica_mode"] == "process"
        assert len(st["proc_boot_seconds"]) == 1
    finally:
        router.shutdown(timeout=15)


def test_sigkill_mid_batch_breaker_replacement_and_bitwise_replay(
        tmp_path, monkeypatch):
    """The kill -9 fault path end to end: a worker is SIGKILLed while
    executing a batch; the router's probe sees the corpse (waitpid, not
    a socket timeout), trips the breaker, boots a warm replacement
    (prewarmed from the shared AOT dir), and replays the stranded
    request — whose result is bitwise-identical to PH.ph_main, and
    whose idempotent resubmit returns the same handle and result."""
    monkeypatch.setenv("MPISPPY_TPU_COMPILE_CACHE_DIR", str(tmp_path))
    _persist_one_artifact(tmp_path)    # replacement has something to prewarm
    cc.clear_prewarmed()

    names = ["s0", "s1", "s2"]
    ph = PH(dict(LONG_OPTS), names, batch=farmer.build_batch(3))
    conv, eobj, trivial = ph.ph_main()

    router = _proc_router(2).start()
    try:
        key = "sigkill-victim"
        h = router.submit(farmer.build_batch(3), LONG_OPTS,
                          scenario_names=names, model="farmer",
                          idempotency_key=key)
        rreq = router._requests[h.id]
        # wait until the request is RUNNING on its replica, then murder
        # that worker process outright
        victim = None
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            handles = list(rreq.handles)
            if handles:
                replica, inner = handles[0]
                if replica.poll(inner) == "running":
                    victim = replica
                    break
            time.sleep(0.01)
        assert victim is not None, "request never started running"
        os.kill(victim.pid, signal.SIGKILL)

        res = router.result(h, timeout=300)
        assert res["status"] == "ok"
        # bitwise parity survives the crash-and-replay path
        assert res["conv"] == conv
        assert res["eobj"] == eobj
        assert res["trivial_bound"] == trivial
        assert np.array_equal(res["xbar"], np.asarray(ph.root_xbar()))

        st = router.stats()
        assert st["counts"].get("breaker_opens", 0) >= 1
        assert router.replica_set.replacements >= 1
        fresh = router.replica_set[victim.slot]
        assert fresh.incarnation == victim.incarnation + 1
        assert fresh.prewarm_loaded >= 1   # replacement booted warm
        assert fresh.pid != victim.pid

        # idempotent resubmit: same key -> the ORIGINAL handle and the
        # exact same terminal result
        h2 = router.submit(farmer.build_batch(3), LONG_OPTS,
                           scenario_names=names, model="farmer",
                           idempotency_key=key)
        assert h2.id == h.id
        res2 = router.result(h2, timeout=60)
        assert res2["conv"] == res["conv"]
        assert res2["eobj"] == res["eobj"]
        assert np.array_equal(res2["xbar"], res["xbar"])
    finally:
        cc.clear_prewarmed()
        router.shutdown(timeout=15)


def test_roll_under_load_process_mode_zero_failures():
    """Rolling restart of the PROCESS fleet under live traffic: every
    slot is replaced exactly once, and no in-flight request fails —
    warm_from adoption, bare-handle replay, and idempotency keys keep
    exactly-once intact across worker process swaps."""
    router = _proc_router(2).start()
    stop = threading.Event()
    outcomes, errors = [], []
    lock = threading.Lock()

    def load(i):
        try:
            k = 0
            while not stop.is_set():
                res = router.solve(farmer.build_batch(3), FAST_OPTS,
                                   model="farmer",
                                   idempotency_key=f"roll{i}-{k}",
                                   timeout=300)
                with lock:
                    outcomes.append(res["status"])
                k += 1
        except Exception as exc:       # pragma: no cover - diagnostics
            with lock:
                errors.append(repr(exc))

    try:
        threads = [threading.Thread(target=load, args=(i,))
                   for i in range(2)]
        for t in threads:
            t.start()
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            with lock:
                if outcomes:
                    break
            time.sleep(0.05)
        rolled = router.roll()
        assert rolled == 2
        time.sleep(0.5)                # keep load flowing a beat
        stop.set()
        for t in threads:
            t.join(120)
        assert not errors, errors
        assert outcomes and all(s == "ok" for s in outcomes), \
            [s for s in outcomes if s != "ok"]
        assert [r.incarnation for r in router.replica_set] == [1, 1]
        assert router.counts.get("rolled_replicas") == 2
    finally:
        stop.set()
        router.shutdown(timeout=15)

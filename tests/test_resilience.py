"""Resilience-layer tests: bound hygiene, chaos (fault injection),
spoke supervision, and crash-resumable runs.

Every failure here is INJECTED deterministically through
mpisppy_tpu/resilience/chaos.py — no timing-dependent flakiness in the
failure itself (detection latencies are bounded by tiny supervision
intervals).  The `chaos` marker keeps these selectable; they run under
tier-1's `-m 'not slow'`.
"""

import os
import time
import types

import numpy as np
import pytest

from mpisppy_tpu.cylinders.hub import PHHub
from mpisppy_tpu.cylinders.lagrangian_bounder import LagrangianOuterBound
from mpisppy_tpu.cylinders.proc import SpokeHandle
from mpisppy_tpu.cylinders.spcommunicator import Window
from mpisppy_tpu.cylinders.xhatshufflelooper_bounder import (
    XhatShuffleInnerBound)
from mpisppy_tpu.models import farmer
from mpisppy_tpu.opt.ph import PH
from mpisppy_tpu.resilience import wheel_counters
from mpisppy_tpu.resilience.bounds import BoundGuard
from mpisppy_tpu.resilience.chaos import ChaosError, ChaosInjector
from mpisppy_tpu.resilience.checkpoint import (
    checkpoint_exists, load_run_checkpoint, restore_hub,
    save_run_checkpoint)
from mpisppy_tpu.resilience.supervisor import SpokeSupervisor
from mpisppy_tpu.runtime import native
from mpisppy_tpu.spin_the_wheel import WheelSpinner
from mpisppy_tpu.utils.xhat_eval import Xhat_Eval

OPTS = {"defaultPHrho": 1.0, "PHIterLimit": 40, "convthresh": 0.0,
        "pdhg_eps": 1e-7, "pdhg_max_iters": 20000}
S = 3
NAMES = [f"scen{i}" for i in range(S)]


def farmer_wheel(spoke_specs, mode="interleaved", hub_opts=None,
                 opt_overrides=None, **ws_kwargs):
    """spoke_specs: (spoke_class, opt_class, spoke_options) triples."""
    b = farmer.build_batch(S)
    opts = {**OPTS, **(opt_overrides or {})}
    hub_dict = {
        "hub_class": PHHub,
        "hub_kwargs": {"options": {"rel_gap": 1e-4, "abs_gap": 1.0,
                                   **(hub_opts or {})}},
        "opt_class": PH,
        "opt_kwargs": {"options": opts, "all_scenario_names": NAMES,
                       "batch": b},
    }
    spoke_dicts = [
        {"spoke_class": cls, "spoke_kwargs": {"options": sp_opts or {}},
         "opt_class": opt_cls,
         "opt_kwargs": {"options": dict(opts),
                        "all_scenario_names": NAMES}}
        for cls, opt_cls, sp_opts in spoke_specs]
    return WheelSpinner(hub_dict, spoke_dicts, mode=mode, **ws_kwargs)


class TestBoundGuard:
    """Unit coverage of the window-read hygiene rules."""

    def test_rejects_non_finite(self):
        g = BoundGuard()
        for bad in (np.nan, np.inf, -np.inf):
            ok, reason = g.check("outer", bad, inner=-100.0, outer=-200.0,
                                 minimizing=True)
            assert not ok and "non-finite" in reason

    def test_rejects_wrong_direction_minimizing(self):
        g = BoundGuard(rtol=1e-2)
        # an outer bound ABOVE the incumbent by >1% is corrupt
        ok, reason = g.check("outer", -100.0, inner=-108390.0,
                             outer=-np.inf, minimizing=True)
        assert not ok and "wrong-direction" in reason
        # an inner bound BELOW the outer bound by >1% is corrupt
        ok, reason = g.check("inner", -200000.0, inner=np.inf,
                             outer=-108390.0, minimizing=True)
        assert not ok

    def test_accepts_valid_and_eps_crossings(self):
        g = BoundGuard(rtol=1e-2)
        assert g.check("outer", -108500.0, inner=-108390.0,
                       outer=-np.inf, minimizing=True)[0]
        # eps-level crossing from a loose solve stays within rtol
        assert g.check("outer", -108389.0, inner=-108390.0,
                       outer=-np.inf, minimizing=True)[0]
        # nothing to compare against yet -> accept
        assert g.check("outer", -1e9, inner=np.inf, outer=-np.inf,
                       minimizing=True)[0]

    def test_maximizing_mirrored(self):
        g = BoundGuard(rtol=1e-2)
        ok, _ = g.check("outer", 50.0, inner=100.0, outer=np.inf,
                        minimizing=False)
        assert not ok
        assert g.check("outer", 150.0, inner=100.0, outer=np.inf,
                       minimizing=False)[0]


class TestChaosInjector:
    def test_inert_by_default(self):
        c = ChaosInjector()
        assert not c.active
        c.step_tick()
        v = np.array([1.0, 2.0])
        assert c.poison(v) is v
        c.hub_iter_tick(10**9)

    def test_env_override_merges(self, monkeypatch):
        monkeypatch.setenv("MPISPPY_TPU_CHAOS",
                           '{"crash_at_step": 7}')
        c = ChaosInjector.from_options({"nan_bound": True})
        assert c.config["crash_at_step"] == 7
        assert c.config["nan_bound"] is True
        monkeypatch.setenv("MPISPPY_TPU_CHAOS", "not json")
        assert ChaosInjector.from_options({"a": 1}).config == {"a": 1}

    def test_crash_and_poison(self):
        c = ChaosInjector({"crash_at_step": 2})
        c.step_tick()
        with pytest.raises(ChaosError):
            c.step_tick()
        p = ChaosInjector({"nan_bound": True}).poison([1.0, 2.0])
        assert np.isnan(p).all()

    def test_hub_crash_at_iter(self):
        c = ChaosInjector({"crash_at_iter": 3})
        c.hub_iter_tick(2)
        with pytest.raises(ChaosError):
            c.hub_iter_tick(3)


@pytest.mark.chaos
class TestBoundHygieneWheel:
    def test_nan_bound_spoke_rejected_then_pruned(self):
        """A spoke whose published bounds are NaN-poisoned never
        corrupts Best*Bound: every message is rejected at the window
        read, the rejection counter grows, and past the budget the
        spoke is pruned like a crashed one — while the healthy inner
        spoke and the hub's own trivial bound still close the run."""
        ws = farmer_wheel(
            [(LagrangianOuterBound, PH, {"chaos": {"nan_bound": True}}),
             (XhatShuffleInnerBound, Xhat_Eval, None)],
            hub_opts={"max_bound_rejects": 3})
        ws.spin()
        hub = ws.spcomm
        assert int(hub.bound_rejects[0]) >= 3
        assert len(hub.failed_spokes) == 1
        assert "rejected bounds" in hub.failed_spokes[0][1]
        # the poison never reached the bound state
        assert np.isfinite(ws.BestOuterBound)
        assert np.isfinite(ws.BestInnerBound)
        assert abs(ws.BestInnerBound - -108390.0) < 50.0
        assert wheel_counters(ws.spcomm) == {"spoke_restarts": 0,
                                             "spokes_failed": 1}

    def test_threaded_chaos_crash_pruned(self):
        """Threaded mode: an injected ChaosError inside the spoke's
        step is reported from the spoke thread and pruned on the hub
        thread; the wheel finishes with valid bounds."""
        # crash on the FIRST step tick: the tick fires in
        # spoke_from_hub (before the expensive compiled solve), so the
        # crash lands while the hub is still iterating no matter how
        # the thread schedules around the hub's fast PH loop
        ws = farmer_wheel(
            [(LagrangianOuterBound, PH,
              {"chaos": {"crash_at_step": 1}}),
             (XhatShuffleInnerBound, Xhat_Eval, None)],
            mode="threads")
        ws.spin()
        hub = ws.spcomm
        assert len(hub.failed_spokes) == 1
        assert hub.failed_spokes[0][0] == "LagrangianOuterBound"
        assert "injected spoke crash" in hub.failed_spokes[0][1]
        assert np.isfinite(ws.BestInnerBound)
        assert np.isfinite(ws.BestOuterBound)
        assert abs(ws.BestInnerBound - -108390.0) < 50.0


def _fake_hub(n):
    hub = types.SimpleNamespace(
        spokes=[types.SimpleNamespace(proc=None, spoke_name=f"Spoke{i}")
                for i in range(n)],
        pairs=[types.SimpleNamespace(to_hub=Window(1)) for _ in range(n)],
        failed=[])
    hub._mark_spoke_failed = lambda i, exc: hub.failed.append((i, str(exc)))
    return hub


def _sleeper_spawn(spec, workdir, tag):
    import subprocess
    import sys
    p = subprocess.Popen([sys.executable, "-c",
                          "import time; time.sleep(60)"])
    return p


class TestSupervisorUnit:
    """Supervisor mechanics against an injected spawn_fn — no JAX child
    processes, so hang detection and the restart/prune ladder are
    exercised in seconds."""

    def _drive(self, sup, hub, until, timeout=30.0):
        t0 = time.monotonic()
        while not until() and time.monotonic() - t0 < timeout:
            sup.poll(force=True)
            time.sleep(0.02)
        assert until(), "supervisor never reached the expected state"

    def test_hang_detected_restarted_then_pruned(self):
        hub = _fake_hub(1)
        sup = SpokeSupervisor(
            hub, specs=[{}], workdir=".", spawn_fn=_sleeper_spawn,
            options={"supervise_interval": 0.0,
                     "spoke_hang_timeout": 0.3,
                     "spoke_max_restarts": 1,
                     "spoke_restart_backoff": 0.01,
                     "spoke_term_deadline": 2.0})
        sup.start()
        try:
            first_pid = hub.spokes[0].proc.pid
            # incarnation 0 never writes -> hung -> killed -> restarted
            self._drive(sup, hub, lambda: sup.restarts[0] == 1)
            assert sup.spoke_restarts == 1
            # incarnation 1 hangs too -> budget exhausted -> pruned
            self._drive(sup, hub, lambda: sup.spokes_failed == 1)
            assert hub.failed and hub.failed[0][0] == 0
            assert "hung" in hub.failed[0][1]
            assert all(r["hung"] for r in sup.exit_reports)
            assert len(sup.exit_reports) == 2
            # both incarnations are really dead
            assert hub.spokes[0].proc.poll() is not None
            assert first_pid in sup.killed_by_us
        finally:
            sup.kill_all()

    def test_window_writes_defer_hang_verdict(self):
        """A spoke whose write_id keeps advancing is NEVER declared
        hung, no matter how long it runs."""
        hub = _fake_hub(1)
        sup = SpokeSupervisor(
            hub, specs=[{}], workdir=".", spawn_fn=_sleeper_spawn,
            options={"supervise_interval": 0.0,
                     "spoke_hang_timeout": 0.2,
                     "spoke_max_restarts": 0})
        sup.start()
        try:
            for _ in range(10):
                hub.pairs[0].to_hub.write([1.0])   # heartbeat analog
                sup.poll(force=True)
                time.sleep(0.05)
            assert sup.spokes_failed == 0 and not hub.failed
        finally:
            sup.kill_all()

    def test_clean_exit_is_not_a_failure(self):
        hub = _fake_hub(1)

        def quick_spawn(spec, workdir, tag):
            import subprocess
            import sys
            return subprocess.Popen([sys.executable, "-c", "pass"])

        sup = SpokeSupervisor(hub, specs=[{}], workdir=".",
                              spawn_fn=quick_spawn,
                              options={"supervise_interval": 0.0})
        sup.start()
        hub.spokes[0].proc.wait(timeout=30)
        sup.poll(force=True)
        assert sup.state[0] == "stopped"
        assert sup.spokes_failed == 0 and not sup.exit_reports


class TestAtomicSolutionFile:
    def test_malformed_sol_file_degrades_to_none(self, tmp_path):
        p = tmp_path / "pair0.sol.npy"
        p.write_bytes(b"\x93NUMPY garbage not a real file")
        h = SpokeHandle(LagrangianOuterBound, 1, 1, sol_path=str(p))
        assert h.best_solution is None

    def test_missing_sol_file(self, tmp_path):
        h = SpokeHandle(LagrangianOuterBound, 1, 1,
                        sol_path=str(tmp_path / "nope.sol.npy"))
        assert h.best_solution is None


@pytest.mark.chaos
class TestCheckpointResume:
    def _ph(self, extra=None):
        b = farmer.build_batch(S)
        opts = {**OPTS, "PHIterLimit": 8, **(extra or {})}
        return PH(opts, NAMES, batch=b)

    def test_crash_at_iter_then_resume_replays(self, tmp_path):
        """A run killed at iter 4 (chaos, AFTER that iteration's
        checkpoint) and resumed from the checkpoint lands on the same
        W/xbar/conv as the uninterrupted run — full-PHState restore
        makes the resumed trajectory a bit-replay."""
        ck = str(tmp_path / "run.ckpt")
        ph_a = self._ph()
        conv_a, _, triv_a = ph_a.ph_main(finalize=False)

        ph_b = self._ph({"run_checkpoint": ck,
                         "chaos": {"crash_at_iter": 4}})
        with pytest.raises(ChaosError):
            ph_b.ph_main(finalize=False)
        assert checkpoint_exists(ck)
        assert int(np.load(ck + ".npz")["it"]) == 4

        ph_c = self._ph({"resume_from": ck})
        conv_c, _, triv_c = ph_c.ph_main(finalize=False)
        assert int(ph_c.state.it) == int(ph_a.state.it) == 8
        assert triv_c == pytest.approx(triv_a)
        assert conv_c == pytest.approx(conv_a, rel=1e-8, abs=1e-12)
        np.testing.assert_allclose(np.asarray(ph_c.state.W),
                                   np.asarray(ph_a.state.W),
                                   rtol=1e-9, atol=1e-9)
        np.testing.assert_allclose(np.asarray(ph_c.state.xbar),
                                   np.asarray(ph_a.state.xbar),
                                   rtol=1e-9, atol=1e-9)

    def test_missing_checkpoint_falls_through_to_fresh(self, tmp_path):
        ph = self._ph({"resume_from": str(tmp_path / "absent.ckpt")})
        conv, _, triv = ph.ph_main(finalize=False)
        assert np.isfinite(triv) and int(ph.state.it) == 8

    def test_shape_mismatch_rejected(self, tmp_path):
        ck = str(tmp_path / "bad.ckpt")
        ph = self._ph({"PHIterLimit": 1})
        ph.ph_main(finalize=False)
        save_run_checkpoint(ck, ph)
        # different nonant count (device padding can make two scenario
        # counts agree, so vary K, not S)
        other = PH(dict(OPTS, PHIterLimit=1), NAMES,
                   batch=farmer.build_batch(S, crops_multiplier=2))
        other.ph_main(finalize=False)
        with pytest.raises(ValueError, match="does not match"):
            load_run_checkpoint(ck, other)

    def test_atomic_write_no_torn_tmp(self, tmp_path):
        ck = str(tmp_path / "atomic.ckpt")
        ph = self._ph({"PHIterLimit": 1})
        ph.ph_main(finalize=False)
        real = save_run_checkpoint(ck, ph)
        assert os.path.exists(real)
        assert not os.path.exists(real + ".tmp")

    def test_wheel_resume_restores_hub_bounds(self, tmp_path):
        """WheelSpinner(resume_from=...) restores BestInner/OuterBound
        and the incumbent, not just the optimizer state."""
        ck = str(tmp_path / "wheel.ckpt")
        ws_a = farmer_wheel(
            [(XhatShuffleInnerBound, Xhat_Eval, None)],
            opt_overrides={"PHIterLimit": 6, "run_checkpoint": ck})
        ws_a.spin()
        assert checkpoint_exists(ck)
        # resumed wheel: checkpointed iter == PHIterLimit, so zero new
        # iterations — every bound it reports came from the checkpoint
        ws_b = farmer_wheel([], opt_overrides={"PHIterLimit": 6},
                            resume_from=ck)
        ws_b.spin()
        assert ws_b.BestOuterBound == pytest.approx(ws_a.BestOuterBound)
        if np.isfinite(ws_a.BestInnerBound):
            assert ws_b.BestInnerBound == pytest.approx(
                ws_a.BestInnerBound)
        sol_a, sol_b = ws_a.best_nonant_solution(), \
            ws_b.best_nonant_solution()
        assert sol_b is not None
        np.testing.assert_allclose(np.asarray(sol_b), np.asarray(sol_a),
                                   rtol=1e-9, atol=1e-9)

    def test_restore_hub_unit(self, tmp_path):
        ck = str(tmp_path / "hubside.ckpt")
        ph = self._ph({"PHIterLimit": 1})
        ph.ph_main(finalize=False)
        ph.spcomm = types.SimpleNamespace(
            BestInnerBound=-108000.0, BestOuterBound=-109000.0,
            best_nonant_solution=np.array([1.0, 2.0, 3.0]))
        save_run_checkpoint(ck, ph)
        fresh = types.SimpleNamespace(BestInnerBound=np.inf,
                                      BestOuterBound=-np.inf,
                                      best_nonant_solution=None)
        restore_hub(ck, fresh)
        assert fresh.BestInnerBound == -108000.0
        assert fresh.BestOuterBound == -109000.0
        np.testing.assert_array_equal(fresh.best_nonant_solution,
                                      [1.0, 2.0, 3.0])


class _SupervisedChaosHub(PHHub):
    """Test hub: spins until the supervisor has pruned a spoke (or a
    wall-clock safety valve), so the PH loop deterministically outlives
    the spawn -> crash -> restart -> crash -> prune sequence regardless
    of child JAX start-up time."""

    WALL_LIMIT_S = 240.0

    def setup_hub(self):
        super().setup_hub()
        self._t0 = time.monotonic()

    def is_converged(self):
        super().is_converged()          # seeds the trivial outer bound
        if self.supervisor is not None and self.supervisor.spokes_failed:
            return True
        # keep the loop cheap while waiting on child process lifecycles
        time.sleep(0.02)
        return time.monotonic() - self._t0 > self.WALL_LIMIT_S


@pytest.mark.chaos
@pytest.mark.skipif(not native.available(),
                    reason="native exchange library unavailable")
def test_multiproc_crashed_spoke_restarted_then_pruned():
    """End-to-end multiproc supervision: a spoke process that hard-exits
    (os._exit, the SIGKILL stand-in — no cleanup, no goodbye) is
    detected via Popen.poll, restarted once from its declarative spec,
    and permanently pruned when the second incarnation dies too; the
    hub finishes on its own valid bounds and surfaces both exits (code
    + log tail) in its final report."""
    b = farmer.build_batch(S)
    batch_spec = {"module": "mpisppy_tpu.models.farmer",
                  "builder": "build_batch",
                  "kwargs": {"num_scens": S}}
    chaos = {"crash_at_step": 3, "hard_exit": True}
    hub_dict = {
        "hub_class": _SupervisedChaosHub,
        "hub_kwargs": {"options": {
            "supervise_interval": 0.05,
            "spoke_max_restarts": 1,
            "spoke_restart_backoff": 0.1,
            "shutdown_join_timeout": 30.0}},
        "opt_class": PH,
        "opt_kwargs": {"options": dict(OPTS, PHIterLimit=10**6),
                       "all_scenario_names": NAMES, "batch": b},
    }
    spoke_dicts = [
        {"spoke_class": LagrangianOuterBound, "opt_class": PH,
         "spoke_kwargs": {"options": {"chaos": chaos}},
         "opt_kwargs": {"options": dict(OPTS),
                        "all_scenario_names": NAMES},
         "proc": {"batch": batch_spec}},
    ]
    ws = WheelSpinner(hub_dict, spoke_dicts, mode="multiproc").spin()
    hub = ws.spcomm
    sup = hub.supervisor
    assert sup.spoke_restarts == 1, "spoke was not restarted exactly once"
    assert sup.spokes_failed == 1, "spoke was not pruned after the budget"
    assert len(hub.failed_spokes) == 1
    assert hub.failed_spokes[0][0] == "LagrangianOuterBound"
    # both incarnations' exits were recorded with the chaos exit code
    assert len(sup.exit_reports) == 2
    assert [r["rc"] for r in sup.exit_reports] == [13, 13]
    assert [r["incarnation"] for r in sup.exit_reports] == [0, 1]
    assert hub.spoke_exit_reports is sup.exit_reports
    # the wheel still ends with the hub's own valid outer bound
    assert np.isfinite(ws.BestOuterBound)
    assert ws.BestOuterBound <= -108000.0
    assert wheel_counters(ws) == {"spoke_restarts": 1, "spokes_failed": 1}


@pytest.mark.chaos
@pytest.mark.skipif(not native.available(),
                    reason="native exchange library unavailable")
def test_multiproc_healthy_run_counters_zero():
    """Supervised healthy multiproc run: delayed window writes (chaos
    delay injector) are tolerated, counters stay zero, children exit
    rc=0 on the kill signal, and the bounds still bracket."""
    b = farmer.build_batch(S)
    batch_spec = {"module": "mpisppy_tpu.models.farmer",
                  "builder": "build_batch",
                  "kwargs": {"num_scens": S}}
    hub_dict = {
        "hub_class": PHHub,
        "hub_kwargs": {"options": {"rel_gap": 1e-4,
                                   "supervise_interval": 0.1,
                                   "shutdown_join_timeout": 60.0}},
        "opt_class": PH,
        "opt_kwargs": {"options": dict(OPTS, PHIterLimit=25),
                       "all_scenario_names": NAMES, "batch": b},
    }
    spoke_dicts = [
        {"spoke_class": LagrangianOuterBound, "opt_class": PH,
         "spoke_kwargs": {"options": {
             "chaos": {"delay_write_s": 0.01},
             "heartbeat_interval": 0.2}},
         "opt_kwargs": {"options": dict(OPTS),
                        "all_scenario_names": NAMES},
         "proc": {"batch": batch_spec}},
    ]
    ws = WheelSpinner(hub_dict, spoke_dicts, mode="multiproc").spin()
    hub = ws.spcomm
    for h in hub.spokes:
        assert h.proc is not None and h.proc.returncode == 0
    assert wheel_counters(ws) == {"spoke_restarts": 0, "spokes_failed": 0}
    assert not hub.supervisor.exit_reports
    assert np.isfinite(ws.BestOuterBound)
    assert ws.BestOuterBound <= -108389.0

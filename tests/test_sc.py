"""SchurComplement IPM tests (reference analog: mpisppy/tests/test_sc.py
— farmer objective via the Schur-complement interior point)."""

import numpy as np
import pytest

from mpisppy_tpu.models import farmer
from mpisppy_tpu.opt.sc import SchurComplement


def test_sc_farmer_objective():
    names = [f"scen{i}" for i in range(3)]
    sc = SchurComplement({}, names, batch=farmer.build_batch(3))
    obj, x = sc.solve()
    # reference test_sc checks the farmer objective (-108390)
    assert obj == pytest.approx(-108390.0, abs=120.0)
    assert np.allclose(x, [170.0, 80.0, 250.0], atol=3.0)


def test_sc_rejects_integers():
    names = [f"scen{i}" for i in range(3)]
    with pytest.raises(RuntimeError, match="continuous"):
        SchurComplement({}, names,
                        batch=farmer.build_batch(3, use_integer=True))


def test_sc_scales_with_scenarios():
    names = [f"scen{i}" for i in range(10)]
    sc = SchurComplement({}, names, batch=farmer.build_batch(10))
    obj, x = sc.solve()
    # scipy/HiGHS EF value for the 10-scenario perturbed farmer is
    # -122146.7; the interior point must land just above it
    assert obj == pytest.approx(-122146.7, rel=2e-3)
    assert obj >= -122147.0
    assert np.all(x >= -1e-6)

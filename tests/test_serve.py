"""Serve-layer tests: compile cache bucketing, request coalescing,
admission control, deadlines, worker supervision, and the batch=1
bitwise-parity guarantee against PH.ph_main (ISSUE 4 acceptance).

All tests here are tier-1 (`serve` marker, no `slow`): farmer-sized
batches, and every service in this file uses the SAME solver config so
the process-shared jit registries (phbase.fused_superstep,
ops.pdhg.shared_solve_jit) amortize compiles across tests.
"""

import ast
import pathlib
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from mpisppy_tpu import telemetry
from mpisppy_tpu.models import farmer
from mpisppy_tpu.opt.ph import PH
from mpisppy_tpu.serve import compile_cache as cc
from mpisppy_tpu.serve.service import SolverService

pytestmark = pytest.mark.serve

REPO = pathlib.Path(__file__).resolve().parents[1]

# the golden-parity options (tests/test_ph_farmer.py's fixture config)
GOLDEN_OPTS = {"defaultPHrho": 1.0, "PHIterLimit": 200,
               "convthresh": 1e-5, "pdhg_eps": 1e-7}
# quick-loop options: SAME solver config (pdhg_eps keys the jit
# registries), loose superstep tolerance + tiny iteration budget
FAST_OPTS = {"defaultPHrho": 1.0, "PHIterLimit": 4, "convthresh": 1e-4,
             "pdhg_eps": 1e-7, "superstep_eps": 1e-5}


@pytest.fixture
def fresh_telemetry():
    prev = telemetry._active
    telemetry.reset()
    yield
    telemetry._active = prev


# -- import contract (the telemetry-guard pattern) ------------------------

def _module_level_imports(path):
    mods = set()
    for node in ast.parse(path.read_text()).body:
        if isinstance(node, ast.Import):
            mods.update(a.name for a in node.names)
        elif isinstance(node, ast.ImportFrom):
            mods.add(node.module or "")
    return mods


def test_api_imports_jax_only_lazily():
    """serve/api.py (and the package front door) must be free to
    import: no module-level jax, directly or transitively.  The PR 11
    traffic layer (router.py, replica.py) is held to the same bar —
    the front door must be constructible in a process that never
    initializes a backend until a replica dispatches."""
    serve_dir = REPO / "mpisppy_tpu" / "serve"
    for fname in ("api.py", "__init__.py", "request.py",
                  "router.py", "replica.py"):
        mods = _module_level_imports(serve_dir / fname)
        bad = {m for m in mods
               if m == "jax" or m.startswith("jax.")}
        assert not bad, f"{fname} imports jax at module level: {bad}"
        # transitive heavyweights would smuggle jax in too
        heavy = {m for m in mods if ".service" in m or ".compile_cache"
                 in m or m.endswith("phbase") or m.endswith("spopt")}
        assert not heavy, f"{fname} imports {heavy} at module level"


def test_api_import_is_jax_free_in_fresh_process():
    code = ("import sys\n"
            "import mpisppy_tpu.serve.api\n"
            "import mpisppy_tpu.serve\n"
            "import mpisppy_tpu.serve.router\n"
            "import mpisppy_tpu.serve.replica\n"
            "sys.exit(1 if 'jax' in sys.modules else 0)\n")
    r = subprocess.run([sys.executable, "-c", code],
                       capture_output=True, text=True, timeout=120)
    assert r.returncode == 0, r.stderr[-2000:]


# -- shared jit registries ------------------------------------------------

def test_solver_jit_shared_across_instances():
    from mpisppy_tpu.ops.pdhg import PDHGSolver
    a = PDHGSolver(eps=1e-7)
    b = PDHGSolver(eps=1e-7)
    c = PDHGSolver(eps=1e-6)
    assert a._solve_jit is b._solve_jit
    assert a._solve_jit is not c._solve_jit
    assert a.config_key() == b.config_key() != c.config_key()


def test_superstep_shared_across_ph_instances():
    b = farmer.build_batch(3)
    ph1 = PH(dict(FAST_OPTS), ["s0", "s1", "s2"], batch=b)
    ph2 = PH(dict(FAST_OPTS), ["s0", "s1", "s2"],
             batch=farmer.build_batch(3))
    assert ph1._superstep is ph2._superstep
    assert ph1.solver._solve_jit is ph2.solver._solve_jit


# -- platform satellite ---------------------------------------------------

def test_enable_compile_cache_env_dir(tmp_path, monkeypatch):
    import jax

    from mpisppy_tpu.utils import platform
    monkeypatch.delenv("JAX_COMPILATION_CACHE_DIR", raising=False)
    monkeypatch.setenv("MPISPPY_TPU_COMPILE_CACHE_DIR",
                       str(tmp_path / "cc"))
    old = jax.config.jax_compilation_cache_dir
    try:
        got = platform.enable_compile_cache()
        assert got == str(tmp_path / "cc")
        assert jax.config.jax_compilation_cache_dir == got
    finally:
        jax.config.update("jax_compilation_cache_dir", old)


def test_enable_compile_cache_alias():
    from mpisppy_tpu.utils import platform
    assert platform.enable_compile_cache_if_cpu \
        is platform.enable_compile_cache


def test_restart_delay_shared_policy():
    from mpisppy_tpu.resilience import restart_delay
    assert restart_delay(1, 0.5, 30.0) == 0.5
    assert restart_delay(3, 0.5, 30.0) == 2.0
    assert restart_delay(10, 0.5, 4.0) == 4.0


# -- compile cache --------------------------------------------------------

def test_bucket_key_separates_shapes_and_config():
    b3, b4 = farmer.build_batch(3), farmer.build_batch(4)
    k3 = cc.bucket_key(b3, FAST_OPTS)
    assert k3 == cc.bucket_key(farmer.build_batch(3), dict(FAST_OPTS))
    assert k3 != cc.bucket_key(b4, FAST_OPTS)
    assert k3 != cc.bucket_key(b3, dict(FAST_OPTS, pdhg_eps=1e-6))
    assert cc.bucket_key(b3, FAST_OPTS, model="farmer") \
        != cc.bucket_key(b3, FAST_OPTS, model="other")


def test_cache_counts_hits_and_misses():
    cache = cc.CompileCache()
    b3 = farmer.build_batch(3)
    e1 = cache.get(b3, FAST_OPTS)
    e2 = cache.get(farmer.build_batch(3), FAST_OPTS)
    assert e1 is e2
    cache.get(farmer.build_batch(4), FAST_OPTS)
    assert cache.stats() == {"hits": 1, "misses": 2, "buckets": 2,
                             "aot_loads": 0, "aot_load_failures": 0,
                             "aot_saves": 0, "aot_export_failures": 0,
                             "aot_prewarm_hits": 0}


# -- admission control (no dispatch thread needed) ------------------------

def test_admission_queue_full():
    svc = SolverService({"serve_max_queue": 1})
    b = farmer.build_batch(3)
    h1 = svc.submit(b, FAST_OPTS)
    h2 = svc.submit(b, FAST_OPTS)
    assert svc.poll(h1) == "queued"
    res = svc.result(h2, timeout=1)
    assert res["status"] == "rejected" and res["reason"] == "queue_full"


def test_admission_max_inflight():
    svc = SolverService({"serve_max_inflight": 1})
    b = farmer.build_batch(3)
    svc.submit(b, FAST_OPTS)
    res = svc.result(svc.submit(b, FAST_OPTS), timeout=1)
    assert res["status"] == "rejected"
    assert res["reason"] == "max_inflight"


def test_result_never_hangs_and_unknown_handle():
    from mpisppy_tpu.serve.request import RequestHandle
    svc = SolverService()   # worker never started
    h = svc.submit(farmer.build_batch(3), FAST_OPTS)
    t0 = time.monotonic()
    res = svc.result(h, timeout=0.2)
    assert time.monotonic() - t0 < 5.0
    assert res["status"] == "timeout" and res["where"] == "result_wait"
    assert svc.poll(RequestHandle(999)) == "unknown"
    assert svc.result(RequestHandle(999))["status"] == "unknown"


def test_shutdown_rejects_leftovers_and_later_submits():
    svc = SolverService()
    h = svc.submit(farmer.build_batch(3), FAST_OPTS)
    svc.shutdown(timeout=1)
    assert svc.result(h, timeout=1)["status"] == "rejected"
    res = svc.result(svc.submit(farmer.build_batch(3), FAST_OPTS),
                     timeout=1)
    assert res["status"] == "rejected" and res["reason"] == "shutdown"


# -- golden parity (acceptance) -------------------------------------------

def test_batch1_result_bitwise_equals_ph_main():
    """The api.py guarantee: a service solve at batch=1 runs the SAME
    process-shared compiled superstep as PH.ph_main — the result is
    bitwise identical, and matches the farmer goldens."""
    names = [f"scen{i}" for i in range(3)]
    ph = PH(dict(GOLDEN_OPTS), names, batch=farmer.build_batch(3))
    conv, eobj, trivial = ph.ph_main()

    svc = SolverService().start()
    try:
        res = svc.solve(farmer.build_batch(3), GOLDEN_OPTS,
                        scenario_names=names, model="farmer")
    finally:
        svc.shutdown()
    assert res["status"] == "ok"
    # bitwise: plain float equality, no tolerance
    assert res["conv"] == conv
    assert res["eobj"] == eobj
    assert res["trivial_bound"] == trivial
    assert np.array_equal(res["xbar"], np.asarray(ph.root_xbar()))
    # goldens (tests/test_ph_farmer.py values)
    assert abs(res["eobj"] - -108390.0) < 20
    assert abs(res["trivial_bound"] - -115405.55) < 5
    assert np.allclose(res["xbar"], [170.0, 80.0, 250.0], atol=0.5)


# -- concurrency + compile-cache acceptance -------------------------------

def test_eight_concurrent_requests_single_compile(fresh_telemetry):
    """8 concurrent same-bucket requests: exactly one compile-cache
    miss, >= 7 hits — asserted on the service cache AND the
    serve.compile_cache.* telemetry counters."""
    svc = SolverService({"serve_max_batch": 8, "serve_max_inflight": 32,
                         "telemetry": True})
    handles = []
    hs_lock = threading.Lock()

    def client(i):
        h = svc.submit(farmer.build_batch(3), FAST_OPTS, model="farmer")
        with hs_lock:
            handles.append(h)

    threads = [threading.Thread(target=client, args=(i,))
               for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    svc.start()
    try:
        results = [svc.result(h, timeout=600) for h in handles]
    finally:
        svc.shutdown()
    assert [r["status"] for r in results] == ["ok"] * 8
    # same model data -> identical solutions
    assert len({r["eobj"] for r in results}) == 1
    st = svc.cache.stats()
    assert st["misses"] == 1
    assert st["hits"] >= 7
    counters = svc._tel.registry._counters
    assert counters["serve.compile_cache.miss"].value == 1
    assert counters["serve.compile_cache.hit"].value >= 7
    assert telemetry.serve_counters(svc._tel.registry)[
        "serve_requests_ok"] == 8


# -- coalescing edge cases ------------------------------------------------

def test_mixed_shape_buckets_interleaved():
    """Interleaved S=3 / S=4 requests: dispatch must coalesce only
    same-bucket neighbors (skipping the other bucket without starving
    it), and every request completes with its own model's answer."""
    svc = SolverService({"serve_max_batch": 4})
    reqs = []
    for i in range(2):
        reqs.append(("s3", svc.submit(farmer.build_batch(3), FAST_OPTS)))
        reqs.append(("s4", svc.submit(farmer.build_batch(4), FAST_OPTS)))
    svc.start()
    try:
        results = {(kind, h.id): svc.result(h, timeout=600)
                   for kind, h in reqs}
    finally:
        svc.shutdown()
    assert all(r["status"] == "ok" for r in results.values())
    eobj3 = {r["eobj"] for (k, _), r in results.items() if k == "s3"}
    eobj4 = {r["eobj"] for (k, _), r in results.items() if k == "s4"}
    assert len(eobj3) == 1 and len(eobj4) == 1
    assert eobj3 != eobj4      # genuinely different problems
    assert svc.cache.stats()["misses"] == 2   # one per bucket
    assert svc.cache.stats()["hits"] == 2


def test_deadline_expiry_mid_batch():
    """Two coalesced requests; one can never converge and carries a
    deadline — it must come back as a structured timeout at some
    iteration while its batchmate finishes OK."""
    svc = SolverService({"serve_max_batch": 4})
    ok_h = svc.submit(farmer.build_batch(3), FAST_OPTS)
    doomed_h = svc.submit(
        farmer.build_batch(3),
        dict(FAST_OPTS, PHIterLimit=10 ** 6, convthresh=0.0),
        deadline=3.0)
    svc.start()
    try:
        ok_res = svc.result(ok_h, timeout=600)
        doomed_res = svc.result(doomed_h, timeout=600)
    finally:
        svc.shutdown()
    assert ok_res["status"] == "ok"
    assert doomed_res["status"] == "timeout"
    assert doomed_res["where"] == "iteration"
    assert doomed_res["iterations"] >= 1


def test_deadline_expired_while_queued():
    svc = SolverService()
    h = svc.submit(farmer.build_batch(3), FAST_OPTS, deadline=0.05)
    time.sleep(0.2)
    svc.start()
    try:
        res = svc.result(h, timeout=60)
    finally:
        svc.shutdown()
    assert res["status"] == "timeout"
    assert res["where"] in ("queued", "dispatch")


# -- worker supervision (resilience integration) --------------------------

@pytest.mark.chaos
def test_worker_crash_restart_then_recover():
    """crash_at_iter counts dispatches: the first dispatch crashes, the
    supervisor requeues the in-flight request and restarts the worker,
    the second dispatch succeeds."""
    svc = SolverService({"chaos": {"crash_at_iter": 1},
                         "serve_max_attempts": 3,
                         "serve_max_restarts": 2,
                         "serve_restart_backoff": 0.05})
    h = svc.submit(farmer.build_batch(3), FAST_OPTS)
    svc.start()
    try:
        res = svc.result(h, timeout=600)
    finally:
        svc.shutdown()
    assert res["status"] == "ok"
    assert svc.restarts == 1


@pytest.mark.chaos
def test_worker_crash_budget_exhausted_fails_service():
    """crash_at_step crashes EVERY dispatch: once the restart budget is
    spent the service fails closed — queued requests get structured
    FAILED results and later submits are rejected."""
    svc = SolverService({"chaos": {"crash_at_step": 1},
                         "serve_max_attempts": 10,
                         "serve_max_restarts": 1,
                         "serve_restart_backoff": 0.05})
    h = svc.submit(farmer.build_batch(3), FAST_OPTS)
    svc.start()
    res = svc.result(h, timeout=60)
    assert res["status"] == "failed"
    assert svc._failed is not None
    late = svc.result(svc.submit(farmer.build_batch(3), FAST_OPTS),
                      timeout=5)
    assert late["status"] == "rejected"
    assert late["reason"] == "service_failed"


# -- graceful drain + warm restart (PR 10) --------------------------------

def test_drain_checkpoints_leftovers_and_closes_admission(tmp_path):
    svc = SolverService()                # worker never started: the
    h = svc.submit(farmer.build_batch(3), FAST_OPTS)  # request stays queued
    p = str(tmp_path / "drain")
    out = svc.drain(deadline=0.3, checkpoint_path=p)
    assert out["drained"] == 1
    assert out["checkpoint"] is not None and out["checkpoint"].endswith(".npz")
    # the leftover got a structured rejection, never a hang
    res = svc.result(h, timeout=1)
    assert res["status"] == "rejected" and res["reason"] == "drained"
    # the saved request round-trips with host-numpy leaves
    from mpisppy_tpu.resilience.checkpoint import load_drain_checkpoint
    saved = load_drain_checkpoint(p)
    assert len(saved) == 1 and saved[0]["options"] == FAST_OPTS
    assert isinstance(saved[0]["batch"].c, np.ndarray)


def test_submit_during_drain_rejects_with_draining():
    svc = SolverService()
    with svc._work:
        svc._draining = True             # admission closed mid-drain
    res = svc.result(svc.submit(farmer.build_batch(3), FAST_OPTS),
                     timeout=1)
    assert res["status"] == "rejected" and res["reason"] == "draining"


def test_drain_empty_service_is_a_noop():
    svc = SolverService()
    out = svc.drain(deadline=0.1, checkpoint_path=None)
    assert out == {"drained": 0, "checkpoint": None}


def test_warm_from_resubmits_and_solves(tmp_path):
    """Full drain -> restart cycle: service 1 drains a queued request
    to disk, a fresh service 2 warms from the file and actually solves
    it."""
    p = str(tmp_path / "drain_cycle")
    svc1 = SolverService()
    svc1.submit(farmer.build_batch(3), FAST_OPTS)
    out = svc1.drain(deadline=0.3, checkpoint_path=p)
    assert out["drained"] == 1

    svc2 = SolverService()
    try:
        handles = svc2.warm_from(p)
        assert [old_id for old_id, _ in handles] == [1]
        res = svc2.result(handles[0][1], timeout=120)
        assert res["status"] == "ok"
        assert np.isfinite(res["conv"])
    finally:
        svc2.shutdown()


# -- api error paths (module-global front door) ----------------------------

def _api_isolated():
    """Import serve.api and stash the process-global router so these
    tests can't leak state into (or inherit it from) other tests."""
    from mpisppy_tpu.serve import api
    return api


@pytest.fixture
def api_mod():
    api = _api_isolated()
    prev = api._router
    api._router = None
    yield api
    api.shutdown_service(timeout=30)
    api._router = prev


def test_api_result_unknown_handle(api_mod):
    """result()/poll() on a handle nobody minted: structured `unknown`,
    never an exception — both before the service exists and against a
    live router that has no such request id."""
    from mpisppy_tpu.serve.request import RouterHandle

    ghost = RouterHandle(id=10**9)
    # no service started at all
    assert api_mod.get_service() is None
    assert api_mod.poll(ghost) == "unknown"
    res = api_mod.result(ghost)
    assert res == {"status": "unknown", "request_id": ghost.id}
    # live router, unknown id: same contract (and still no exception)
    api_mod.start_service({"serve_replicas": 1})
    assert api_mod.poll(ghost) == "unknown"
    res = api_mod.result(ghost, timeout=0.1)
    assert res["status"] == "unknown" and res["request_id"] == ghost.id


def test_api_double_shutdown_is_noop(api_mod):
    """shutdown_service() twice: the second call finds no router and
    returns without error (idempotent teardown)."""
    api_mod.start_service({"serve_replicas": 1})
    assert api_mod.get_service() is not None
    api_mod.shutdown_service(timeout=30)
    assert api_mod.get_service() is None
    api_mod.shutdown_service(timeout=30)     # must not raise
    assert api_mod.get_service() is None


def test_api_start_after_shutdown_gets_fresh_router(api_mod):
    """start_service after shutdown_service builds a FRESH router —
    the old object is gone, and handles minted by the dead router are
    `unknown` to its replacement."""
    r1 = api_mod.start_service({"serve_replicas": 1})
    api_mod.shutdown_service(timeout=30)
    r2 = api_mod.start_service({"serve_replicas": 1})
    assert r2 is not r1
    assert api_mod.get_service() is r2
    # a handle from the dead incarnation means nothing to the new one
    from mpisppy_tpu.serve.request import RouterHandle
    stale = RouterHandle(id=1)
    assert api_mod.poll(stale) == "unknown"
    assert api_mod.result(stale)["status"] == "unknown"

"""Replica-set front-door tests (PR 11): circuit breakers, hedged
retries + idempotency, tenant quotas, brownout ladder, poison
quarantine, replace-and-replay — plus the chaos-on open-load
acceptance test and the warm_from corruption regression.

Two tiers inside this file:
  * pure-router unit tests drive `Router` against FAKE replicas (no
    jax, milliseconds) — the traffic logic is jax-free by contract, so
    it is testable without a backend;
  * the acceptance tests run REAL replicas (SolverService) under
    injected chaos with farmer-sized batches.
"""

import itertools
import pathlib
import threading
import time
import types

import numpy as np
import pytest

from mpisppy_tpu import telemetry
from mpisppy_tpu.serve.router import (CircuitBreaker, Router, TokenBucket)

pytestmark = pytest.mark.serve

REPO = pathlib.Path(__file__).resolve().parents[1]

FAST_OPTS = {"defaultPHrho": 1.0, "PHIterLimit": 4, "convthresh": 1e-4,
             "pdhg_eps": 1e-7, "superstep_eps": 1e-5}


def _wait_for(cond, timeout=5.0, interval=0.005):
    end = time.monotonic() + timeout
    while time.monotonic() < end:
        if cond():
            return True
        time.sleep(interval)
    return cond()


def _is_subsequence(needle, haystack):
    it = iter(haystack)
    return all(x in it for x in needle)


# -- breaker + bucket state machines (no replicas at all) ------------------

class TestCircuitBreaker:
    def test_traversal_closed_open_half_open_closed(self):
        br = CircuitBreaker(fail_threshold=2, backoff=1.0, backoff_cap=8.0)
        t = 100.0
        assert br.allow(t)
        br.record_failure(t)
        assert br.state == "closed"          # below threshold
        br.record_failure(t)
        assert br.state == "open"            # tripped
        assert not br.allow(t + 0.5)         # reopen timer not expired
        assert br.allow(t + 1.1)             # probe flips to half-open
        assert br.state == "half_open"
        br.record_success(t + 1.2)
        assert br.state == "closed"
        assert _is_subsequence(
            ["closed", "open", "half_open", "closed"], br.states_seen())

    def test_half_open_failure_reopens_with_longer_backoff(self):
        br = CircuitBreaker(fail_threshold=1, backoff=1.0, backoff_cap=8.0)
        t = 10.0
        br.record_failure(t)                 # trip 1: reopen_at = t + 1
        assert br.reopen_at == pytest.approx(t + 1.0)
        assert br.allow(t + 1.5)             # half-open probe
        br.record_failure(t + 1.5)           # probe fails: trip 2
        assert br.state == "open"
        assert br.reopen_at == pytest.approx(t + 1.5 + 2.0)  # 2^1 * backoff
        assert br.opens == 2

    def test_reopen_backoff_is_capped(self):
        br = CircuitBreaker(fail_threshold=1, backoff=1.0, backoff_cap=3.0)
        t = 0.0
        for _ in range(6):                   # trip over and over
            br.trip(t)
            assert br.reopen_at - t <= 3.0 + 1e-9
            t = br.reopen_at
            assert br.allow(t)               # half-open
        assert br.opens == 6

    def test_success_resets_failure_count(self):
        br = CircuitBreaker(fail_threshold=3)
        t = 0.0
        br.record_failure(t)
        br.record_failure(t)
        br.record_success(t)
        br.record_failure(t)
        br.record_failure(t)
        assert br.state == "closed"          # never 3 consecutive


class TestTokenBucket:
    def test_burst_then_starve_then_refill(self):
        tb = TokenBucket(rate=10.0, burst=2)
        t = tb.stamp
        assert tb.take(t) and tb.take(t)
        assert not tb.take(t)                # burst spent
        assert tb.take(t + 0.12)             # one token refilled
        assert not tb.take(t + 0.12)

    def test_refill_never_exceeds_burst(self):
        tb = TokenBucket(rate=100.0, burst=3)
        t = tb.stamp
        tb.take(t)
        # a long idle period refills to AT MOST burst, not rate*idle
        for _ in range(3):
            assert tb.take(t + 100.0)
        assert not tb.take(t + 100.0)


# -- fake replicas: deterministic router-logic tests -----------------------

class FakeReplica:
    """Duck-typed Replica: completes every request with a canned OK
    (or canned terminal) result after `latency` seconds; `black_hole`
    never completes.  Health is whatever the test sets."""

    def __init__(self, slot, incarnation=0, latency=0.0, behavior="ok"):
        self.slot = slot
        self.incarnation = incarnation
        self.name = f"f{slot}i{incarnation}"
        self.condemned = False
        self.failed = False
        self.assigned = {}
        self.latency = latency
        self.behavior = behavior
        self.submitted = []
        self._ids = itertools.count(1)
        self._pending = {}               # id -> (ready_at, result)
        self.health_overrides = {}

    def start(self):
        return self

    def submit(self, batch, options=None, scenario_names=None,
               deadline=None, model=None):
        i = next(self._ids)
        self.submitted.append((i, options))
        if self.behavior == "black_hole":
            res = None
        elif self.behavior == "fail":
            res = {"status": "failed", "reason": "canned failure"}
        else:
            res = {"status": "ok", "eobj": -1.0, "conv": 0.0,
                   "solved_by": self.name}
        self._pending[i] = (time.monotonic() + self.latency, res)
        return types.SimpleNamespace(id=i)

    def peek(self, handle):
        ready_at, res = self._pending[handle.id]
        if res is None or time.monotonic() < ready_at:
            return None
        return dict(res, request_id=handle.id)

    def poll(self, handle):
        r = self.peek(handle)
        return "queued" if r is None else r["status"]

    def health(self):
        h = {"failed": None, "draining": False, "stopped": False,
             "queue_depth": 0, "inflight": 0, "last_dispatch_age": 0.0,
             "restarts": 0, "crash_suspects": set()}
        h.update(self.health_overrides)
        return h

    def drain(self, deadline=1.0, checkpoint_path=None):
        return {"drained": 0, "checkpoint": None}

    def warm_from(self, path):
        return []

    def shutdown(self, timeout=5.0):
        pass


class FakeSet:
    def __init__(self, replicas):
        self.replicas = list(replicas)
        self.replacements = 0

    def start(self):
        return self

    def shutdown(self, timeout=5.0):
        pass

    def __iter__(self):
        return iter(self.replicas)

    def __len__(self):
        return len(self.replicas)

    def __getitem__(self, slot):
        return self.replicas[slot]

    def replace(self, slot, drain_deadline=1.0, checkpoint_path=None):
        corpse = self.replicas[slot]
        corpse.condemned = True
        self.replacements += 1
        fresh = FakeReplica(slot, incarnation=corpse.incarnation + 1)
        self.replicas[slot] = fresh
        return fresh, {"drained": 0, "checkpoint": None}, []


def _fake_router(replicas, **opts):
    o = {"router_tick": 0.002, "router_probe_interval": 0.004,
         "router_hedge_threshold": None, "router_brownout_interval": 0.01}
    o.update(opts)
    return Router(o, replica_set=FakeSet(replicas)).start()


class TestRouterLogic:
    def test_solve_roundtrip_and_least_loaded_pick(self):
        r0, r1 = FakeReplica(0), FakeReplica(1)
        r1.health_overrides["queue_depth"] = 5   # r0 is less loaded
        router = _fake_router([r0, r1])
        try:
            res = router.solve("B", {"x": 1}, timeout=5)
            assert res["status"] == "ok"
            assert res["replica"] == "f0i0"
            assert "router_wall_s" in res
        finally:
            router.shutdown(timeout=1)

    def test_idempotency_key_dedupes_to_one_request(self):
        router = _fake_router([FakeReplica(0)])
        try:
            h1 = router.submit("B", idempotency_key="job-1")
            h2 = router.submit("B", idempotency_key="job-1")
            assert h1.id == h2.id
            assert router.counts["requests_submitted"] == 1
            res1 = router.result(h1, timeout=5)
            # a LATE duplicate submit resolves instantly to the same
            # already-computed result — the dedup half of exactly-once
            h3 = router.submit("B", idempotency_key="job-1")
            assert router.result(h3, timeout=1) is res1
        finally:
            router.shutdown(timeout=1)

    def test_hedge_fires_and_first_completion_wins(self):
        slow, fast = FakeReplica(0, latency=0.4), FakeReplica(1)
        fast.health_overrides["queue_depth"] = 1  # initial pick: slot 0
        router = _fake_router([slow, fast], router_hedge_threshold=0.05)
        try:
            h = router.submit("B")
            res = router.result(h, timeout=5)
            assert res["status"] == "ok"
            assert res["replica"] == "f1i0"       # hedge won
            assert router.counts["hedged_requests"] == 1
            # the slow twin completes later: observed, counted, never
            # delivered — and the request leaves the lingering table
            assert _wait_for(
                lambda: router.counts.get("duplicate_completions", 0) == 1)
            assert _wait_for(lambda: not router._lingering)
            assert router.result(h, timeout=1)["replica"] == "f1i0"
        finally:
            router.shutdown(timeout=1)

    def test_tenant_token_bucket_rejects_over_quota(self):
        router = _fake_router([FakeReplica(0)],
                              router_tenant_rate=0.001,
                              router_tenant_burst=2)
        try:
            r1 = router.result(router.submit("B", tenant="acme"), timeout=5)
            r2 = router.result(router.submit("B", tenant="acme"), timeout=5)
            r3 = router.result(router.submit("B", tenant="acme"), timeout=5)
            assert r1["status"] == r2["status"] == "ok"
            assert r3["status"] == "rejected"
            assert r3["reason"] == "over_quota"
            # independent tenants have independent buckets
            other = router.result(router.submit("B", tenant="zeta"),
                                  timeout=5)
            assert other["status"] == "ok"
            assert router.counts["over_quota"] == 1
        finally:
            router.shutdown(timeout=1)

    def test_breaker_gates_routing_and_recovers(self):
        r0, r1 = FakeReplica(0), FakeReplica(1)
        router = _fake_router([r0, r1],
                              router_breaker_failures=2,
                              router_breaker_backoff=0.05,
                              router_breaker_backoff_cap=0.2,
                              router_breaker_queue_depth=4)
        try:
            # unhealthy probes (deep queue) open slot 0's breaker
            r0.health_overrides["queue_depth"] = 100
            assert _wait_for(
                lambda: router.breakers[0].state == "open")
            res = router.solve("B", timeout=5)
            assert res["replica"] == "f1i0"      # slot 0 shed
            # recovery: healthy probes close it through half-open
            r0.health_overrides["queue_depth"] = 0
            assert _wait_for(
                lambda: router.breakers[0].state == "closed", timeout=3)
            assert _is_subsequence(
                ["closed", "open", "half_open", "closed"],
                router.breakers[0].states_seen())
            assert router.counts["breaker_opens"] >= 1
        finally:
            router.shutdown(timeout=1)

    def test_failed_replica_replaced_and_request_replayed(self):
        r0, r1 = FakeReplica(0, behavior="black_hole"), FakeReplica(1)
        r1.health_overrides["queue_depth"] = 9   # first pick: slot 0
        router = _fake_router([r0, r1])
        try:
            h = router.submit("B")
            time.sleep(0.02)
            r0.health_overrides["failed"] = "boom"
            r0.failed = True
            res = router.result(h, timeout=5)
            assert res["status"] == "ok"          # replayed, not lost
            assert router.counts["replica_restarts"] == 1
            assert router.counts.get("replayed_requests", 0) >= 1
            assert router.replica_set.replacements == 1
            assert router.replica_set[0].incarnation == 1
            assert router.breakers[0].opens >= 1
        finally:
            router.shutdown(timeout=1)

    def test_poison_budget_quarantines_attributed_request(self):
        r0 = FakeReplica(0, behavior="black_hole")
        router = _fake_router([r0], router_poison_budget=1)
        try:
            h = router.submit("B")
            assert _wait_for(lambda: r0.assigned)
            inner_id = next(iter(r0.assigned))
            # the service attributes the crash to THIS request
            r0.health_overrides["crash_suspects"] = {inner_id}
            res = router.result(h, timeout=5)
            assert res["status"] == "failed"
            assert "quarantined" in res["reason"]
            assert router.counts["quarantined"] == 1
            # no replacement happened: quarantine is request-scoped
            assert router.replica_set.replacements == 0
        finally:
            router.shutdown(timeout=1)

    def test_failed_results_respect_attempt_budget(self):
        router = _fake_router([FakeReplica(0, behavior="fail"),
                               FakeReplica(1, behavior="fail")],
                              router_max_attempts=2)
        try:
            res = router.solve("B", timeout=5)
            assert res["status"] == "failed"
            assert router.counts["requests_failed"] == 1
        finally:
            router.shutdown(timeout=1)

    def test_router_deadline_sweeps_unresolvable_request(self):
        router = _fake_router([FakeReplica(0, behavior="black_hole")])
        try:
            res = router.solve("B", deadline=0.1, timeout=5)
            assert res["status"] == "timeout"
        finally:
            router.shutdown(timeout=1)

    def test_shutdown_rejects_new_and_unresolved(self):
        router = _fake_router([FakeReplica(0, behavior="black_hole")])
        h = router.submit("B")
        router.shutdown(timeout=1)
        assert router.result(h, timeout=1)["status"] == "rejected"
        late = router.submit("B")
        assert router.result(late, timeout=1)["reason"] == "shutdown"


class TestBrownoutLadder:
    def test_overload_escalates_and_relaxes(self):
        """Sustained load above the high-water fraction walks the
        ladder up one level per sustained eval; load draining away
        walks it back down.  Every transition is recorded."""
        slow = FakeReplica(0, latency=0.25)
        router = _fake_router(
            [slow], serve_max_inflight=1,
            router_brownout_high=0.5, router_brownout_low=0.25,
            router_brownout_sustain=1, router_brownout_interval=0.01)
        try:
            handles = [router.submit("B") for _ in range(4)]
            assert _wait_for(lambda: router.brownout_level >= 1,
                             timeout=3)
            for h in handles:
                router.result(h, timeout=5)
            assert _wait_for(lambda: router.brownout_level == 0,
                             timeout=3)
            levels = [lv for lv, _ in router.brownout_transitions]
            assert levels[0] == 1            # stepwise, not a jump
            assert levels[-1] == 0
        finally:
            router.shutdown(timeout=1)

    def test_level1_sheds_hedges(self):
        slow = FakeReplica(0, latency=0.3)
        router = _fake_router([slow, FakeReplica(1)],
                              router_hedge_threshold=0.02,
                              router_brownout_interval=1e9)
        try:
            router.brownout_level = 1
            slow.health_overrides["queue_depth"] = 0
            h = router.submit("B")
            res = router.result(h, timeout=5)
            assert res["status"] == "ok"
            assert router.counts.get("hedged_requests", 0) == 0
            assert router.counts["shed_hedges"] == 1
        finally:
            router.shutdown(timeout=1)

    def test_level2_widens_eps_of_admitted_requests(self):
        router = _fake_router([FakeReplica(0)],
                              router_brownout_conv_factor=10.0,
                              router_brownout_interval=1e9)
        try:
            router.brownout_level = 2
            h = router.submit("B", options={"convthresh": 1e-4})
            rreq = router._requests[h.id]
            assert rreq.options["convthresh"] == pytest.approx(1e-3)
            assert rreq.options["eps_ladder"]["start"] >= \
                rreq.options["eps_ladder"]["min"]
            assert router.counts["degraded_requests"] == 1
            assert router.result(h, timeout=5)["status"] == "ok"
        finally:
            router.shutdown(timeout=1)

    def test_level3_rejects_low_priority_tenants(self):
        router = _fake_router([FakeReplica(0)],
                              router_brownout_min_priority=1,
                              router_brownout_interval=1e9)
        try:
            router.brownout_level = 3
            res_lo = router.result(
                router.submit("B", priority=0), timeout=5)
            assert res_lo["status"] == "rejected"
            assert res_lo["reason"] == "brownout_shed"
            res_hi = router.result(
                router.submit("B", priority=1), timeout=5)
            assert res_hi["status"] == "ok"
            assert router.counts["shed_requests"] == 1
        finally:
            router.shutdown(timeout=1)


# -- telemetry accessor ----------------------------------------------------

def test_router_counters_keys_stable_on_and_off():
    off = telemetry.router_counters(
        telemetry.Telemetry({"enabled": False}).registry)
    assert all(v == 0 for v in off.values())
    tel = telemetry.Telemetry({"enabled": True})
    tel.counter("router.hedged_requests").inc(3)
    tel.gauge("router.brownout_level").set(2)
    on = telemetry.router_counters(tel.registry)
    assert set(on) == set(off)
    assert on["router_hedged_requests"] == 3
    assert on["router_brownout_level"] == 2


# -- warm_from corruption regression (satellite 2) -------------------------

class TestWarmFromCorruption:
    def _drained_checkpoint(self, tmp_path):
        from mpisppy_tpu.models import farmer
        from mpisppy_tpu.serve.service import SolverService

        svc = SolverService()            # never started: request stays
        svc.submit(farmer.build_batch(3), FAST_OPTS, model="farmer")
        info = svc.drain(deadline=0.05,
                         checkpoint_path=str(tmp_path / "drain"))
        assert info["drained"] == 1 and info["checkpoint"]
        return pathlib.Path(info["checkpoint"])

    def _assert_rejected_and_alive(self, out, svc):
        from mpisppy_tpu.models import farmer

        assert isinstance(out, dict), out
        assert out["status"] == "failed"
        assert out["reason"] == "corrupt_drain_checkpoint"
        assert "error" in out and "path" in out
        # the service is NOT poisoned: it still accepts and solves
        h = svc.submit(farmer.build_batch(3), FAST_OPTS, model="farmer")
        svc.start()
        try:
            assert svc.result(h, timeout=600)["status"] == "ok"
        finally:
            svc.shutdown(timeout=5)

    def test_bitflipped_checkpoint_is_structured_reject(self, tmp_path):
        from mpisppy_tpu.serve.service import SolverService

        p = self._drained_checkpoint(tmp_path)
        raw = bytearray(p.read_bytes())
        mid = len(raw) // 2              # inside member data: the zip
        for i in range(8):               # CRC catches the flip
            raw[mid + i] ^= 0xFF
        p.write_bytes(bytes(raw))
        svc = SolverService()
        self._assert_rejected_and_alive(svc.warm_from(str(p)), svc)

    def test_truncated_checkpoint_is_structured_reject(self, tmp_path):
        from mpisppy_tpu.serve.service import SolverService

        p = self._drained_checkpoint(tmp_path)
        p.write_bytes(p.read_bytes()[: len(p.read_bytes()) // 3])
        svc = SolverService()
        self._assert_rejected_and_alive(svc.warm_from(str(p)), svc)

    def test_entry_missing_keys_is_structured_reject(self, tmp_path):
        """A well-formed npz whose entries lack required request keys
        is rejected BEFORE any resubmit — never a half-warmed service."""
        from mpisppy_tpu.resilience.checkpoint import save_drain_checkpoint
        from mpisppy_tpu.serve.service import SolverService

        path = save_drain_checkpoint(
            str(tmp_path / "bad"), [{"id": 1, "options": {}}])
        svc = SolverService()
        out = svc.warm_from(path)
        assert out["status"] == "failed"
        assert out["reason"] == "corrupt_drain_checkpoint"
        assert "missing keys" in out["error"]
        assert not svc._requests     # nothing was resubmitted


# -- chaos-on open-load acceptance (the ISSUE 11 e2e) ----------------------

@pytest.mark.chaos
def test_open_load_with_chaos_exactly_once_and_bounded_p99():
    """Open-load generator against a 2-replica set with replica_crash +
    slow_replica + poison_request armed:

      * every admitted request resolves EXACTLY once — no lost results,
        duplicate completions suppressed through the idempotency table;
      * batch=1 results are bitwise-identical to PH.ph_main;
      * the poison request is quarantined without pruning more than
        one replica;
      * slot 0's breaker traverses closed -> open -> half_open ->
        closed across the replacement;
      * p99 is finite and bounded, with breaker_opens >= 1 and
        replica_restarts >= 1 (the bench chaos row's signals)."""
    from mpisppy_tpu.models import farmer
    from mpisppy_tpu.opt.ph import PH

    names = [f"scen{i}" for i in range(3)]
    ph = PH(dict(FAST_OPTS), names, batch=farmer.build_batch(3))
    g_conv, g_eobj, g_trivial = ph.ph_main()

    router = Router({
        "serve_replicas": 2,
        "serve_max_batch": 1,            # singleton groups: bitwise path
        "serve_restart_backoff": 0.01,
        "serve_restart_backoff_cap": 0.05,
        "router_tick": 0.01, "router_probe_interval": 0.02,
        "router_hedge_threshold": 1.0,
        "router_breaker_backoff": 0.05,
        "router_breaker_backoff_cap": 0.5,
        "router_drain_deadline": 0.3,
        "chaos": {"replica_crash": 1, "slow_replica": 0.02,
                  "poison_request": True, "chaos_replica": 0},
    }).start()
    handles = {}
    try:
        batch = farmer.build_batch(3)
        # open loop: submit at a fixed rate, never waiting on results
        for i in range(8):
            handles[f"req{i}"] = router.submit(
                batch, FAST_OPTS, scenario_names=names, model="farmer",
                idempotency_key=f"req{i}")
            if i == 3:                   # poison mid-stream
                handles["poison"] = router.submit(
                    batch, dict(FAST_OPTS, chaos_poison=True),
                    scenario_names=names, model="farmer",
                    idempotency_key="poison")
            time.sleep(0.05)
        results = {k: router.result(h, timeout=300)
                   for k, h in handles.items()}

        # exactly-once: every request terminal, one rid per key, and a
        # re-ask returns the SAME result object (no second delivery)
        assert len(router._idempotency) == len(handles)
        for k, h in handles.items():
            assert results[k]["status"] in ("ok", "failed"), results[k]
            assert router.result(h, timeout=1) is results[k]
            assert router.submit(batch, FAST_OPTS,
                                 idempotency_key=k).id == h.id

        # poison: quarantined; everything else solved
        assert results["poison"]["status"] == "failed"
        assert "quarantined" in results["poison"]["reason"]
        oks = {k: r for k, r in results.items() if k != "poison"}
        assert all(r["status"] == "ok" for r in oks.values()), \
            {k: r["status"] for k, r in oks.items()}

        # bitwise parity at batch=1 (every group is a singleton)
        for r in oks.values():
            assert r["conv"] == g_conv
            assert r["eobj"] == g_eobj
            assert r["trivial_bound"] == g_trivial
            assert np.array_equal(r["xbar"], np.asarray(ph.root_xbar()))

        st = router.stats()
        # only the chaos-targeted replica was pruned
        assert st["replica_restarts"] == 1
        assert router.replica_set[0].incarnation == 1
        assert router.replica_set[1].incarnation == 0
        # breaker traversal on the crashed slot
        assert st["counts"]["breaker_opens"] >= 1
        assert _is_subsequence(
            ["closed", "open", "half_open", "closed"],
            st["breakers"][0]["states_seen"])
        # bounded latency under chaos
        assert st["p99"] is not None and np.isfinite(st["p99"])
        assert st["p99"] < 240.0
        assert st["counts"]["quarantined"] == 1
    finally:
        router.shutdown(timeout=10)

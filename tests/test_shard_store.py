"""Durable shard store tests (PR 14): checksummed corpus round-trip,
header/CRC validation on every read, transient-fault retry, shard
quarantine with deterministic resampling and certified-gap debit, the
hard-fail quarantine budget, readahead hit/wait accounting, the
storage cursor's checkpoint round-trip, and the chaos e2e — a
StreamingPH run over a faulted corpus reaching a certified stop whose
CI carries the lost-mass debit, plus mid-superstep crash-resume
bit-equality.  Also the laziness guards: store.py/readahead.py never
import jax at module level (AST + fresh interpreter)."""

import ast
import json
import os
import pathlib
import subprocess
import sys
import zlib

import numpy as np
import pytest

import mpisppy_tpu.streaming as streaming_pkg
from mpisppy_tpu import telemetry
from mpisppy_tpu.models import farmer, uc
from mpisppy_tpu.resilience.checkpoint import atomic_write
from mpisppy_tpu.streaming import (QuarantinedCorpusError,
                                   ReadaheadCache, ShardIntegrityError,
                                   ShardQuarantinedError, ShardSource,
                                   ShardStore, write_corpus)
from mpisppy_tpu.streaming.store import MAGIC, _decode_shard

pytestmark = pytest.mark.storage


@pytest.fixture
def farmer_corpus(tmp_path):
    """A 64-scenario farmer corpus in 8-wide shards (split-native A)."""
    path = os.fspath(tmp_path / "corpus")
    farmer.export_corpus(path, 64, shard_width=8)
    return path


# ---- format round-trip ----------------------------------------------------

def test_corpus_roundtrip_parity_with_generator(farmer_corpus):
    """Blocks served off disk are bit-identical to generator-built
    blocks — arrays, SplitA structure, names, block-uniform probs."""
    src = farmer.scenario_source(64, {})
    ss = ShardSource(farmer_corpus, depth=2)
    idx = np.array([1, 5, 9, 17, 23, 63])
    served, blk = ss.block_with_indices(idx)
    ref = src.block(idx)
    assert np.array_equal(served, idx)
    for f in ("c", "row_lo", "row_hi", "lb", "ub", "obj_const",
              "nonant_idx"):
        assert np.array_equal(np.asarray(getattr(blk, f)),
                              np.asarray(getattr(ref, f))), f
    # split-native A survives the disk trip: shared matrix + deltas
    assert type(blk.A).__name__ == "SplitA"
    assert np.array_equal(np.asarray(blk.A.shared),
                          np.asarray(ref.A.shared))
    assert np.array_equal(np.asarray(blk.A.vals),
                          np.asarray(ref.A.vals))
    assert blk.tree.scen_names == ref.tree.scen_names
    assert np.allclose(np.asarray(blk.tree.prob), 1.0 / idx.size)
    assert ss.names(idx) == src.names(idx)
    ss.close()


def test_uc_shared_a_corpus_stays_shared_on_disk(tmp_path):
    """A shared-A corpus (UC wind) round-trips with A still (1, M, N)
    — the corpus never replicates the shared matrix per scenario."""
    path = os.fspath(tmp_path / "uc_corpus")
    uc.export_corpus(path, 12, shard_width=4, cfg={"H": 4, "n_units": 2})
    src = uc.scenario_source(12, {"H": 4, "n_units": 2})
    ss = ShardSource(path, depth=2)
    idx = np.array([0, 5, 9])
    _, blk = ss.block_with_indices(idx)
    ref = src.block(idx)
    A = np.asarray(blk.A)
    assert A.shape[0] == 1 and A.shape == np.asarray(ref.A).shape
    assert np.array_equal(A, np.asarray(ref.A))
    assert np.array_equal(np.asarray(blk.row_lo),
                          np.asarray(ref.row_lo))
    assert ss.names(idx) == ["Scenario1", "Scenario6", "Scenario10"]
    ss.close()


def test_write_corpus_rejects_multistage(tmp_path):
    from mpisppy_tpu.models import aircond
    src = aircond.scenario_source(None, {"branching_factors": (3, 2)})
    with pytest.raises(NotImplementedError, match="two-stage only"):
        write_corpus(src, os.fspath(tmp_path / "ms"), 4)


# ---- every read validated -------------------------------------------------

def test_read_checked_rejects_flipped_payload_byte(farmer_corpus):
    st = ShardStore(farmer_corpus, max_shard_retries=0,
                    max_quarantined_frac=0.5)
    p = st.shard_path(2)
    data = bytearray(open(p, "rb").read())
    data[-1] ^= 0xFF                       # payload region
    atomic_write(p, bytes(data))
    with pytest.raises(ShardQuarantinedError):
        st.read_checked(2)
    assert st.quarantined == {2}
    # the direct decode names the CRC mismatch
    with pytest.raises(ShardIntegrityError, match="CRC mismatch"):
        _decode_shard(bytes(data))


def test_decode_rejects_bad_magic_and_truncation(farmer_corpus):
    st = ShardStore(farmer_corpus)
    data = open(st.shard_path(0), "rb").read()
    with pytest.raises(ShardIntegrityError, match="magic"):
        _decode_shard(b"NOTMAGIC" + data[len(MAGIC):])
    with pytest.raises(ShardIntegrityError, match="truncated|length"):
        _decode_shard(data[:len(data) // 2])
    # header expectations: wrong model ident / seed range
    with pytest.raises(ShardIntegrityError, match="model ident"):
        _decode_shard(data, expect_model="not_farmer")
    with pytest.raises(ShardIntegrityError, match="seed range"):
        _decode_shard(data, expect_range=(8, 16))


def test_transient_io_error_recovers_without_quarantine(farmer_corpus):
    st = ShardStore(farmer_corpus, max_shard_retries=2, backoff=0.001,
                    backoff_cap=0.002, chaos={"io_error": 2})
    blk = st.read_checked(0)
    assert blk.num_scens == 8
    assert st.read_retries == 2
    assert st.quarantined == set()


# ---- quarantine + deterministic substitution ------------------------------

def test_quarantine_substitution_is_deterministic_and_healthy_only(
        farmer_corpus):
    ss = ShardSource(farmer_corpus, depth=3, max_shard_retries=1,
                     backoff=0.001, max_quarantined_frac=0.5,
                     chaos={"shard_corrupt": [5], "shard_missing": 6})
    served, blk = ss.block_with_indices(np.arange(64))
    assert sorted(ss.store.quarantined) == [5, 6]
    assert ss.store.quarantined_frac == pytest.approx(0.25)
    # substitutes never land in quarantined shards; block keeps shape
    assert not np.isin(served // 8, [5, 6]).any()
    assert served.size == 64 and blk.num_scens == 64
    # pure function of (indices, quarantine set): a FRESH store with
    # the same quarantine set replays the identical substitution
    st2 = ShardStore(farmer_corpus, max_quarantined_frac=0.5)
    st2.quarantined = {5, 6}
    assert np.array_equal(served, st2.substitute_quarantined(
        np.arange(64)))
    # partial blocks keep the active-prefix discipline + distinctness
    ss.close()
    st3 = ShardStore(farmer_corpus, max_quarantined_frac=0.5)
    st3.quarantined = {0}
    out = st3.substitute_quarantined(np.array([0, 3, 17, 20, 41]))
    assert out.max() <= 41 and np.unique(out).size == 5


def test_quarantine_budget_hard_fails(farmer_corpus):
    ss = ShardSource(farmer_corpus, depth=2, max_shard_retries=0,
                     backoff=0.001, max_quarantined_frac=0.1,
                     chaos={"shard_corrupt": [1, 2]})
    with pytest.raises(QuarantinedCorpusError,
                       match="max_quarantined_frac"):
        ss.block_with_indices(np.arange(64))
    ss.close()


def test_retrying_source_propagates_corpus_hard_fail(farmer_corpus):
    """RetryingSource must NOT retry (or mask as SourceBuildError) a
    terminal QuarantinedCorpusError — retrying a dead corpus only
    delays the hard fail."""
    from mpisppy_tpu.streaming.source import RetryingSource
    inner = ShardSource(farmer_corpus, depth=2, max_shard_retries=0,
                        backoff=0.001, max_quarantined_frac=0.1,
                        chaos={"shard_missing": [1, 2]})
    src = RetryingSource(inner, retries=3, backoff=0.001)
    with pytest.raises(QuarantinedCorpusError):
        src.block_with_indices(np.arange(64))
    assert src.retry_log == []           # zero retry attempts burned
    inner.close()


# ---- storage cursor -------------------------------------------------------

def test_storage_cursor_roundtrips_quarantine_and_rng(farmer_corpus):
    st = ShardStore(farmer_corpus, max_shard_retries=0, backoff=0.001,
                    max_quarantined_frac=0.5,
                    chaos={"shard_missing": [3]})
    with pytest.raises(ShardQuarantinedError):
        st.read_checked(3)
    cur = st.state()
    json.dumps(cur)                       # JSON-serializable contract
    st2 = ShardStore(farmer_corpus, max_quarantined_frac=0.5)
    st2.restore(cur)
    assert st2.quarantined == {3}
    assert st2.read_retries == st.read_retries
    assert st2._retry_rng.getstate() == st._retry_rng.getstate()
    idx = np.arange(40)
    assert np.array_equal(st.substitute_quarantined(idx),
                          st2.substitute_quarantined(idx))


# ---- readahead ------------------------------------------------------------

def test_readahead_hit_and_wait_accounting(farmer_corpus):
    tel = telemetry.configure(True)
    try:
        st = ShardStore(farmer_corpus, telemetry=tel)
        ra = ReadaheadCache(st, depth=4, telemetry=tel)
        ra.schedule([0, 1])
        a = ra.get(0)                     # hinted -> hit
        b = ra.get(1)                     # hinted -> hit
        c = ra.get(7)                     # demand -> miss
        assert a.num_scens == b.num_scens == c.num_scens == 8
        assert ra.hits == 2 and ra.misses == 1
        assert ra.hit_rate == pytest.approx(2 / 3)
        assert ra.wait_seconds >= 0.0
        ctr = telemetry.storage_counters(tel.registry)
        assert ctr["store_readahead_hits"] == 2
        assert ctr["store_readahead_misses"] == 1
        assert ctr["store_readahead_hit_rate"] == pytest.approx(2 / 3)
        assert ctr["store_shards_read"] == 3
        ra.close()
    finally:
        telemetry.reset()


def test_readahead_relays_errors_and_drops_poisoned_entry(
        farmer_corpus):
    st = ShardStore(farmer_corpus, max_shard_retries=0, backoff=0.001,
                    max_quarantined_frac=0.9,
                    chaos={"shard_missing": [2]})
    ra = ReadaheadCache(st, depth=2)
    with pytest.raises(ShardQuarantinedError):
        ra.get(2)
    assert 2 not in ra._cache             # poisoned entry dropped
    assert ra.get(0).num_scens == 8       # cache still serves
    ra.close()
    with pytest.raises(Exception):        # closed cache refuses demand
        ra.get(1)


def test_storage_counters_keys_stable_on_and_off():
    keys = {"store_shards_read", "store_read_retries",
            "store_shards_quarantined", "store_resampled_indices",
            "store_readahead_hits", "store_readahead_misses",
            "store_quarantined_frac", "store_readahead_hit_rate",
            "store_read_wait_seconds"}
    off = telemetry.storage_counters(
        telemetry.Telemetry({"enabled": False}).registry)
    assert set(off) == keys
    assert all(v == 0 for v in off.values())
    on = telemetry.storage_counters(
        telemetry.Telemetry({"enabled": True}).registry)
    assert set(on) == keys


# ---- atomic_write (shared tmp-rename discipline) --------------------------

def test_atomic_write_replaces_and_leaves_no_tmp(tmp_path):
    p = os.fspath(tmp_path / "blob.bin")
    atomic_write(p, b"first")
    atomic_write(p, b"second")
    assert open(p, "rb").read() == b"second"
    assert not os.path.exists(p + ".tmp")


def test_atomic_writers_share_one_helper():
    """The satellite de-dup: run/stream checkpoints, the W/xbar
    snapshot, and the spoke solution publish all route through
    resilience.checkpoint.atomic_write instead of carrying private
    tmp-rename copies."""
    import inspect

    from mpisppy_tpu.cylinders import proc
    from mpisppy_tpu.resilience import checkpoint
    from mpisppy_tpu.utils import wxbarutils
    assert "atomic_write" in inspect.getsource(checkpoint._atomic_savez)
    assert "atomic_write" in inspect.getsource(
        wxbarutils.write_W_and_xbar)
    assert "atomic_write" in inspect.getsource(proc)


# ---- chaos e2e + crash resume (acceptance) --------------------------------

def _shard_opts(**kw):
    o = {"PHIterLimit": 25, "defaultPHrho": 1.0, "solver_eps": 1e-6,
         "stream_block_size": 8, "stream_check_every": 5,
         "stream_seed": 0, "BM_h": 2.0, "BM_hprime": 0.4,
         "BM_eps": 60000.0, "n0min": 64}
    o.update(kw)
    return o


@pytest.mark.chaos
def test_streaming_ph_chaos_e2e_certifies_with_gap_debit(tmp_path):
    """The acceptance e2e: StreamingPH over a corpus under ALL FOUR
    storage chaos modes reaches a certified stop with the same CI
    verdict as the healthy run, the quarantined mass debited into the
    reported gap (non-zero, CI strictly wider than healthy)."""
    from mpisppy_tpu.streaming import StreamingPH

    path = os.fspath(tmp_path / "corpus")
    farmer.export_corpus(path, 64, shard_width=4)   # 16 shards

    healthy = StreamingPH(_shard_opts(), ShardSource(path, depth=4),
                          module=farmer)
    healthy.stream_main(finalize=False)

    chaotic = StreamingPH(
        _shard_opts(),
        ShardSource(path, depth=4, max_shard_retries=2, backoff=0.001,
                    max_quarantined_frac=0.5,
                    chaos={"io_delay": 0.002, "io_error": 2,
                           "shard_corrupt": [10], "shard_missing": 13}),
        module=farmer)
    chaotic.stream_main(finalize=False)

    hc, cc = healthy.certified, chaotic.certified
    # CI-verdict parity: both certified under the same rule
    assert hc is not None and cc is not None
    assert hc["criterion"] == cc["criterion"]
    # healthy run's estimate is bit-untouched by the debit machinery
    assert hc["gap_debit"] == 0.0 and hc["quarantined_frac"] == 0.0
    # lost mass debited into the reported gap: non-zero, CI wider
    assert cc["gap_debit"] > 0.0
    assert cc["quarantined_frac"] == pytest.approx(2 / 16)
    assert cc["CI"][1] > hc["CI"][1]
    assert cc["CI"][1] == pytest.approx(
        hc["CI"][1] + cc["gap_debit"], rel=0.2)
    st = chaotic.stream_stats()["storage"]
    assert st["shards_quarantined"] == 2
    assert st["read_retries"] >= 2        # io_error recovered, twice
    assert st["resampled_indices"] > 0
    assert st["readahead_hit_rate"] > 0.0


@pytest.mark.chaos
def test_crash_resume_bit_equal_through_storage_faults(tmp_path):
    """A run that quarantines a shard, checkpoints every superstep,
    and crashes mid-run resumes from the stream checkpoint's storage
    cursor and bit-replays the uninterrupted degraded trajectory —
    including the quarantine substitutions."""
    from mpisppy_tpu.resilience.chaos import ChaosError
    from mpisppy_tpu.streaming import StreamingPH

    path = os.fspath(tmp_path / "corpus")
    farmer.export_corpus(path, 64, shard_width=4)
    ck = os.fspath(tmp_path / "stream_ck")

    def mk(extra):
        o = {"PHIterLimit": 6, "defaultPHrho": 1.0, "solver_eps": 1e-6,
             "stream_block_size": 8, "stream_check_every": 100,
             "stream_seed": 0, "n0min": 64}
        o.update(extra)
        src = ShardSource(path, depth=4, max_shard_retries=1,
                          backoff=0.001, max_quarantined_frac=0.5,
                          chaos={"shard_missing": 13})
        return StreamingPH(o, src, module=None)

    a = mk({})
    a.stream_main(finalize=False)
    assert a._shard_store().quarantined == {13}

    b1 = mk({"run_checkpoint": ck, "checkpoint_every": 1,
             "chaos": {"crash_at_iter": 3}})
    with pytest.raises(ChaosError):
        b1.stream_main(finalize=False)
    b2 = mk({"resume_from": ck})
    b2.stream_main(finalize=False)

    assert b2._shard_store().quarantined == {13}
    assert np.array_equal(a.W_host, b2.W_host)
    assert np.array_equal(a.x_na_host, b2.x_na_host)
    assert np.array_equal(a.xbar_host, b2.xbar_host)
    assert np.array_equal(a.solved, b2.solved)
    assert a.conv == b2.conv
    assert int(a.state.it) == int(b2.state.it)
    assert a.sampler.state()["rng_state"] == \
        b2.sampler.state()["rng_state"]
    assert np.array_equal(a._pending_indices, b2._pending_indices)


# ---- ciutils debit unit ---------------------------------------------------

def test_debit_quarantined_mass_scales_and_noops():
    from mpisppy_tpu.confidence_intervals.ciutils import \
        debit_quarantined_mass
    est = {"G": 10.0, "zhats": -1000.0, "zstar": -900.0}
    assert debit_quarantined_mass(dict(est), 0.0) == 0.0
    e = dict(est)
    d = debit_quarantined_mass(e, 0.1)
    assert d == pytest.approx(100.0)      # 0.1 * |zhats| (the max)
    assert e["G"] == pytest.approx(110.0)
    assert e["quarantine_debit"] == d
    # near-zero objectives floor the scale at 1.0
    e2 = {"G": 0.0, "zhats": 1e-6, "zstar": 0.0}
    assert debit_quarantined_mass(e2, 0.5) == pytest.approx(0.5)


# ---- laziness guards ------------------------------------------------------

def test_store_modules_fresh_interpreter_never_imports_jax():
    """Runtime check for the AST guard (mirrors the mpmd pattern): a
    fresh interpreter importing the store/readahead modules must not
    pull jax."""
    code = ("import mpisppy_tpu.streaming.store, "
            "mpisppy_tpu.streaming.readahead, sys; "
            "assert 'jax' not in sys.modules, 'store pulled jax'")
    pkg_root = os.path.dirname(os.path.dirname(streaming_pkg.__file__))
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    r = subprocess.run([sys.executable, "-c", code],
                       cwd=os.path.dirname(pkg_root),
                       env=env, capture_output=True, text=True)
    assert r.returncode == 0, r.stderr


@pytest.mark.parametrize("mod", ["store.py", "readahead.py"])
def test_store_modules_never_import_jax_eagerly(mod):
    path = pathlib.Path(streaming_pkg.__file__).parent / mod
    tree = ast.parse(path.read_text())
    for node in tree.body:
        if isinstance(node, ast.Import):
            assert not any(a.name.split(".")[0] == "jax"
                           for a in node.names), f"{mod}: import jax"
        elif isinstance(node, ast.ImportFrom):
            assert (node.module or "").split(".")[0] != "jax", \
                f"{mod}: from jax import ..."
            root = (node.module or "").rsplit(".", 1)[-1]
            assert root not in ("ir", "streaming_ph"), \
                f"{mod}: eager import of jax-backed module {root}"

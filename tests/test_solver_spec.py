"""solver_spec cascade tests (reference analog:
mpisppy/utils/solver_spec.py usage in vanilla/seqsampling)."""

import pytest

from mpisppy_tpu.utils.solver_spec import (option_string_to_dict,
                                           solver_specification)


def test_option_string_parsing():
    d = option_string_to_dict("eps=1e-6 max_iters=30000 flag")
    assert d == {"eps": 1e-6, "max_iters": 30000, "flag": True}
    assert option_string_to_dict(None) is None


def test_prefix_cascade():
    cfg = {"lagrangian_solver_eps": 1e-5, "solver_eps": 1e-7,
           "solver_max_iters": 40000}
    root, opts = solver_specification(cfg, ["lagrangian", ""])
    assert root == "lagrangian"
    assert opts == {"pdhg_eps": 1e-5}
    root, opts = solver_specification(cfg, ["fwph", ""])
    assert root == ""
    assert opts == {"pdhg_eps": 1e-7, "pdhg_max_iters": 40000}


def test_options_string_root():
    cfg = {"ef_solver_options": "eps=1e-8 restart_every=32"}
    root, opts = solver_specification(cfg, ["ef", ""])
    assert root == "ef"
    assert opts == {"pdhg_eps": 1e-8, "pdhg_restart_every": 32}


def test_name_required_raises():
    with pytest.raises(RuntimeError):
        solver_specification({}, ["ph"], name_required=True)
    root, opts = solver_specification({}, ["ph"])
    assert root is None and opts == {}

"""ir.SplitA (shared + sparse-delta constraint matrices): operator
parity, prepared-batch parity, and end-to-end PH trajectory parity
against the dense representation.

Farmer is the motivating family (reference examples/farmer/farmer.py:
the yield coefficients are the ONLY scenario-varying matrix entries);
these tests pin that declaring model_meta["A_delta_idx"] changes no
numbers, only the kernel's memory traffic.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from mpisppy_tpu.ir import SplitA, bmatvec, bmatvec_t
from mpisppy_tpu.models import farmer
from mpisppy_tpu.opt.ph import PH
from mpisppy_tpu.ops.pdhg import (PDHGSolver, prepare_batch,
                                  prepare_batch_split)


def _farmer_delta(b):
    rows, cols = b.model_meta["A_delta_idx"]
    return jnp.asarray(rows, jnp.int32), jnp.asarray(cols, jnp.int32)


def _split_of(b):
    rows, cols = _farmer_delta(b)
    A = jnp.asarray(b.A)
    vals = A[:, rows, cols]
    shared = A[0].at[rows, cols].set(0.0)
    return SplitA(shared=shared, rows=rows, cols=cols, vals=vals)


def test_farmer_declares_consistent_delta():
    """The model's declaration contract: outside the delta coordinate
    set, every scenario's matrix row equals scenario 0's."""
    b = farmer.build_batch(5, crops_multiplier=2)
    rows, cols = (np.asarray(v) for v in b.model_meta["A_delta_idx"])
    A = np.asarray(b.A).copy()
    A[:, rows, cols] = 0.0
    assert np.array_equal(A[1:], np.broadcast_to(A[0], A[1:].shape))


def test_bmatvec_matches_dense():
    b = farmer.build_batch(7, crops_multiplier=3)
    sp = _split_of(b)
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(7, b.num_vars))
    y = jnp.asarray(rng.randn(7, b.num_rows))
    np.testing.assert_allclose(np.asarray(bmatvec(sp, x)),
                               np.asarray(bmatvec(jnp.asarray(b.A), x)),
                               rtol=1e-12, atol=1e-12)
    np.testing.assert_allclose(np.asarray(bmatvec_t(sp, y)),
                               np.asarray(bmatvec_t(jnp.asarray(b.A), y)),
                               rtol=1e-12, atol=1e-12)
    dense = np.asarray(sp.to_dense())
    np.testing.assert_allclose(dense, np.asarray(b.A), rtol=0, atol=0)


def test_prepare_split_scaled_operator_matches():
    """The split prep's scaled operator D_r A D_c must match a dense
    reconstruction of the same scalings."""
    b = farmer.build_batch(6, crops_multiplier=2)
    rows, cols = _farmer_delta(b)
    prep = prepare_batch_split(jnp.asarray(b.A), rows, cols,
                               jnp.asarray(b.row_lo),
                               jnp.asarray(b.row_hi))
    assert isinstance(prep.A, SplitA)
    dr = np.asarray(prep.d_row)[0]
    dc = np.asarray(prep.d_col)[0]
    want = dr[None, :, None] * np.asarray(b.A) * dc[None, None, :]
    got = np.asarray(prep.A.to_dense())
    np.testing.assert_allclose(got, want, rtol=1e-10, atol=1e-12)
    # equilibration actually helps: scaled row inf-norms near 1
    rmax = np.abs(got).max(axis=2)
    assert rmax[rmax > 0].max() < 4.0
    assert rmax[rmax > 0].min() > 0.1


def test_solver_split_vs_dense_parity():
    b = farmer.build_batch(8, crops_multiplier=2)
    rows, cols = _farmer_delta(b)
    sp_prep = prepare_batch_split(jnp.asarray(b.A), rows, cols,
                                  jnp.asarray(b.row_lo),
                                  jnp.asarray(b.row_hi))
    de_prep = prepare_batch(jnp.asarray(b.A), jnp.asarray(b.row_lo),
                            jnp.asarray(b.row_hi))
    solver = PDHGSolver(max_iters=60000, eps=1e-8)
    r_sp = solver.solve(sp_prep, jnp.asarray(b.c), jnp.asarray(b.qdiag),
                        jnp.asarray(b.lb), jnp.asarray(b.ub))
    r_de = solver.solve(de_prep, jnp.asarray(b.c), jnp.asarray(b.qdiag),
                        jnp.asarray(b.lb), jnp.asarray(b.ub))
    assert bool(np.all(np.asarray(r_sp.converged)))
    assert bool(np.all(np.asarray(r_de.converged)))
    np.testing.assert_allclose(np.asarray(r_sp.obj),
                               np.asarray(r_de.obj), rtol=5e-6)
    np.testing.assert_allclose(np.asarray(r_sp.dual_obj),
                               np.asarray(r_de.dual_obj), rtol=5e-5)


@pytest.fixture(scope="module")
def ph_pair():
    opts = {"defaultPHrho": 1.0, "PHIterLimit": 8, "convthresh": 0.0,
            "pdhg_eps": 1e-7}
    names = [f"scen{i}" for i in range(3)]
    ph_sp = PH(dict(opts), names, batch=farmer.build_batch(3))
    assert isinstance(ph_sp.prep.A, SplitA)   # meta took effect
    ph_de = PH(dict(opts, no_split_prep=True), names,
               batch=farmer.build_batch(3))
    assert not isinstance(ph_de.prep.A, SplitA)
    for p in (ph_sp, ph_de):
        p.Iter0()
        for _ in range(8):
            p.ph_iteration()
    return ph_sp, ph_de


def test_ph_trajectory_parity(ph_pair):
    ph_sp, ph_de = ph_pair
    assert abs(ph_sp.trivial_bound - ph_de.trivial_bound) < 1.0
    assert abs(ph_sp.conv - ph_de.conv) < 1e-4 * (1 + abs(ph_de.conv))
    np.testing.assert_allclose(np.asarray(ph_sp.root_xbar()),
                               np.asarray(ph_de.root_xbar()), atol=0.3)


def test_ph_bounds_parity(ph_pair):
    ph_sp, ph_de = ph_pair
    lag_sp = ph_sp.lagrangian_bound()
    lag_de = ph_de.lagrangian_bound()
    assert abs(lag_sp - lag_de) < 1.0 + 1e-4 * abs(lag_de)
    in_sp, f_sp = ph_sp.evaluate_xhat(ph_sp.root_xbar())
    in_de, f_de = ph_de.evaluate_xhat(ph_de.root_xbar())
    assert f_sp and f_de
    assert abs(in_sp - in_de) < 1.0 + 1e-4 * abs(in_de)


def test_xhat_reduced_system_is_shared():
    """Farmer's delta columns are all nonants, so the reduced xhat
    system must collapse to the (1, M, N) shared-A fast path."""
    b = farmer.build_batch(4)
    opts = {"defaultPHrho": 1.0, "PHIterLimit": 2, "convthresh": 0.0}
    ph = PH(opts, [f"scen{i}" for i in range(4)], batch=b)
    cache = ph._xhat_cache(None)
    assert cache["A_red"].shape[0] == 1
    # the no_split_prep escape hatch disables this fast path too (it
    # rests on the same A_delta_idx declaration contract)
    ph2 = PH(dict(opts, no_split_prep=True),
             [f"scen{i}" for i in range(4)], batch=farmer.build_batch(4))
    assert ph2._xhat_cache(None)["A_red"].shape[0] \
        == ph2.batch.num_scens


def test_bundled_delta_remap():
    from mpisppy_tpu.utils.bundles import bundle_batch
    b = farmer.build_batch(6)
    bb = bundle_batch(b, 3)
    rows, cols = (np.asarray(v) for v in bb.model_meta["A_delta_idx"])
    A = np.asarray(bb.A).copy()
    vals = A[:, rows, cols]
    A[:, rows, cols] = 0.0
    # shared outside deltas, and the deltas carry the member yields
    assert np.array_equal(A[1:], np.broadcast_to(A[0], A[1:].shape))
    assert vals.std() > 0
    # bundled PH still solves through the split path
    opts = {"defaultPHrho": 1.0, "PHIterLimit": 4, "convthresh": 0.0}
    ph = PH(opts, list(bb.tree.scen_names), batch=bb)
    assert isinstance(ph.prep.A, SplitA)
    ph.Iter0()
    assert np.isfinite(ph.trivial_bound)

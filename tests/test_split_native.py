"""Split-NATIVE batches: ir.ScenarioBatch whose A is born an ir.SplitA
(never materialized dense).  This is the only representation at
true-baseline farmer size — S=1000, crops_multiplier=1000 (reference
paperruns/scripts/farmer/ef_1000_1000.out) is ~288 GB dense f32 — so
these tests pin, at small sizes, that the split-native build produces
the SAME numbers as the dense build through every path the benchmark
exercises: prep, PH superstep, Iter0 certify, Lagrangian bound, xhat
evaluation, stacked candidate screening, and mesh padding/sharding.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from mpisppy_tpu.ir import SplitA, pad_scenarios
from mpisppy_tpu.models import farmer
from mpisppy_tpu.opt.ph import PH

S, MULT = 6, 2
NAMES = [f"scen{i}" for i in range(S)]
OPTS = {"defaultPHrho": 1.0, "PHIterLimit": 6, "convthresh": 0.0,
        "pdhg_eps": 1e-7}


def _dense():
    return farmer.build_batch(S, crops_multiplier=MULT, split=False)


def _native():
    return farmer.build_batch(S, crops_multiplier=MULT, split=True)


def test_native_build_matches_dense():
    bd, bn = _dense(), _native()
    assert isinstance(bn.A, SplitA)
    assert bn.split_A and not bd.split_A
    assert bn.A.shape == bd.A.shape
    np.testing.assert_allclose(np.asarray(bn.A.to_dense()),
                               np.asarray(bd.A), rtol=0, atol=0)
    for f in ("c", "row_lo", "row_hi", "lb", "ub"):
        np.testing.assert_array_equal(np.asarray(getattr(bn, f)),
                                      np.asarray(getattr(bd, f)))


def test_auto_split_threshold():
    # small stays dense; the "auto" rule is by dense-tensor bytes
    b = farmer.build_batch(3, crops_multiplier=1)
    assert not b.split_A
    assert farmer.build_batch(
        3, crops_multiplier=1, split=True).split_A


def test_pad_scenarios_split_native():
    bn = pad_scenarios(_native(), 8)
    assert isinstance(bn.A, SplitA)
    assert bn.A.vals.shape[0] == 8
    # pads carry ZERO deltas under free rows
    assert float(jnp.abs(bn.A.vals[S:]).max()) == 0.0
    assert bool(jnp.all(~jnp.isfinite(bn.row_lo[S:])
                        | (bn.row_lo[S:] == 0)))


@pytest.fixture(scope="module")
def ph_pair():
    ph_n = PH(dict(OPTS), NAMES, batch=_native())
    assert isinstance(ph_n.prep.A, SplitA)
    # split-PREP over the dense build: identical math to split-native
    # (same shared/vals extraction, same shared Ruiz), so these two
    # must agree to numerical noise; the dense-PREP comparison below
    # is loose (a per-scenario Ruiz scaling walks a slightly different
    # iterate path to the same solution)
    ph_s = PH(dict(OPTS), NAMES, batch=_dense())
    assert isinstance(ph_s.prep.A, SplitA)
    ph_d = PH(dict(OPTS, no_split_prep=True), NAMES, batch=_dense())
    for p in (ph_n, ph_s, ph_d):
        p.Iter0()
        for _ in range(6):
            p.ph_iteration()
    return ph_n, ph_s, ph_d


def test_ph_trajectory_parity(ph_pair):
    ph_n, ph_s, ph_d = ph_pair
    # native vs split-prep: same computation, near-exact
    assert ph_n.trivial_bound == pytest.approx(ph_s.trivial_bound,
                                               rel=1e-9)
    assert ph_n.conv == pytest.approx(ph_s.conv, rel=1e-6, abs=1e-9)
    np.testing.assert_allclose(np.asarray(ph_n.root_xbar()),
                               np.asarray(ph_s.root_xbar()),
                               rtol=1e-6, atol=1e-6)
    # native vs dense-prep: same solution, different scaling path —
    # mid-trajectory iterates drift ~1% (farmer's acreage split has
    # near-alternative optima); the BOUNDS parity test below is the
    # tight number check
    assert abs(ph_n.trivial_bound - ph_d.trivial_bound) < 1.0
    assert abs(ph_n.conv - ph_d.conv) < 5e-3 * (1 + abs(ph_d.conv))
    np.testing.assert_allclose(np.asarray(ph_n.root_xbar()),
                               np.asarray(ph_d.root_xbar()),
                               rtol=0.03, atol=1.5)


def test_bounds_parity(ph_pair):
    ph_n, _, ph_d = ph_pair
    lag_n = ph_n.lagrangian_bound()
    lag_d = ph_d.lagrangian_bound()
    assert abs(lag_n - lag_d) < 1.0 + 1e-4 * abs(lag_d)
    in_n, f_n = ph_n.evaluate_xhat(ph_n.root_xbar())
    in_d, f_d = ph_d.evaluate_xhat(ph_d.root_xbar())
    assert f_n and f_d
    assert abs(in_n - in_d) < 1.0 + 1e-4 * abs(in_d)


def test_candidate_screening_parity(ph_pair):
    ph_n, _, ph_d = ph_pair
    cands = np.stack([np.asarray(ph_n.root_xbar()),
                      np.asarray(ph_n.root_xbar()) * 0.9])
    on, fn = ph_n.evaluate_candidates(cands)
    od, fd = ph_d.evaluate_candidates(cands)
    assert list(fn) == list(fd)
    np.testing.assert_allclose(on, od, rtol=1e-4, atol=1.0)


def test_certified_resolve_split_native():
    """Force stragglers (tiny fast-solve budget) so the f64 certified
    re-solve runs through the SplitA gather path."""
    ph = PH(dict(OPTS, pdhg_max_iters=80, certify_max_iters=60000),
            NAMES, batch=_native())
    res = ph.solve_loop(certify=True)
    assert bool(np.all(np.asarray(res.converged)))
    # certified objectives match a fully-converged dense reference
    ph_ref = PH(dict(OPTS, no_split_prep=True), NAMES, batch=_dense())
    ref = ph_ref.solve_loop()
    np.testing.assert_allclose(np.asarray(res.obj),
                               np.asarray(ref.obj), rtol=1e-5)


def test_xhat_reduced_system_split_native():
    """Farmer's deltas all sit in eliminated columns, so the reduced
    system of a split-native batch is the (1, M, N) shared fast path
    and A_na is a SplitA over the reduced column space."""
    ph = PH(dict(OPTS), NAMES, batch=_native())
    cache = ph._xhat_cache(None)
    assert cache["A_red"].shape[0] == 1
    assert isinstance(cache["A_na"], SplitA)

"""Streaming subsystem tests (mpisppy_tpu/streaming/): ScenarioSource
block parity with the historical full-batch builders, gather/relabel
semantics, the double-buffered ScenarioStream, AdaptiveSampler growth
monotonicity + RNG round-trip, the SamplingRule/SeqSampling delegation
equivalence, StreamingPH consensus parity with resident PH at small S,
checkpoint/resume bit-parity, the peak-device-residency bound, and the
AST guard that the host-path modules never import jax eagerly.
"""

import ast
import os
import pathlib

import numpy as np
import pytest

import mpisppy_tpu.streaming as streaming_pkg
from mpisppy_tpu import telemetry
from mpisppy_tpu.confidence_intervals import ciutils
from mpisppy_tpu.confidence_intervals.seqsampling import (SamplingRule,
                                                          SeqSampling)
from mpisppy_tpu.models import aircond, farmer, uc
from mpisppy_tpu.streaming import (AdaptiveSampler, BatchSource,
                                   GeneratorSource, ScenarioStream,
                                   StreamClosed, gather_block,
                                   source_for_module)
from mpisppy_tpu.streaming.streaming_ph import StreamingPH

pytestmark = pytest.mark.streaming


# ---- ScenarioSource protocol / model scenario_block parity ---------------

def test_farmer_scenario_block_is_build_batch_on_the_full_range():
    b = farmer.build_batch(12, seedoffset=3)
    bb = farmer.scenario_block(np.arange(12), seedoffset=3)
    assert np.array_equal(np.asarray(b.A), np.asarray(bb.A))
    assert np.array_equal(np.asarray(b.c), np.asarray(bb.c))
    assert np.array_equal(np.asarray(b.ub), np.asarray(bb.ub))
    assert b.tree.scen_names == bb.tree.scen_names


def test_farmer_block_rows_match_global_scenarios():
    full = farmer.build_batch(20)
    blk = farmer.scenario_block(np.array([3, 7, 19]))
    for j, i in enumerate((3, 7, 19)):
        assert np.allclose(np.asarray(blk.A)[j], np.asarray(full.A)[i])
    assert blk.tree.scen_names == ("scen3", "scen7", "scen19")
    # block-uniform probabilities: each block is a valid sampled batch
    assert abs(float(np.sum(np.asarray(blk.tree.prob))) - 1.0) < 1e-12


def test_uc_block_rows_match_global_scenarios():
    full = uc.build_batch(8)
    blk = uc.scenario_block(np.array([2, 5]))
    assert np.allclose(np.asarray(blk.row_lo)[0],
                       np.asarray(full.row_lo)[2])
    assert np.allclose(np.asarray(blk.row_lo)[1],
                       np.asarray(full.row_lo)[5])
    assert blk.tree.scen_names == ("Scenario3", "Scenario6")
    assert blk.shared_A  # the shared matrix never replicates per block


def test_generator_source_validates_indices():
    src = source_for_module(farmer, 10, {})
    assert isinstance(src, GeneratorSource)
    assert src.total_scens == 10
    with pytest.raises(ValueError):
        src.block(np.array([], dtype=np.int64))
    with pytest.raises(IndexError):
        src.block(np.array([10]))
    assert src.names(np.array([0, 9])) == ["scen0", "scen9"]


def test_gather_block_relabels_tree_nodes_and_renormalizes():
    src = aircond.scenario_source(None, {"branching_factors": (3, 2)})
    assert isinstance(src, BatchSource)
    blk = src.block(np.array([0, 3, 5]))
    assert blk.num_scens == 3
    assert abs(float(np.sum(np.asarray(blk.tree.prob))) - 1.0) < 1e-9
    # node ids relabeled to the block's compact universe
    node = np.asarray(blk.tree.node_of)
    assert node.min() >= 0
    assert node.max() < blk.tree.num_nodes
    assert blk.tree.num_nodes <= len(np.unique(node)) + 0 or True
    assert blk.tree.num_nodes == len(np.unique(node))


def test_gather_block_keeps_splitA_shared_block_unreplicated():
    from mpisppy_tpu.ir import SplitA
    full = farmer.build_batch(16, split=True)
    blk = gather_block(full, np.array([1, 4, 9]))
    assert isinstance(blk.A, SplitA)
    # shared matrix is the SAME object (never gathered/replicated)
    assert blk.A.shared is full.A.shared
    assert np.asarray(blk.A.vals).shape[0] == 3
    assert np.allclose(np.asarray(blk.A.vals)[2],
                       np.asarray(full.A.vals)[9])


# ---- ScenarioStream -------------------------------------------------------

def test_stream_preserves_prefetch_order_and_counts():
    src = source_for_module(farmer, 32, {})
    with ScenarioStream(src) as st:
        st.prefetch([0, 1, 2])
        st.prefetch([10, 11])
        i1, b1 = st.next_block()
        i2, b2 = st.next_block()
    assert list(i1) == [0, 1, 2] and b1.num_scens == 3
    assert list(i2) == [10, 11] and b2.num_scens == 2
    s = st.stats()
    assert s["blocks_loaded"] == 2 and s["scenarios_streamed"] == 5
    assert s["prefetch_wait_seconds"] >= 0.0


def test_stream_surfaces_worker_errors_and_close_is_idempotent():
    src = source_for_module(farmer, 8, {})
    st = ScenarioStream(src)
    st.prefetch([99])                     # out of range -> worker error
    with pytest.raises(IndexError):
        st.next_block()
    st.close()
    st.close()
    with pytest.raises(StreamClosed):
        st.prefetch([0])


# ---- AdaptiveSampler ------------------------------------------------------

def test_sampler_growth_is_monotone_and_capped():
    # moderate h -> n_1 well under the universe, and the BM schedule's
    # 2p*ln^2(k) term demands a strictly larger n_k every few rounds
    rule = SamplingRule({"BM_h": 0.3, "BM_hprime": 0.0,
                         "BM_eps": 1e-12, "n0min": 4})
    smp = AdaptiveSampler(rule, total_scens=500, block_size=8, seed=1)
    sizes = [smp.active_n]
    for _ in range(6):
        done = smp.observe(G=1e9, s=1.0)   # huge gap: never certifies
        assert done is False
        sizes.append(smp.active_n)
    assert sizes == sorted(sizes)                 # monotone growth
    assert sizes[-1] > sizes[0]                   # actually grew
    assert all(n <= 500 for n in sizes)           # capped at universe
    assert smp.growth_events >= 1
    idx = smp.draw_block()
    assert idx.size == 8 and np.all(np.diff(idx) > 0)
    assert idx.max() < smp.active_n


def test_sampler_rng_state_roundtrip_replays_draws():
    rule = SamplingRule({"n0min": 16})
    a = AdaptiveSampler(rule, 100, block_size=8, seed=7)
    a.draw_block()
    saved = a.state()
    b = AdaptiveSampler(rule, 100, block_size=8, seed=0)
    b.restore(saved)
    assert np.array_equal(a.draw_block(), b.draw_block())
    assert a.state()["rng_state"] == b.state()["rng_state"]


def test_sampling_rule_matches_seqsampling_delegation():
    opts = {"BM_h": 1.2, "BM_hprime": 0.3, "BM_eps": 0.5, "n0min": 9}
    rule = SamplingRule(opts)
    seq = SeqSampling("mpisppy_tpu.models.farmer", opts)
    for (k, G, s, nk) in [(1, None, None, None), (2, 10.0, 4.0, 9),
                          (3, 2.0, 1.0, 20)]:
        assert rule.sample_size(k, G, s, nk) == \
            seq._sample_size(k, G, s, nk)
    for (G, s, nk) in [(10.0, 1.0, 9), (0.1, 1.0, 30)]:
        assert rule.should_continue(G, s, nk) == seq._continue(G, s, nk)
    assert rule.ci_upper(2.0) == seq.rule.ci_upper(2.0)


# ---- StreamingPH ----------------------------------------------------------

def _stream_opts(**kw):
    o = {"PHIterLimit": 6, "defaultPHrho": 1.0, "solver_eps": 1e-6,
         "stream_block_size": 8, "stream_check_every": 100,
         "stream_seed": 0}
    o.update(kw)
    return o


def test_streaming_ph_peak_residency_bounded_by_block_width():
    S = 64
    src = source_for_module(farmer, S, {})
    sph = StreamingPH(_stream_opts(PHIterLimit=3), src, module=None)
    sph.stream_main(finalize=False)
    st = sph.stream_stats()
    # the residency acceptance bound: device scenario residency never
    # exceeds the configured (bucketed) block width, which is << S
    assert st["peak_block_scens"] <= st["block_width"]
    assert sph.batch.num_scens == st["block_width"]
    assert st["block_width"] < S
    assert st["sampled_scenarios"] <= S
    # the solved mask stays inside the active prefix
    assert not sph.solved[sph.sampler.active_n:].any()


def test_streaming_ph_reaches_full_ph_consensus_and_verdict():
    """Streamed randomized PH at small S lands on the same consensus
    region as resident PH.ph_main, and the SAME certification rule
    reaches the SAME verdict on both candidates (matched estimator
    seed), which is the acceptance's 'same certified verdict'."""
    from mpisppy_tpu.opt.ph import PH

    S = 24
    batch = farmer.build_batch(S)
    ph = PH({"PHIterLimit": 30, "defaultPHrho": 1.0,
             "convthresh": 1e-3, "solver_eps": 1e-6},
            [f"scen{i}" for i in range(S)], batch=batch)
    ph.ph_main()
    xbar_full = np.asarray(ph.root_xbar())

    src = BatchSource(batch, name="farmer24")
    sph = StreamingPH(
        _stream_opts(PHIterLimit=25, stream_block_size=8,
                     stream_check_every=5,
                     BM_h=2.0, BM_hprime=0.4, BM_eps=200.0),
        src, module=farmer)
    sph.stream_main(finalize=False)
    xbar_stream = sph.xbar_host

    # consensus parity: same region of the acreage simplex
    denom = max(float(np.abs(xbar_full).max()), 1.0)
    assert np.abs(xbar_stream - xbar_full).max() / denom < 0.15

    # identical rule + estimator seed -> identical certified verdict
    rule = SamplingRule({"BM_h": 2.0, "BM_hprime": 0.4, "BM_eps": 200.0})
    cfg = {"solver_eps": 1e-6}
    nk = 16
    verdicts = []
    for cand in (xbar_stream, xbar_full):
        est = ciutils.gap_estimators(cand, farmer, num_scens=nk,
                                     seed=424242, cfg=cfg)
        verdicts.append(
            not rule.should_continue(est["G"], est["std"], nk))
    assert verdicts[0] == verdicts[1]


def test_streaming_ph_certifies_with_internal_rule():
    src = source_for_module(farmer, 64, {})
    sph = StreamingPH(
        _stream_opts(PHIterLimit=25, stream_check_every=3,
                     BM_h=2.0, BM_hprime=0.5, BM_eps=500.0),
        src, module=farmer)
    conv, eobj, trivial = sph.stream_main()
    assert sph.certified is not None
    ci = sph.certified["CI"]
    assert ci[0] == 0.0 and ci[1] > 0.0
    # the CI upper is exactly the rule's h*s + eps form
    assert ci[1] == pytest.approx(
        sph.rule.ci_upper(sph.certified["s"]))
    assert np.isfinite(eobj) and np.isfinite(trivial)
    st = sph.stream_stats()
    assert st["ci_gap"] == ci


def test_streaming_ph_checkpoint_resume_is_bit_equal(tmp_path):
    batch = farmer.build_batch(24)

    def mk(extra):
        return StreamingPH(_stream_opts(**extra),
                           BatchSource(batch, name="farmer24"),
                           module=None)

    a = mk({})
    a.stream_main(finalize=False)

    ck = os.fspath(tmp_path / "stream_ck")
    b1 = mk({"PHIterLimit": 3, "run_checkpoint": ck,
             "checkpoint_every": 1})
    b1.stream_main(finalize=False)
    b2 = mk({"resume_from": ck})
    b2.stream_main(finalize=False)

    assert np.array_equal(a.W_host, b2.W_host)
    assert np.array_equal(a.x_na_host, b2.x_na_host)
    assert np.array_equal(a.xbar_host, b2.xbar_host)
    assert np.array_equal(a.solved, b2.solved)
    assert a.conv == b2.conv
    assert int(a.state.it) == int(b2.state.it)
    # the sampler RNG and the in-flight draw replayed exactly
    assert a.sampler.state()["rng_state"] == \
        b2.sampler.state()["rng_state"]
    assert np.array_equal(a._pending_indices, b2._pending_indices)


def test_stream_checkpoint_rejects_plain_ph_format(tmp_path):
    from mpisppy_tpu.resilience.checkpoint import load_stream_checkpoint
    batch = farmer.build_batch(24)
    sph = StreamingPH(_stream_opts(PHIterLimit=1),
                      BatchSource(batch), module=None)
    sph.stream_main(finalize=False)
    p = os.fspath(tmp_path / "plain.npz")
    np.savez(p, W=np.zeros((24, 3)))    # no stream_format marker
    with pytest.raises(ValueError, match="plain PH run checkpoint"):
        load_stream_checkpoint(p, sph)


def test_streaming_ph_rejects_multistage_sources():
    src = aircond.scenario_source(None, {"branching_factors": (3, 2)})
    with pytest.raises(NotImplementedError, match="two-stage"):
        StreamingPH(_stream_opts(), src, module=None)


def test_streaming_ph_rejects_w_bounds():
    src = source_for_module(farmer, 16, {})
    sph = StreamingPH(_stream_opts(PHIterLimit=1), src, module=None)
    with pytest.raises(NotImplementedError):
        sph.check_W_bound_supported()


# ---- telemetry + laziness guards ------------------------------------------

def test_stream_counters_keys_stable_on_and_off():
    keys = {"stream_blocks_loaded", "stream_scenarios_streamed",
            "stream_sample_growth_events", "stream_supersteps",
            "stream_source_retries", "stream_source_giveups",
            "stream_active_sample_size",
            "stream_prefetch_wait_seconds"}
    off = telemetry.stream_counters(
        telemetry.Telemetry({"enabled": False}).registry)
    assert set(off) == keys
    assert all(v == 0 for v in off.values())

    tel = telemetry.Telemetry({"enabled": True})
    src = source_for_module(farmer, 16, {})
    st = ScenarioStream(src, telemetry=tel)
    st.prefetch([0, 1, 2])
    st.next_block()
    st.close()
    on = telemetry.stream_counters(tel.registry)
    assert set(on) == keys
    assert on["stream_blocks_loaded"] == 1
    assert on["stream_scenarios_streamed"] == 3


@pytest.mark.parametrize("mod", ["__init__.py", "source.py",
                                 "stream.py", "sampler.py",
                                 "store.py", "readahead.py"])
def test_streaming_host_modules_never_import_jax_eagerly(mod):
    """AST guard (module-level statements only): the host-path modules
    must be importable without pulling in the accelerator runtime —
    jax is allowed only lazily inside functions (streaming_ph.py is
    the accelerator-side driver and is exempt)."""
    path = pathlib.Path(streaming_pkg.__file__).parent / mod
    tree = ast.parse(path.read_text())
    for node in tree.body:
        if isinstance(node, ast.Import):
            assert not any(a.name.split(".")[0] == "jax"
                           for a in node.names), f"{mod}: import jax"
        elif isinstance(node, ast.ImportFrom):
            assert (node.module or "").split(".")[0] != "jax", \
                f"{mod}: from jax import ..."
            assert node.module != "mpisppy_tpu.streaming.streaming_ph", \
                f"{mod}: eager import of the jax-backed driver"


# ---- retry-with-capped-backoff source wrapper (PR 10) ---------------------

def test_retrying_source_recovers_from_transient_failures():
    from mpisppy_tpu.resilience.chaos import ChaosInjector
    from mpisppy_tpu.streaming.source import RetryingSource

    src = RetryingSource(
        BatchSource(farmer.build_batch(8)), retries=2,
        backoff=0.001, backoff_cap=0.002,
        chaos=ChaosInjector({"block_build_fail": 2}))
    b = src.block(np.arange(3))          # fails twice, succeeds third
    assert b.num_scens == 3
    assert len(src.retry_log) == 2
    assert [r["attempt"] for r in src.retry_log] == [1, 2]
    assert all("block build failure" in r["error"]
               for r in src.retry_log)
    assert all(r["delay"] <= 0.002 for r in src.retry_log)  # capped
    # names delegate to the inner source untouched
    assert src.names([0]) == ["scen0"]
    assert src.total_scens == 8


def test_retrying_source_exhaustion_is_structured():
    from mpisppy_tpu.resilience.chaos import ChaosError, ChaosInjector
    from mpisppy_tpu.streaming.source import (RetryingSource,
                                              SourceBuildError)

    src = RetryingSource(
        BatchSource(farmer.build_batch(8)), retries=1,
        backoff=0.001, backoff_cap=0.002,
        chaos=ChaosInjector({"block_build_fail": 5}))
    with pytest.raises(SourceBuildError,
                       match="failed after 1 retry") as ei:
        src.block(np.arange(3))
    e = ei.value
    assert e.attempts == 2               # first try + one retry
    assert e.indices == (0, 1, 2)
    assert isinstance(e.last_error, ChaosError)
    assert len(src.retry_log) == 1       # the final attempt is not a retry


def test_retrying_source_wraps_non_chaos_errors_too():
    from mpisppy_tpu.streaming.source import (RetryingSource,
                                              SourceBuildError)

    src = RetryingSource(BatchSource(farmer.build_batch(4)), retries=0,
                         backoff=0.001)
    with pytest.raises(SourceBuildError, match="failed after 0 retries"):
        src.block(np.array([99]))        # IndexError inside, wrapped
    assert src.retry_log == []


def test_retrying_source_backoff_is_jittered_and_capped():
    """PR 11: fixed retry delays synchronize retry storms across
    concurrent blocks — the delay must carry jitter, the jitter must
    never push a delay past backoff_cap, and every retry bumps the
    stream.source_retries telemetry counter."""
    from mpisppy_tpu.resilience.chaos import ChaosInjector
    from mpisppy_tpu.resilience.supervisor import restart_delay
    from mpisppy_tpu.streaming.source import RetryingSource

    tel = telemetry.configure(True)
    try:
        src = RetryingSource(
            BatchSource(farmer.build_batch(8)), retries=6,
            backoff=0.0005, backoff_cap=0.002,
            chaos=ChaosInjector({"block_build_fail": 6}),
            jitter=0.5, jitter_seed=7)
        b = src.block(np.arange(2))
        assert b.num_scens == 2
        delays = [r["delay"] for r in src.retry_log]
        assert len(delays) == 6
        # capped: jitter may spread a delay but never past backoff_cap
        assert all(0.0 <= d <= 0.002 for d in delays)
        # jittered: the observed delays are NOT the deterministic ladder
        ladder = [restart_delay(a, 0.0005, 0.002) for a in range(1, 7)]
        assert delays != ladder
        # attempts 3..6 all sit on the capped ladder rung (0.002) —
        # with jitter their delays still disagree with each other
        assert len({round(d, 9) for d in delays[2:]}) > 1
        assert telemetry.stream_counters(tel.registry)[
            "stream_source_retries"] == 6
    finally:
        telemetry.reset()


def test_retrying_source_jitter_zero_reproduces_ladder():
    """jitter=0 is the escape hatch: delays collapse back to the exact
    supervisor restart ladder (the pre-jitter behaviour)."""
    from mpisppy_tpu.resilience.chaos import ChaosInjector
    from mpisppy_tpu.resilience.supervisor import restart_delay
    from mpisppy_tpu.streaming.source import RetryingSource

    src = RetryingSource(
        BatchSource(farmer.build_batch(8)), retries=3,
        backoff=0.0005, backoff_cap=0.002,
        chaos=ChaosInjector({"block_build_fail": 3}), jitter=0)
    src.block(np.arange(2))
    assert [r["delay"] for r in src.retry_log] == [
        restart_delay(a, 0.0005, 0.002) for a in range(1, 4)]


def test_streaming_ph_wires_source_retries_from_options():
    """source_retries>0 wraps the source BEFORE the template block
    build, so even the constructor-time build survives a transient
    fault — and the run completes normally afterwards."""
    from mpisppy_tpu.streaming.source import RetryingSource

    sph = StreamingPH(
        _stream_opts(PHIterLimit=2, source_retries=2,
                     source_backoff=0.001, source_backoff_cap=0.002,
                     chaos={"block_build_fail": 1}),
        BatchSource(farmer.build_batch(24)), module=None)
    assert isinstance(sph.source, RetryingSource)
    assert len(sph.source.retry_log) >= 1   # the template build retried
    sph.stream_main(finalize=False)
    assert np.isfinite(sph.conv)


# ---- source error paths (PR 14 satellites) --------------------------------

def test_source_build_error_carries_retry_state_and_giveups_counter():
    """Terminal exhaustion surfaces THIS call's attempt/backoff ladder
    on the exception (not just the wrapper's cumulative log) and bumps
    stream.source_giveups — retries alone would leave give-ups
    invisible to telemetry."""
    from mpisppy_tpu.resilience.chaos import ChaosInjector
    from mpisppy_tpu.streaming.source import (RetryingSource,
                                              SourceBuildError)

    tel = telemetry.configure(True)
    try:
        src = RetryingSource(
            BatchSource(farmer.build_batch(8)), retries=2,
            backoff=0.001, backoff_cap=0.002,
            chaos=ChaosInjector({"block_build_fail": 99}))
        with pytest.raises(SourceBuildError) as ei:
            src.block(np.arange(2))
        e = ei.value
        assert len(e.retry_state) == 2
        assert [r["attempt"] for r in e.retry_state] == [1, 2]
        assert all(set(r) == {"attempt", "error", "delay"}
                   for r in e.retry_state)
        # a SECOND failing call's exception carries only ITS ladder
        with pytest.raises(SourceBuildError) as ei2:
            src.block(np.arange(2))
        assert len(ei2.value.retry_state) == 2
        assert len(src.retry_log) == 4       # cumulative wrapper log
        ctr = telemetry.stream_counters(tel.registry)
        assert ctr["stream_source_giveups"] == 2
        assert ctr["stream_source_retries"] == 4
    finally:
        telemetry.reset()


def test_generator_builder_raising_mid_block_surfaces_on_next_block():
    """A builder that dies partway through a block (not at validation
    time) propagates through the stream worker and re-raises on
    next_block() — the stream never emits a half-built block."""
    from mpisppy_tpu.streaming.source import GeneratorSource

    calls = {"n": 0}

    def flaky(idx):
        calls["n"] += 1
        if calls["n"] >= 2:
            raise RuntimeError("store died mid-block")
        return farmer.scenario_block(idx)

    src = GeneratorSource("flaky", 16, flaky)
    st = ScenarioStream(src)
    st.prefetch(np.arange(4))
    st.prefetch(np.arange(4, 8))
    i0, b0 = st.next_block()             # first build succeeds
    assert b0.num_scens == 4
    with pytest.raises(RuntimeError, match="mid-block"):
        st.next_block()
    st.close()


def test_batch_source_rejects_empty_index_set():
    src = BatchSource(farmer.build_batch(8))
    with pytest.raises(ValueError, match="empty scenario block"):
        src.block(np.array([], dtype=np.int64))
    with pytest.raises(IndexError):
        src.block(np.array([8]))


def test_gather_block_uniform_fallback_on_all_zero_prob_block():
    """Gathering a block whose scenario probabilities sum to zero
    (degenerate corner of prob renormalization) falls back to
    block-uniform instead of dividing by zero."""
    import dataclasses

    from mpisppy_tpu.streaming.source import gather_block

    batch = farmer.build_batch(8)
    prob = np.asarray(batch.tree.prob, np.float64).copy()
    prob[:3] = 0.0
    batch = dataclasses.replace(
        batch, tree=dataclasses.replace(batch.tree, prob=prob))
    blk = gather_block(batch, np.array([0, 1, 2]))   # all-zero subset
    p = np.asarray(blk.tree.prob)
    assert np.allclose(p, 1.0 / 3.0)
    assert abs(p.sum() - 1.0) < 1e-12

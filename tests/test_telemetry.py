"""Telemetry subsystem tests (mpisppy_tpu/telemetry/): registry and
tracer units, Chrome trace export, wheel smoke producing the merged
trace.json + metrics.jsonl, supervisor lifecycle events, and the two
structural guarantees of the zero-cost-when-off contract —

  * the telemetry package never imports jax / never syncs the device
    (AST guard over the package AND over every instrumented hot-path
    module, pinned to an allowlist of block_until_ready sites);
  * a telemetry-disabled PH iteration runs the pre-telemetry fused
    superstep with <2% measured overhead.
"""

import ast
import inspect
import json
import subprocess
import sys
import time
import types
from pathlib import Path

import numpy as np
import pytest

import mpisppy_tpu
from mpisppy_tpu import phbase, telemetry
from mpisppy_tpu.models import farmer
from mpisppy_tpu.opt.ph import PH
from mpisppy_tpu.telemetry import export
from mpisppy_tpu.telemetry.metrics import (MetricsRegistry, NULL_COUNTER,
                                           NULL_GAUGE, NULL_HISTOGRAM)
from mpisppy_tpu.telemetry.tracer import NULL_SPAN, Tracer
from mpisppy_tpu.utils import mfu as _mfu
from mpisppy_tpu.utils.wtracker import WTracker

pytestmark = pytest.mark.telemetry

S = 3
NAMES = [f"scen{i}" for i in range(S)]
OPTS = {"defaultPHrho": 1.0, "PHIterLimit": 40, "convthresh": 0.0,
        "pdhg_eps": 1e-7, "pdhg_max_iters": 20000}


@pytest.fixture(autouse=True)
def _fresh_telemetry():
    """Each test starts and ends with no process-global telemetry (the
    env var is absent in the test tier, so get() is disabled)."""
    telemetry.reset()
    yield
    telemetry.reset()


# ---------------------------------------------------------------------------
# metrics registry
# ---------------------------------------------------------------------------
class TestRegistry:
    def test_counter_gauge_histogram(self):
        r = MetricsRegistry()
        c = r.counter("a")
        c.inc()
        c.inc(4)
        assert c.value == 5
        assert r.counter("a") is c          # get-or-create
        g = r.gauge("b")
        g.set(2.5)
        assert g.value == 2.5
        h = r.histogram("t")
        for v in (0.001, 0.01, 3.0):
            h.observe(v)
        assert h.count == 3
        assert h.min == 0.001 and h.max == 3.0
        assert abs(h.mean - (3.011 / 3)) < 1e-12
        assert sum(h.bucket_counts) == 3

    def test_histogram_inf_tail(self):
        r = MetricsRegistry()
        h = r.histogram("t")
        h.observe(1e9)                      # beyond every bucket
        assert h.bucket_counts[-1] == 1

    def test_event_log_bounded(self):
        r = MetricsRegistry(max_events=4)
        for i in range(10):
            r.event("e", i=i)
        evs = r.events("e")
        assert len(evs) == 4
        assert evs[-1]["i"] == 9
        assert all("ts" in e for e in evs)

    def test_disabled_registry_returns_nulls(self):
        r = MetricsRegistry(enabled=False)
        assert r.counter("x") is NULL_COUNTER
        assert r.gauge("x") is NULL_GAUGE
        assert r.histogram("x") is NULL_HISTOGRAM
        r.counter("x").inc()
        r.gauge("x").set(1.0)
        r.histogram("x").observe(1.0)
        r.event("boom")
        snap = r.snapshot()
        assert snap["counters"] == {} and snap["events"] == []

    def test_jsonl_snapshot_strict_json(self, tmp_path):
        r = MetricsRegistry()
        r.counter("window.writes").inc(3)
        r.gauge("hub.best_outer").set(float("-inf"))   # pre-seed bound
        r.gauge("hub.best_inner").set(float("nan"))
        p = tmp_path / "metrics.jsonl"
        r.write_jsonl(str(p))
        r.write_jsonl(str(p))               # JSONL appends
        lines = p.read_text().strip().splitlines()
        assert len(lines) == 2
        snap = json.loads(lines[-1])        # strict parser
        assert snap["counters"]["window.writes"] == 3
        assert snap["gauges"]["hub.best_outer"] is None   # non-finite
        assert snap["gauges"]["hub.best_inner"] is None

    def test_prometheus_text(self, tmp_path):
        r = MetricsRegistry()
        r.counter("window.writes").inc(2)
        r.gauge("ph.conv").set(0.5)
        h = r.histogram("solve.seconds")
        h.observe(0.02)
        h.observe(200.0)
        text = r.prometheus_text()
        assert "# TYPE window_writes counter\nwindow_writes 2" in text
        assert "ph_conv 0.5" in text
        # cumulative le buckets + +Inf + sum/count
        assert 'solve_seconds_bucket{le="+Inf"} 2' in text
        assert "solve_seconds_count 2" in text
        assert "solve_seconds_sum 200.02" in text
        p = tmp_path / "prom.txt"
        r.write_prometheus(str(p))
        assert p.read_text() == text


# ---------------------------------------------------------------------------
# tracer + chrome export
# ---------------------------------------------------------------------------
class TestTracer:
    def test_span_records_complete_event(self):
        tr = Tracer()
        with tr.span("work", args={"k": 1}):
            time.sleep(0.002)
        recs = tr.records()
        assert len(recs) == 1
        kind, name, pid, tid, ts, dur, args = recs[0]
        assert kind == "X" and name == "work"
        assert pid == tr._pid and args == {"k": 1}
        assert dur >= 1000                  # at least 1 ms, in µs

    def test_ring_wraps_and_counts_drops(self):
        tr = Tracer(capacity=16)
        for i in range(40):
            tr.instant(f"e{i}")
        recs = tr.records()
        assert len(recs) == 16
        assert recs[-1][1] == "e39"         # newest survives
        assert tr.emitted == 40 and tr.dropped == 24

    def test_tracks_get_distinct_row_pids(self):
        tr = Tracer(main_label="hub")
        with tr.span("hub-side"):
            pass
        with tr.track("spoke0:Lagrangian"):
            with tr.span("step"):
                pass
        with tr.track("spoke1:Xhat"):
            tr.instant("evt")
        pids = {rec[2] for rec in tr.records()}
        assert len(pids) == 3
        assert tr._pid in pids
        assert len(tr._tracks) == 2
        assert len(set(tr._tracks.values()) | {tr._pid}) == 3

    def test_record_span_and_counter(self):
        tr = Tracer()
        t0 = time.monotonic_ns()
        tr.record_span("solve.loop", t0, t0 + 5_000_000)
        tr.counter("hub.bounds", {"outer": -1.0})
        recs = tr.records()
        assert recs[0][0] == "X" and recs[0][5] == 5000   # µs
        assert recs[1][0] == "C" and recs[1][4] == {"outer": -1.0}

    def test_chrome_export_is_valid_trace(self, tmp_path):
        tr = Tracer(main_label="hub")
        with tr.span("a"):
            pass
        with tr.track("spoke0"):
            tr.instant("b")
        p = tmp_path / "trace.json"
        export.write_trace(str(p), export.chrome_events(tr))
        data = json.loads(p.read_text())
        evs = data["traceEvents"]
        meta = [e for e in evs if e["ph"] == "M"]
        names = {e["args"]["name"] for e in meta}
        assert {"hub", "spoke0"} <= names
        assert all({"ph", "pid"} <= set(e) for e in evs)
        assert any(e["ph"] == "X" and e["name"] == "a" for e in evs)
        assert any(e["ph"] == "i" and e["name"] == "b" for e in evs)

    def test_merge_traces_metadata_first_then_by_ts(self, tmp_path):
        tr1, tr2 = Tracer(main_label="hub"), Tracer(main_label="spokeP")
        with tr1.span("one"):
            time.sleep(0.001)
        with tr2.span("two"):
            pass
        f2 = tmp_path / "spoke.json"
        export.write_trace(str(f2), export.chrome_events(tr2))
        out = tmp_path / "trace.json"
        export.merge_traces(str(out),
                            event_lists=[export.chrome_events(tr1)],
                            trace_files=[str(f2), str(tmp_path / "no")])
        evs = json.loads(out.read_text())["traceEvents"]
        kinds = [e["ph"] for e in evs]
        assert kinds[:2] == ["M", "M"]
        rest = [e["ts"] for e in evs if e["ph"] != "M"]
        assert rest == sorted(rest)

    def test_corrupt_trace_file_ignored(self, tmp_path):
        p = tmp_path / "bad.json"
        p.write_text("{not json")
        assert export.load_trace_events(str(p)) == []


# ---------------------------------------------------------------------------
# config facade
# ---------------------------------------------------------------------------
class TestConfig:
    def test_default_disabled(self):
        tel = telemetry.get()
        assert not tel.enabled
        assert tel.span("x") is NULL_SPAN
        assert tel.counter("c") is NULL_COUNTER

    def test_config_forms(self, tmp_path):
        assert telemetry.configure(True).enabled
        assert not telemetry.configure("off").enabled
        tel = telemetry.configure(str(tmp_path))
        assert tel.enabled and tel.out_dir == str(tmp_path)
        tel = telemetry.configure({"enabled": True,
                                   "phase_timing": False})
        assert tel.enabled and not tel.phase_timing

    def test_env_var_wins(self, monkeypatch):
        monkeypatch.setenv(telemetry.ENV_VAR, "0")
        assert not telemetry.configure({"enabled": True}).enabled
        monkeypatch.setenv(telemetry.ENV_VAR, "1")
        assert telemetry.configure(None).enabled

    def test_env_dir_form(self, monkeypatch, tmp_path):
        monkeypatch.setenv(telemetry.ENV_VAR, str(tmp_path))
        telemetry.reset()
        tel = telemetry.get()
        assert tel.enabled and tel.out_dir == str(tmp_path)

    def test_configure_from_options_is_idempotent(self):
        a = telemetry.configure_from_options({"enabled": True})
        a.counter("keep").inc()
        b = telemetry.configure_from_options({"enabled": True})
        assert a is b                       # same registry survives
        assert b.registry.counter("keep").value == 1
        c = telemetry.configure_from_options({"enabled": True,
                                              "capacity": 1024})
        assert c is not a
        # None never resets an installed instance
        assert telemetry.configure_from_options(None) is c

    def test_traffic_counters_stable_keys_when_off(self):
        tc = telemetry.traffic_counters()
        assert tc == {"window_writes": 0, "window_reads": 0,
                      "window_stale_reads": 0, "window_kill_signals": 0,
                      "window_bound_rejects": 0}


# ---------------------------------------------------------------------------
# instrumented hot paths
# ---------------------------------------------------------------------------
def _ph(tel_cfg, batch=None, **overrides):
    opts = {**OPTS, "telemetry": tel_cfg, **overrides}
    return PH(opts, NAMES, batch=batch or farmer.build_batch(S))


class TestInstrumentation:
    def test_disabled_mode_records_nothing(self):
        ph = _ph(None)
        assert not ph._tel.enabled
        ph.Iter0()
        for _ in range(2):
            ph.ph_iteration()
        tel = telemetry.get()
        assert tel.tracer.records() == []
        assert tel.registry._counters == {}
        assert tel.registry.events() == []

    def test_enabled_ph_metrics(self):
        ph = _ph({"enabled": True, "phase_timing": False})
        ph.Iter0()
        ph.ph_iteration()
        ph.ph_iteration()
        r = ph._tel.registry
        assert r.counter("ph.iterations").value == 2
        # the fused superstep never routes through solve_loop; Iter0 does
        assert r.counter("solve.calls").value >= 1
        assert r.counter("solve.kernel_iters").value > 0
        assert r.histogram("ph.iteration_seconds").count == 2
        assert r.histogram("solve.seconds").count >= 1
        assert r.events("ph.iter0") and \
            r.events("ph.iter0")[0]["trivial_bound"] == pytest.approx(
                ph.trivial_bound)
        assert r.gauge("mfu.kernel_flops").value > 0
        assert r.gauge("mfu.iters_per_sec").value > 0
        names = {rec[1] for rec in ph._tel.tracer.records()}
        assert {"solve.loop", "ph.iteration"} <= names

    def test_phased_superstep_matches_fused(self):
        b = farmer.build_batch(S)
        ph_f = _ph(False, batch=b)
        ph_f.Iter0()
        telemetry.reset()
        ph_p = _ph({"enabled": True, "phase_timing": True}, batch=b)
        ph_p.Iter0()
        for _ in range(3):
            ph_f.ph_iteration()
            ph_p.ph_iteration()
        for field in ("x", "xbar", "W", "conv"):
            np.testing.assert_allclose(
                np.asarray(getattr(ph_p.state, field)),
                np.asarray(getattr(ph_f.state, field)),
                rtol=1e-6, atol=1e-8, err_msg=field)
        h = ph_p._tel.registry.histogram
        for k in ("solve", "psum", "w_update", "conv"):
            assert h(f"ph.phase.{k}_seconds").count == 3

    def test_mfu_record_to_registry(self, monkeypatch):
        monkeypatch.setenv("TPU_PEAK_FLOPS", "1e12")
        r = MetricsRegistry()
        _mfu.record_to_registry(r, 1e12, 2.0, kernel_iters=100)
        assert r.gauge("mfu.kernel_flops").value == 1e12
        assert r.gauge("mfu.iters_per_sec").value == 50.0
        assert r.gauge("mfu.mfu").value == pytest.approx(0.5)
        off = MetricsRegistry(enabled=False)
        _mfu.record_to_registry(off, 1e12, 2.0)   # must be a no-op
        assert off.snapshot()["gauges"] == {}


class TestWTracker:
    def test_ring_buffer_is_deque(self):
        import collections
        fake = types.SimpleNamespace(state=None)
        wt = WTracker(fake, wlen=10)
        assert isinstance(wt._hist, collections.deque)
        assert wt._hist.maxlen == 10
        for i in range(15):
            fake.state = types.SimpleNamespace(
                it=i, W=np.full((2, 3), float(i)))
            wt.grab_local_Ws()
        assert len(wt._hist) == 10
        assert wt._hist[0][0] == 5          # oldest evicted
        mean, std = wt.moving_stats()
        assert mean.shape == (2, 3)
        assert mean[0, 0] == pytest.approx(np.mean(range(5, 15)))


# ---------------------------------------------------------------------------
# wheel smoke: merged trace + metrics artifacts
# ---------------------------------------------------------------------------
class TestWheelSmoke:
    def test_farmer_wheel_writes_merged_trace_and_metrics(self, tmp_path):
        from mpisppy_tpu.cylinders.hub import PHHub
        from mpisppy_tpu.cylinders.lagrangian_bounder import (
            LagrangianOuterBound)
        from mpisppy_tpu.cylinders.xhatshufflelooper_bounder import (
            XhatShuffleInnerBound)
        from mpisppy_tpu.spin_the_wheel import WheelSpinner
        from mpisppy_tpu.utils.xhat_eval import Xhat_Eval

        tel_cfg = {"enabled": True, "dir": str(tmp_path),
                   "prometheus": True}
        b = farmer.build_batch(S)
        opts = {**OPTS, "PHIterLimit": 10, "telemetry": tel_cfg}
        hub_dict = {
            "hub_class": PHHub,
            "hub_kwargs": {"options": {"rel_gap": 1e-4, "abs_gap": 1.0,
                                       "telemetry": tel_cfg}},
            "opt_class": PH,
            "opt_kwargs": {"options": opts, "all_scenario_names": NAMES,
                           "batch": b},
        }
        spoke_dicts = [
            {"spoke_class": LagrangianOuterBound,
             "spoke_kwargs": {"options": {"telemetry": tel_cfg}},
             "opt_class": PH,
             "opt_kwargs": {"options": dict(opts),
                            "all_scenario_names": NAMES}},
            {"spoke_class": XhatShuffleInnerBound,
             "spoke_kwargs": {"options": {"telemetry": tel_cfg}},
             "opt_class": Xhat_Eval,
             "opt_kwargs": {"options": dict(opts),
                            "all_scenario_names": NAMES}},
        ]
        WheelSpinner(hub_dict, spoke_dicts, mode="interleaved").spin()

        # -- merged Chrome trace: one row per hub/spoke ------------------
        trace = tmp_path / "trace.json"
        assert trace.exists()
        evs = json.loads(trace.read_text())["traceEvents"]
        meta_names = {e["args"]["name"] for e in evs if e["ph"] == "M"}
        assert "hub" in meta_names
        assert any(n.startswith("spoke0:") for n in meta_names)
        assert any(n.startswith("spoke1:") for n in meta_names)
        pids = {e["pid"] for e in evs if e["ph"] != "M"}
        assert len(pids) >= 3               # hub + 2 spoke rows
        names = {e["name"] for e in evs}
        assert "hub.sync" in names
        assert "LagrangianOuterBound.step" in names
        assert "XhatShuffleInnerBound.step" in names

        # -- metrics snapshot --------------------------------------------
        mpath = tmp_path / "metrics.jsonl"
        assert mpath.exists()
        snap = json.loads(mpath.read_text().strip().splitlines()[-1])
        c = snap["counters"]
        assert c["window.writes"] > 0
        assert c["window.reads"] > 0
        assert c["window.kill_signals"] >= 2
        assert c["ph.iterations"] > 0
        # phase timing was on (default): superstep phase histograms
        assert snap["histograms"]["ph.phase.solve_seconds"]["count"] > 0
        assert (tmp_path / "prometheus.txt").exists()


# ---------------------------------------------------------------------------
# supervisor lifecycle events
# ---------------------------------------------------------------------------
def _fake_hub(n):
    from mpisppy_tpu.cylinders.spcommunicator import Window
    hub = types.SimpleNamespace(
        options={},
        spokes=[types.SimpleNamespace(proc=None, spoke_name=f"Spoke{i}")
                for i in range(n)],
        pairs=[types.SimpleNamespace(to_hub=Window(1)) for _ in range(n)],
        failed=[])
    hub._mark_spoke_failed = lambda i, exc: hub.failed.append(
        (i, str(exc)))
    return hub


def _sleeper_spawn(spec, workdir, tag):
    return subprocess.Popen([sys.executable, "-c",
                             "import time; time.sleep(60)"])


class TestSupervisorEvents:
    def _drive(self, sup, until, timeout=30.0):
        t0 = time.monotonic()
        while not until() and time.monotonic() - t0 < timeout:
            sup.poll(force=True)
            time.sleep(0.02)
        assert until(), "supervisor never reached the expected state"

    def test_restart_and_prune_events_recorded(self):
        from mpisppy_tpu.resilience.supervisor import SpokeSupervisor
        telemetry.configure({"enabled": True})
        hub = _fake_hub(1)    # no .telemetry -> falls back to get()
        sup = SpokeSupervisor(
            hub, specs=[{}], workdir=".", spawn_fn=_sleeper_spawn,
            options={"supervise_interval": 0.0,
                     "spoke_hang_timeout": 0.3,
                     "spoke_max_restarts": 1,
                     "spoke_restart_backoff": 0.01,
                     "spoke_term_deadline": 2.0})
        sup.start()
        try:
            self._drive(sup, lambda: sup.restarts[0] == 1)
            self._drive(sup, lambda: sup.spokes_failed == 1)
        finally:
            sup.kill_all()
        r = telemetry.get().registry
        # two incarnations spawned, one restart, then pruned
        assert len(r.events("supervisor.spawn")) == 2
        assert len(r.events("supervisor.restart")) == 1
        assert r.events("supervisor.restart")[0]["spoke"] == 0
        assert len(r.events("supervisor.prune")) == 1
        assert r.events("supervisor.sigterm")   # hang kill path
        assert r.counter("supervisor.restarts").value == 1
        assert r.counter("supervisor.spokes_failed").value == 1
        # heartbeat-age gauge was tracked while the spoke was live
        assert "supervisor.heartbeat_age.spoke0" in r._gauges

    def test_disabled_supervisor_emits_nothing(self):
        from mpisppy_tpu.resilience.supervisor import SpokeSupervisor
        hub = _fake_hub(1)

        def quick_spawn(spec, workdir, tag):
            return subprocess.Popen([sys.executable, "-c", "pass"])

        sup = SpokeSupervisor(hub, specs=[{}], workdir=".",
                              spawn_fn=quick_spawn,
                              options={"supervise_interval": 0.0})
        sup.start()
        hub.spokes[0].proc.wait(timeout=30)
        sup.poll(force=True)
        assert telemetry.get().registry.events() == []


# ---------------------------------------------------------------------------
# zero-cost-when-off guards
# ---------------------------------------------------------------------------
ROOT = Path(mpisppy_tpu.__file__).resolve().parent

# the ONLY functions in instrumented hot-path modules allowed to hold a
# device sync; anything new must be reviewed against the telemetry
# zero-cost contract (doc/src/telemetry.md) and added here explicitly
SYNC_ALLOWLIST = {
    "phbase.py": {"_run_superstep", "_superstep_phased"},
    "spopt.py": {"solve_loop", "_certified_resolve",
                 "evaluate_candidates"},
    "cylinders/spcommunicator.py": set(),
    "cylinders/spoke.py": set(),
    "cylinders/hub.py": set(),
    "cylinders/proc.py": set(),
    "resilience/supervisor.py": set(),
    "spin_the_wheel.py": set(),
}


def _sync_functions(path):
    """Names of functions whose body mentions block_until_ready."""
    src = path.read_text()
    tree = ast.parse(src)
    hits = [i + 1 for i, ln in enumerate(src.splitlines())
            if "block_until_ready" in ln and "#" not in ln.split(
                "block_until_ready")[0]]
    spans = [(n.lineno, n.end_lineno, n.name) for n in ast.walk(tree)
             if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
    out = set()
    for h in hits:
        enclosing = [s for s in spans if s[0] <= h <= s[1]]
        assert enclosing, f"{path}:{h}: module-level device sync"
        out.add(min(enclosing, key=lambda s: s[1] - s[0])[2])
    return out


class TestZeroCostGuards:
    def test_telemetry_package_never_touches_jax(self):
        for p in (ROOT / "telemetry").glob("*.py"):
            tree = ast.parse(p.read_text())
            for node in ast.walk(tree):
                if isinstance(node, ast.Import):
                    assert not any(
                        a.name.split(".")[0] == "jax"
                        for a in node.names), f"{p}: imports jax"
                elif isinstance(node, ast.ImportFrom):
                    assert (node.module or "").split(".")[0] != "jax", \
                        f"{p}: imports from jax"
                elif isinstance(node, ast.Attribute):
                    assert node.attr not in (
                        "block_until_ready", "device_get"), \
                        f"{p}: device sync in telemetry layer"

    def test_hot_path_sync_sites_pinned_to_allowlist(self):
        for rel, allowed in SYNC_ALLOWLIST.items():
            p = ROOT / rel
            assert _sync_functions(p) == allowed, rel
            assert "device_get" not in p.read_text(), rel

    def test_disabled_path_is_the_fused_superstep(self):
        src = inspect.getsource(phbase.PHBase._run_superstep)
        # phased (unfused, per-phase-synced) execution is reachable
        # ONLY behind tel.phase_timing; the else branch is the original
        # fused jitted call
        assert "phase_timing" in src
        assert "_superstep_phased" in src
        assert "self._superstep(" in src

    def test_disabled_overhead_under_two_percent(self):
        """Telemetry-off ph_iteration vs a replica of the pre-telemetry
        iteration body: <2% overhead (plus a small absolute slack to
        absorb scheduler noise at sub-ms per-iteration scale), taking
        the min over interleaved trials."""
        import jax

        ph = _ph(None, superstep_eps=1e-4)
        assert not ph._tel.enabled
        ph.Iter0()

        def raw_iteration():
            # byte-for-byte the pre-telemetry ph_iteration body
            ph._ext("pre_solve_loop")
            t0 = time.time()
            ph.state = ph._superstep(
                ph.state, ph.rho, ph.W_on, ph.prox_on,
                ph.lb_eff, ph.ub_eff, ph.superstep_eps, ph.prep,
                ph.batch)
            jax.block_until_ready(ph.state.x)
            b = ph.batch
            it_n = int(ph.state.solve_iters)
            ph._flops += _mfu.pdhg_flops(
                it_n, b.num_scens, b.num_rows, b.num_vars,
                ph.solver.check_every)
            wall = time.time() - t0
            ph._solve_wall += wall
            ph._ext("post_solve_loop")
            ph.conv = float(ph.state.conv)
            return ph.conv

        def timed(fn, n=25):
            t0 = time.perf_counter()
            for _ in range(n):
                fn()
            return time.perf_counter() - t0

        raw_iteration()        # shared warmup
        ph.ph_iteration()
        t_raw, t_ins = [], []
        for _ in range(4):     # interleaved: drift hits both equally
            t_raw.append(timed(raw_iteration))
            t_ins.append(timed(ph.ph_iteration))
        assert min(t_ins) <= min(t_raw) * 1.02 + 0.05, \
            (min(t_ins), min(t_raw))

"""UC at scale (VERDICT r2 item 9 / BASELINE stretch axis): 100 wind
scenarios lowered in one batch, commitment recovered near the TRUE MIP
optimum, plus a valid LP-based outer bound.

Ground truth: scipy/HiGHS branch-and-cut on the same EF gives MIP
optimum 24567.04 and LP relaxation 23077.82 — an inherent 6.1%
integrality gap, so no LP-bound-based certificate can reach 1% here
(the reference's UC runs close such gaps by solving MIP subproblems
inside the Lagrangian spokes).  On the 1-core CPU test budget the
threshold-screening pipeline lands within ~3% of the oracle optimum
(measured 25255 = +2.8%); the batched 1-opt flip search
(uc.one_opt_commitment, smoke-tested separately) is the TPU-scale
refinement stage.

Recovery pipeline (all batched): PH consensus -> threshold-commitment
candidates screened in one stacked launch (speculative parallelism,
SURVEY.md §2.10).
"""

import numpy as np

from mpisppy_tpu.models import uc
from mpisppy_tpu.opt.ph import PH

ORACLE_MIP = 24567.04        # HiGHS branch-and-cut, mip_rel_gap 1e-4
ORACLE_LP = 23077.82


def test_uc_100_scenarios_near_optimum():
    S = 100
    b = uc.build_batch(S, H=6)
    ph = PH({"defaultPHrho": 50.0, "PHIterLimit": 10,
             "convthresh": 0.0, "pdhg_eps": 1e-6,
             "superstep_eps": 1e-4, "lagrangian_eps": 1e-5,
             "pdhg_max_iters": 200000},
            [f"s{i}" for i in range(S)], batch=b)
    ph.Iter0()
    outer = ph.trivial_bound
    for _ in range(10):
        ph.ph_iteration()
    outer = max(outer, ph.lagrangian_bound())

    xbar = np.asarray(ph.state.xbar)[0]
    cands = uc.commitment_candidates(b, xbar)   # default 5 thresholds
    objs, feas = ph.evaluate_candidates(cands)
    ok = np.flatnonzero(feas)
    assert ok.size > 0
    best = int(ok[np.argmin(objs[ok])])
    inner, cfeas = ph.evaluate_xhat(cands[best])
    assert cfeas

    # incumbent within 3.5% of the true MIP optimum (measured +2.8%)
    assert inner <= ORACLE_MIP * 1.035, inner
    assert inner >= ORACLE_MIP * (1 - 1e-6)      # oracle is optimal
    # valid outer bound: below the incumbent, consistent with the LP
    assert outer <= inner
    assert outer <= ORACLE_LP * 1.001
    assert outer >= ORACLE_LP * 0.97


def test_uc_1000_reference_scale_fits():
    """The reference's larger_uc stretch instance — 1000 wind
    scenarios, 21-unit fleet, 24 h horizon (paperruns/larger_uc) — must
    LOWER and FIT: with the shared constraint matrix (uc shared_A,
    ir.ScenarioBatch.shared_A) the constraint tensor is (1, M, N)
    instead of (1000, M, N), a ~1000x memory cut that brings the
    instance under a single chip's HBM."""
    b = uc.build_batch(1000, H=24, fleet_multiplier=7)
    G = 21
    assert b.num_scens == 1000
    assert b.shared_A and b.A.shape[0] == 1
    assert b.num_nonants == 2 * G * 24
    dense_bytes = 1000 * b.num_rows * b.num_vars * b.A.dtype.itemsize
    shared_bytes = b.A.nbytes
    assert shared_bytes * 500 < dense_bytes     # the memory story
    # total batch well under 1 GB (fits HBM with room for solver state)
    total = sum(np.asarray(getattr(b, f)).nbytes
                for f in ("A", "c", "qdiag", "row_lo", "row_hi",
                          "lb", "ub"))
    assert total < 1e9, total


def test_uc_1000_scenarios_slow():
    """1000-wind-scenario tier (VERDICT r3 item 6): PH + Lagrangian +
    threshold-commitment xhat on a 6-unit fleet at S=1000, all batched
    through the shared-A matmul path, to a MEASURED gap.  (The
    21-unit/24 h full instance is the TPU bench entry —
    BENCH_MODEL=uc1000 in bench.py; this tier keeps the per-scenario
    LP small enough for the 1-core CPU test budget.)"""
    S = 1000
    b = uc.build_batch(S, H=6)
    assert b.shared_A
    ph = PH({"defaultPHrho": 50.0, "PHIterLimit": 2,
             "convthresh": 0.0, "pdhg_eps": 1e-5,
             "superstep_eps": 1e-3, "lagrangian_eps": 1e-4,
             "pdhg_max_iters": 2000},
            [f"s{i}" for i in range(S)], batch=b)
    ph.Iter0()
    outer = ph.trivial_bound
    assert np.isfinite(outer)
    for _ in range(2):
        ph.ph_iteration()
    outer = max(outer, ph.lagrangian_bound())

    xbar = np.asarray(ph.state.xbar)[0]
    cands = uc.commitment_candidates(b, xbar)
    objs, feas = ph.evaluate_candidates(cands)
    ok = np.flatnonzero(feas)
    assert ok.size > 0
    best = int(ok[np.argmin(objs[ok])])
    inner, cfeas = ph.evaluate_xhat(cands[best])
    assert cfeas
    # a measured, finite gap with a VALID outer bound (UC carries an
    # inherent integrality gap — see the module docstring — so the
    # assertion is validity + sanity, not 1%)
    assert np.isfinite(inner) and outer <= inner
    gap = (inner - outer) / max(abs(inner), 1e-9)
    assert gap < 0.5, gap


def test_uc_shared_vs_dense_parity():
    """The shared-A matmul path must reproduce the dense per-scenario
    path exactly (same model, same solves)."""
    S = 8
    bs = uc.build_batch(S, H=6)
    bd = uc.build_batch(S, H=6, shared_A=False)
    assert bs.shared_A and not bd.shared_A
    opts = {"defaultPHrho": 50.0, "PHIterLimit": 2, "convthresh": 0.0,
            "pdhg_eps": 1e-6, "pdhg_max_iters": 100000}
    phs = PH(opts, [f"s{i}" for i in range(S)], batch=bs)
    phd = PH(opts, [f"s{i}" for i in range(S)], batch=bd)
    ts, td = phs.Iter0(), phd.Iter0()
    assert abs(ts - td) <= 1e-6 * max(abs(td), 1.0), (ts, td)
    phs.ph_iteration()
    phd.ph_iteration()
    assert np.allclose(np.asarray(phs.state.xbar),
                       np.asarray(phd.state.xbar), atol=1e-5)
    ls, ld = phs.lagrangian_bound(), phd.lagrangian_bound()
    assert abs(ls - ld) <= 1e-5 * max(abs(ld), 1.0), (ls, ld)


def test_uc_one_opt_smoke():
    """Batched 1-opt flip search improves (or retains) a deliberately
    over-committed candidate on a small instance."""
    S = 10
    b = uc.build_batch(S, H=6)
    ph = PH({"defaultPHrho": 50.0, "PHIterLimit": 3,
             "convthresh": 0.0, "pdhg_eps": 1e-6,
             "pdhg_max_iters": 100000},
            [f"s{i}" for i in range(S)], batch=b)
    ph.Iter0()
    ph.ph_iteration()
    all_on = uc.commitment_candidate(
        b, np.ones(b.num_nonants), threshold=0.5)
    v0, f0 = ph.evaluate_xhat(all_on)
    assert f0
    cand, v1 = uc.one_opt_commitment(ph, b, all_on, max_sweeps=2,
                                     flip_slots=np.arange(6))
    assert v1 <= v0 + 1e-6
    # chunked sweeps (reference-scale fleets launch bounded stacks)
    # must satisfy the same contract as one whole-sweep launch: a
    # feasible incumbent no worse than the start, and in the same
    # neighborhood.  NOT bitwise/solver-tolerance equality — chunk
    # layout changes warm-start chains, so a near-tied argmin may
    # legitimately pick a different flip and descend to a different
    # (comparable) local optimum.
    cand2, v2 = uc.one_opt_commitment(ph, b, all_on, max_sweeps=2,
                                      flip_slots=np.arange(6), chunk=2)
    assert v2 <= v0 + 1e-6
    assert abs(v1 - v2) <= 2e-2 * (1 + abs(v1))
    # screen/verify mode (loose-eps capped ranking launches, accurate
    # certify in rank order) obeys the same contract: every acceptance
    # is gated by evaluate_xhat, so a bad screen can cost improvement
    # but never a worse-than-start or unverified incumbent
    cand3, v3 = uc.one_opt_commitment(ph, b, all_on, max_sweeps=2,
                                      flip_slots=np.arange(6),
                                      screen_eps=3e-3, screen_cap=500)
    assert v3 <= v0 + 1e-6
    assert abs(v1 - v3) <= 2e-2 * (1 + abs(v1))


def test_uc_min_up_down_rows():
    """min_up_down=True adds the egret-style uptime/downtime window
    rows: a commitment that starts a big unit for a single hour
    violates its min-up window; honoring the window satisfies it."""
    b = uc.build_batch(4, H=6, min_up_down=True)
    b0 = uc.build_batch(4, H=6)
    assert b.num_rows > b0.num_rows
    A = np.asarray(b.A)[0]
    hi = np.asarray(b.row_hi)[0]
    G, H = 3, 6
    GH = G * H

    def commit(u):
        x = np.zeros(b.num_vars)
        x[:GH] = u.reshape(-1)
        return x

    # big unit (g=0, UT=3) on for exactly one hour (h=2): min-up rows
    # u_3 - u_2 - u_tau <= 0 must be violated for tau in {4, 5}... in
    # 0-based: start at h=2 (u[2]=1, u[1]=0) with u[3]=u[4]=0
    u_bad = np.zeros((G, H))
    u_bad[0, 2] = 1.0
    viol = A @ commit(u_bad) - np.where(np.isfinite(hi), hi, np.inf)
    assert np.max(viol) > 0.5            # some min-up row violated
    # honoring the 3-hour window satisfies every extra row
    u_ok = np.zeros((G, H))
    u_ok[0, 2:5] = 1.0
    viol2 = A @ commit(u_ok) - np.where(np.isfinite(hi), hi, np.inf)
    assert np.max(viol2[b0.num_rows:]) <= 1e-9
    # min-down: shutting the big unit for one hour then restarting
    u_cyc = np.ones((G, H))
    u_cyc[0, 3] = 0.0
    viol3 = A @ commit(u_cyc) - np.where(np.isfinite(hi), hi, np.inf)
    assert np.max(viol3[b0.num_rows:]) > 0.5


def test_uc_commitment_repair_windows():
    """Threshold candidates on a min_up_down batch are repaired to
    window feasibility (runs extended), so the recovery pipeline keeps
    producing feasible incumbents with the windows on."""
    S = 6
    b = uc.build_batch(S, H=6, min_up_down=True)
    # a single-hour spike for the big unit (UT=3) must stretch to 3h;
    # the tables come from the batch's own metadata
    ut = np.asarray(b.model_meta["uc_ut"])
    dt_ = np.asarray(b.model_meta["uc_dt"])
    u = np.zeros(18)
    u[2] = 1.0                       # unit 0, hour 2
    rep = uc.repair_min_up_down(u, ut, dt_, 6)
    assert rep[2:5].sum() == 3.0     # extended to the 3-hour window
    # a 1-hour off-gap inside an on-run gets merged (DT=3)
    u2 = np.ones(18)
    u2[3] = 0.0
    rep2 = uc.repair_min_up_down(u2, ut, dt_, 6)
    assert rep2[:6].sum() == 6.0
    # end-to-end: PH consensus -> candidates stay feasible
    ph = PH({"defaultPHrho": 50.0, "PHIterLimit": 3, "convthresh": 0.0,
             "pdhg_eps": 1e-6, "pdhg_max_iters": 100000},
            [f"s{i}" for i in range(S)], batch=b)
    ph.Iter0()
    for _ in range(3):
        ph.ph_iteration()
    cands = uc.commitment_candidates(b, np.asarray(ph.state.xbar)[0])
    objs, feas = ph.evaluate_candidates(cands)
    assert np.any(feas)
    best = int(np.flatnonzero(feas)[np.argmin(objs[np.asarray(feas)])])
    inner, cfeas = ph.evaluate_xhat(cands[best])
    assert cfeas and np.isfinite(inner)


def test_ef_dual_bound_validity():
    """The shared EF-dual outer bound helper (opt/ef.ef_dual_bound,
    used by bench.py worker_uc and uc_scale_demo) must lower-bound any
    feasible integer commitment's objective, and must beat the iter-0
    trivial bound's slack at small iteration counts (the calibration
    that cut the r4 UC artifact's reported gap from 17.7% to 4.1%)."""
    from mpisppy_tpu.opt.ef import ef_dual_bound

    S = 20
    b = uc.build_batch(S, H=6, fleet_multiplier=2)
    names = [f"s{i}" for i in range(S)]
    bound, secs = ef_dual_bound(b, names)
    assert np.isfinite(bound) and secs >= 0.0
    ph = PH({"defaultPHrho": 50.0, "PHIterLimit": 2, "convthresh": 0.0,
             "pdhg_eps": 1e-5, "pdhg_max_iters": 60000,
             "iter0_infeasibility_ok": True},
            names, batch=b)
    ph.Iter0()
    ph.ph_iteration()
    # valid: below every feasible integer commitment
    cands = uc.commitment_candidates(b, np.asarray(ph.state.xbar)[0])
    objs, feas = ph.evaluate_candidates(cands)
    ok = np.flatnonzero(feas)
    assert ok.size and bound <= float(np.min(objs[ok])) + 1e-6
    # and tighter than (or equal to) the trivial bound
    assert bound >= ph.trivial_bound - 1e-6 * (1 + abs(bound))


def test_uc_spinning_reserve_rows():
    """reserve_factor adds per-hour capacity-adequacy reserve rows
    (egret-style: committed capacity >= net load + r * demand).
    Neither dispatch nor shed appears in the row — a PARTIALLY
    committed fleet whose energy balance is shed-rescuable must still
    be reserve-infeasible (the leak a headroom-form constraint would
    have: shedding frees dispatch headroom one-for-one)."""
    S = 6
    br = uc.build_batch(S, H=6, reserve_factor=0.25)
    b0 = uc.build_batch(S, H=6)
    assert br.shared_A                     # reserve keeps the matmul path
    assert br.num_rows == b0.num_rows + 6  # one row per hour
    opts = {"defaultPHrho": 50.0, "PHIterLimit": 2, "convthresh": 0.0,
            "pdhg_eps": 1e-6, "pdhg_max_iters": 100000}
    phr = PH(opts, [f"s{i}" for i in range(S)], batch=br)
    ph0 = PH(opts, [f"s{i}" for i in range(S)], batch=b0)
    phr.Iter0()
    ph0.Iter0()
    all_on = uc.commitment_candidate(br, np.ones(br.num_nonants),
                                     threshold=0.5)
    vr, fr = phr.evaluate_xhat(all_on)
    assert fr and np.isfinite(vr)
    # peaker-only (Pmax 100 << net load + reserve): energy is
    # shed-rescuable, capacity is not — the partial-commitment case
    # that distinguishes the capacity form from the leaky headroom form
    GH = br.num_nonants // 2
    u = np.zeros(GH)
    u[2 * 6: 3 * 6] = 1.0           # unit 2 = the peaker, all hours
    peaker = uc.commitment_candidate(
        br, np.concatenate([u, np.zeros(GH)]), threshold=0.5)
    v0_p, f0_p = ph0.evaluate_xhat(peaker)
    vr_p, fr_p = phr.evaluate_xhat(peaker)
    assert f0_p                      # shed (penalty 1000/MWh) rescues
    assert not fr_p                  # reserve cannot be shed
    # reserve binds the commitment: all-on objective >= no-reserve one
    v0, _ = ph0.evaluate_xhat(all_on)
    assert vr >= v0 - 1e-6 * (1 + abs(v0))


def test_infeasible_uc_detected_without_iter0_certify():
    """The bench's UC path disables the iter0 certified hard-stop
    (iter0_certify=False + iter0_infeasibility_ok=True) on the
    argument that UC is structurally feasible (load shed) and the
    published bounds are validated independently.  This test closes
    the loophole: a GENUINELY infeasible variant (shed capped to zero,
    demand above fleet capacity) must still be caught by that
    independent validation — iter0 feasible mass collapses and every
    recovered-commitment candidate fails the feasibility screen, so
    the bench reports 'no feasible commitment candidate' instead of a
    gap (bench.py worker_uc)."""
    import dataclasses

    S, H = 8, 4
    b = uc.build_batch(S, H=H)
    G = 3
    ub = np.asarray(b.ub).copy()
    ub[:, 3 * G * H:] = 0.0                  # no load shed allowed
    row_lo = np.asarray(b.row_lo).copy()
    cap = 700.0                              # fleet Pmax sum
    bal = 2 * G * H + np.arange(H)           # balance row indices
    row_lo[:, bal] = 10.0 * cap              # unserviceable demand
    b = dataclasses.replace(b, ub=ub, row_lo=row_lo)

    ph = PH({"defaultPHrho": 50.0, "PHIterLimit": 2, "convthresh": 0.0,
             "pdhg_eps": 1e-6, "pdhg_max_iters": 20000,
             "iter0_certify": False, "iter0_infeasibility_ok": True},
            [f"s{i}" for i in range(S)], batch=b)
    ph.Iter0()
    # the uncertified iter0 path still SEES the infeasibility
    assert ph.iter0_feas_mass < 0.5
    ph.ph_iteration()
    xbar = np.asarray(ph.state.xbar)[0]
    cands = uc.commitment_candidates(b, xbar)
    objs, feas, mass = ph.evaluate_candidates(cands, return_mass=True)
    # every candidate fails the independent feasibility screen — the
    # bench path publishes value -1, never a gap/incumbent
    assert not np.any(feas)
    assert float(np.max(mass)) < 0.5


def test_infeasible_uc_raises_with_message_when_not_ok():
    """Without iter0_infeasibility_ok the uncertified iter0 hard-stops,
    and the message says certification was SKIPPED (ADVICE r4: the old
    message claimed 'after certified re-solve' even when
    iter0_certify=False)."""
    import dataclasses

    import pytest as _pytest

    S, H = 4, 3
    b = uc.build_batch(S, H=H)
    G = 3
    ub = np.asarray(b.ub).copy()
    ub[:, 3 * G * H:] = 0.0
    row_lo = np.asarray(b.row_lo).copy()
    row_lo[:, 2 * G * H + np.arange(H)] = 7000.0
    b = dataclasses.replace(b, ub=ub, row_lo=row_lo)
    ph = PH({"defaultPHrho": 50.0, "PHIterLimit": 1, "convthresh": 0.0,
             "pdhg_eps": 1e-6, "iter0_certify": False},
            [f"s{i}" for i in range(S)], batch=b)
    with _pytest.raises(RuntimeError, match="UNCERTIFIED"):
        ph.Iter0()

"""UC at scale (VERDICT r2 item 9 / BASELINE stretch axis): 100 wind
scenarios lowered in one batch, commitment recovered near the TRUE MIP
optimum, plus a valid LP-based outer bound.

Ground truth: scipy/HiGHS branch-and-cut on the same EF gives MIP
optimum 24567.04 and LP relaxation 23077.82 — an inherent 6.1%
integrality gap, so no LP-bound-based certificate can reach 1% here
(the reference's UC runs close such gaps by solving MIP subproblems
inside the Lagrangian spokes).  On the 1-core CPU test budget the
threshold-screening pipeline lands within ~3% of the oracle optimum
(measured 25255 = +2.8%); the batched 1-opt flip search
(uc.one_opt_commitment, smoke-tested separately) is the TPU-scale
refinement stage.

Recovery pipeline (all batched): PH consensus -> threshold-commitment
candidates screened in one stacked launch (speculative parallelism,
SURVEY.md §2.10).
"""

import numpy as np

from mpisppy_tpu.models import uc
from mpisppy_tpu.opt.ph import PH

ORACLE_MIP = 24567.04        # HiGHS branch-and-cut, mip_rel_gap 1e-4
ORACLE_LP = 23077.82


def test_uc_100_scenarios_near_optimum():
    S = 100
    b = uc.build_batch(S, H=6)
    ph = PH({"defaultPHrho": 50.0, "PHIterLimit": 10,
             "convthresh": 0.0, "pdhg_eps": 1e-6,
             "superstep_eps": 1e-4, "lagrangian_eps": 1e-5,
             "pdhg_max_iters": 200000},
            [f"s{i}" for i in range(S)], batch=b)
    ph.Iter0()
    outer = ph.trivial_bound
    for _ in range(10):
        ph.ph_iteration()
    outer = max(outer, ph.lagrangian_bound())

    xbar = np.asarray(ph.state.xbar)[0]
    cands = uc.commitment_candidates(b, xbar)   # default 5 thresholds
    objs, feas = ph.evaluate_candidates(cands)
    ok = np.flatnonzero(feas)
    assert ok.size > 0
    best = int(ok[np.argmin(objs[ok])])
    inner, cfeas = ph.evaluate_xhat(cands[best])
    assert cfeas

    # incumbent within 3.5% of the true MIP optimum (measured +2.8%)
    assert inner <= ORACLE_MIP * 1.035, inner
    assert inner >= ORACLE_MIP * (1 - 1e-6)      # oracle is optimal
    # valid outer bound: below the incumbent, consistent with the LP
    assert outer <= inner
    assert outer <= ORACLE_LP * 1.001
    assert outer >= ORACLE_LP * 0.97


def test_uc_one_opt_smoke():
    """Batched 1-opt flip search improves (or retains) a deliberately
    over-committed candidate on a small instance."""
    S = 10
    b = uc.build_batch(S, H=6)
    ph = PH({"defaultPHrho": 50.0, "PHIterLimit": 3,
             "convthresh": 0.0, "pdhg_eps": 1e-6,
             "pdhg_max_iters": 100000},
            [f"s{i}" for i in range(S)], batch=b)
    ph.Iter0()
    ph.ph_iteration()
    all_on = uc.commitment_candidate(
        b, np.ones(b.num_nonants), threshold=0.5)
    v0, f0 = ph.evaluate_xhat(all_on)
    assert f0
    cand, v1 = uc.one_opt_commitment(ph, b, all_on, max_sweeps=2,
                                     flip_slots=np.arange(6))
    assert v1 <= v0 + 1e-6

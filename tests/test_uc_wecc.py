"""Reference-data UC (models/uc_wecc.py): parse + lower the ACTUAL
WECC-240 instances the reference ships (reference
examples/uc/3scenarios_r1/ — uc_funcs.py loads the same files through
egret), and validate the lowering against the scipy EF oracle."""

import numpy as np
import pytest

from efcheck import ef_linprog
from mpisppy_tpu.models import uc_wecc
from mpisppy_tpu.opt.ph import PH

DATA = "/root/reference/examples/uc/3scenarios_r1"


def small(**kw):
    kw.setdefault("data_dir", DATA)
    kw.setdefault("num_scens", 3)
    kw.setdefault("hours", 4)
    kw.setdefault("max_units", 20)
    return uc_wecc.build_batch(**kw)


def test_parse_demand_matches_file():
    d = uc_wecc.parse_demand(f"{DATA}/Node1.dat", 48)
    assert d[0] == pytest.approx(384.788341022)
    assert d[11] == pytest.approx(826.741784622)
    assert d[47] == pytest.approx(408.981525761)


def test_parse_root_fleet():
    root = uc_wecc.parse_root(f"{DATA}/RootNode.dat")
    assert root["H"] == 48 and len(root["gens"]) == 85
    t = root["table"]["BRIDGER_20_6333_C"]
    # PowerGeneratedT0 UnitOnT0State Pmin Pmax UT DT RU RD SUr SDr Fuel
    assert t[:6] == pytest.approx(
        [14.05945, 23, 7.40250, 29.61, 12, 12])
    assert root["su_lags"]["BRIDGER_20_6333_C"] == [12, 14, 18]
    assert root["pw_values"]["CANAD_G1_20_5031_G"][0] == \
        pytest.approx(865.15)


def test_lowered_batch_shape_and_sharing():
    b = small()
    assert b.shared_A                      # demand lives in row bounds
    assert b.num_scens == 3
    G, H = int(b.model_meta["G"]), int(b.model_meta["H"])
    assert (G, H) == (20, 4)
    assert b.num_nonants == G * H          # UnitOn only
    assert all(n.startswith("UnitOn[") for n in b.tree.nonant_names)
    # per-scenario demand reached the balance rows
    d1 = uc_wecc.parse_demand(f"{DATA}/Node1.dat", 48)[:4]
    d2 = uc_wecc.parse_demand(f"{DATA}/Node2.dat", 48)[:4]
    assert not np.allclose(d1, d2)


def test_t0_initial_commitment_holds():
    """DIABLO1 (nuclear, UT=48) was only on 1 hour at T0: the scaled
    min-up obligation pins it ON through the truncated horizon."""
    b = uc_wecc.build_batch(data_dir=DATA, num_scens=3, hours=4)
    gens = b.model_meta["gens"].value
    H = int(b.model_meta["H"])
    gi = gens.index("DIABLO1_20_3831_NN")
    lb = np.asarray(b.lb)
    assert np.all(lb[:, gi * H:(gi + 1) * H] == 1.0)
    # a unit off at T0 with a long min-down stays off initially
    root = uc_wecc.parse_root(f"{DATA}/RootNode.dat")
    ub = np.asarray(b.ub)
    for i, g in enumerate(gens):
        t0 = root["table"][g][1]
        if t0 < 0 and round(-t0 / 12) < max(
                1, round(root["table"][g][5] / 12)):
            assert ub[0, i * H] == 0.0
            break


@pytest.fixture(scope="module")
def oracle():
    b = small()
    val, x = ef_linprog(b, n_real=3)
    return b, val, x


def test_ef_lp_is_sane(oracle):
    b, val, x = oracle
    assert np.isfinite(val) and val > 0
    # load mismatch slacks are (near) unused at the optimum — the
    # instance is feasible without paying the 1e6 penalty
    meta = b.model_meta
    G, H = int(meta["G"]), int(meta["H"])
    N = b.num_vars
    shed = x[:, N - 2 * H:]
    assert float(np.abs(shed).max()) < 1e-5


def test_ph_bounds_bracket_oracle(oracle):
    b, val, _ = oracle
    ph = PH({"defaultPHrho": 50.0, "PHIterLimit": 10,
             "convthresh": 0.0, "pdhg_eps": 1e-6,
             "pdhg_max_iters": 60000},
            uc_wecc.scenario_names_creator(3), batch=b)
    ph.Iter0()
    for _ in range(10):
        ph.ph_iteration()
    outer = max(ph.trivial_bound, ph.lagrangian_bound())
    assert outer <= val + 1e-4 * abs(val)
    inner, feas = ph.evaluate_xhat(ph.root_xbar())
    assert feas
    assert inner >= val - 1e-4 * abs(val)
    # LP consensus is near-tight on this instance slice
    assert (inner - outer) / abs(val) < 0.3

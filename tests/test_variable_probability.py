"""variable_probability tests (reference: mpisppy/spbase.py:394
_mpisppy_variable_probability consumed by Compute_Xbar,
phbase.py:71-88; reference test analog tests/test_ef_ph.py
_vb_callback usage)."""

import numpy as np
import pytest

from mpisppy_tpu.models import farmer
from mpisppy_tpu.opt.ph import PH
from mpisppy_tpu.phbase import compute_xbar

OPTS = {"defaultPHrho": 1.0, "PHIterLimit": 5, "convthresh": 0.0,
        "pdhg_eps": 1e-7}
S = 3


def test_var_prob_changes_xbar_weighting():
    names = [f"scen{i}" for i in range(S)]
    b = farmer.build_batch(S)
    K = b.num_nonants
    # all weight on scenario 0 for slot 0; uniform elsewhere
    vp = np.full((S, K), 1.0 / S)
    vp[:, 0] = [1.0, 0.0, 0.0]
    ph = PH(dict(OPTS), names, batch=b, variable_probability=vp)
    ph.Iter0()
    x_na = np.asarray(ph.batch.nonants(ph.state.x))[:S]
    xbar = np.asarray(ph.state.xbar)[0]
    assert xbar[0] == pytest.approx(x_na[0, 0], rel=1e-9)
    assert xbar[1] == pytest.approx(x_na[:, 1].mean(), rel=1e-6)


def test_var_prob_shape_guard():
    names = [f"scen{i}" for i in range(S)]
    b = farmer.build_batch(S)
    with pytest.raises(ValueError):
        PH(dict(OPTS), names, batch=b,
           variable_probability=np.ones((S, 2)))


def test_var_prob_sum_warning(capsys):
    names = [f"scen{i}" for i in range(S)]
    b = farmer.build_batch(S)
    vp = np.full((S, b.num_nonants), 0.5)    # sums to 1.5 per node
    PH(dict(OPTS), names, batch=b, variable_probability=vp)
    out = capsys.readouterr().out
    assert "variable_probability sums deviate" in out


def test_compute_xbar_uniform_equivalence():
    """var_prob == broadcast scenario probs must reproduce the default
    path bit-for-bit (same formula, same weights)."""
    import dataclasses

    b = farmer.build_batch(S)
    x_na = np.random.RandomState(0).rand(S, b.num_nonants)
    xb0, xs0 = compute_xbar(b, x_na)
    vp = np.broadcast_to(np.asarray(b.prob)[:, None],
                         (S, b.num_nonants)).copy()
    b2 = dataclasses.replace(b, var_prob=vp)
    xb1, xs1 = compute_xbar(b2, x_na)
    assert np.allclose(np.asarray(xb0), np.asarray(xb1))
    assert np.allclose(np.asarray(xs0), np.asarray(xs1))

"""In-hub xhat extension family tests (reference:
mpisppy/extensions/xhatclosest.py, xhatxbar.py, xhatbase.py:38-230 —
candidate evaluation inside the hub at miditer, not via spokes)."""

import numpy as np
import pytest

from efcheck import ef_linprog
from mpisppy_tpu.extensions.extension import MultiExtension
from mpisppy_tpu.extensions.xhatter import (
    XhatClosest, XhatLooper, XhatSpecific, XhatXbar,
)
from mpisppy_tpu.models import farmer
from mpisppy_tpu.opt.ph import PH

OPTS = {"defaultPHrho": 1.0, "PHIterLimit": 40, "convthresh": 1e-4,
        "pdhg_eps": 1e-7}


def run_ph(ext_cls, ext_options=None, S=3):
    names = [f"scen{i}" for i in range(S)]
    b = farmer.build_batch(S)
    ph = PH(dict(OPTS), names, batch=b,
            extensions=MultiExtension,
            extension_kwargs={"ext_classes": [ext_cls]})
    # thread per-extension options through the MultiExtension instance
    if ext_options is not None:
        ph.extobject.extdict[ext_cls.__name__].options.update(ext_options)
    ph.ph_main()
    return ph, b


@pytest.mark.parametrize("ext_cls",
                         [XhatClosest, XhatXbar, XhatSpecific,
                          XhatLooper])
def test_inhub_xhat_inner_bound(ext_cls):
    ph, b = run_ph(ext_cls)
    ref, _ = ef_linprog(b, n_real=3)          # -108390
    ib = ph.best_inner_bound
    assert np.isfinite(ib)
    # inner bound is an upper bound on the optimum (within feastol) ...
    assert ib >= ref - 1.0
    # ... and PH convergence makes it tight
    assert ib <= ref + 0.02 * abs(ref)
    assert ph.best_inner_nonants is not None
    assert ph.best_inner_nonants.shape == (b.num_nonants,)


def test_xhat_closest_picks_nearest_scenario():
    ph, _ = run_ph(XhatClosest)
    ext = ph.extobject.extdict["XhatClosest"]
    cands = ext.candidates()
    x_na = np.asarray(ph.batch.nonants(ph.state.x))[:3]
    xbar = np.asarray(ph.state.xbar)[0]
    d = np.sum((x_na - xbar[None, :]) ** 2, axis=1)
    assert np.allclose(cands[0], x_na[np.argmin(d)])


def test_xhat_looper_walks_scenarios():
    """The looper's walk position advances cyclically: successive
    passes cover different scenario solutions (reference
    extensions/xhatlooper.py scen_limit walk)."""
    ph, _ = run_ph(XhatLooper, ext_options={"scen_limit": 2})
    ext = ph.extobject.extdict["XhatLooper"]
    x_na = np.asarray(ph.batch.nonants(ph.state.x))[:3]
    ext._pos = 0
    c1 = ext.candidates()
    c2 = ext.candidates()
    assert c1.shape == (2, x_na.shape[1])
    assert np.allclose(c1, x_na[[0, 1]])
    assert np.allclose(c2, x_na[[2, 0]])   # wrapped
